"""HTTP inference server speaking the KServe/Triton v2 protocol subset.

Reference: triton/ (SURVEY §2.9) — the reference serves its Legion op
graph as a Triton backend; its wire protocol is Triton's v2 inference
API. This server implements the same surface directly (stdlib only):

  GET  /v2/health/live                     -> 200 while the process runs
  GET  /v2/health/ready                    -> 200 only when actually able
                                              to serve (not draining, no
                                              model breaker open)
  GET  /v2/stats                           -> per-model serving stats
                                              (queue depth, admission
                                              counters, latency,
                                              generation tokens/s +
                                              cache occupancy, and the
                                              self-healing counters:
                                              recoveries, replayed_tokens,
                                              quarantined, watchdog_trips)
  GET  /metrics                            -> Prometheus text exposition:
                                              every per-model counter,
                                              gauge, latency window and
                                              the TTFT/TPOT/queue-time
                                              histograms (obs/prom.py)
  GET  /v2/debug/traces[?id=N&model=M&n=K] -> recent per-request traces
                                              (queue time, TTFT, TPOT,
                                              event waterfall)
  GET  /v2/debug/timeline[?model=M]        -> engine flight recorder as
                                              chrome://tracing JSON
                                              (+ recent incident dumps)
  GET  /v2/debug/cache[?model=M]           -> KV-cache block telemetry:
                                              per-request residency,
                                              fragmentation, watermarks,
                                              pressure, admission waits
  GET  /v2/debug/programs[?model=M]        -> jit program registry:
                                              traced signatures, compile
                                              times, retrace blame
  GET  /v2/debug/predictions[?model=M]     -> cost-model truth: per-step
                                              (predicted, measured)
                                              pairs, relative-error
                                              distributions, and
                                              calibration-drift alarms
                                              with blame
  GET  /v2/debug/anatomy[?model=M&capture=K] -> step-anatomy profiler:
                                              per-kind phase breakdown,
                                              device-bubble ratio,
                                              host/device-bound
                                              classification, the
                                              overlap-headroom
                                              projection, and (with
                                              capture=K) arming a
                                              K-step two-lane capture
                                              whose chrome://tracing
                                              timeline rides the next
                                              scrape — per replica on
                                              fleets, like the other
                                              debug endpoints
  GET  /v2/slo                             -> per-model SLO objectives
                                              with fast/slow burn rates
  GET  /v2/overload[?model=M]              -> overload control state per
                                              generation unit: adaptive
                                              concurrency limiter,
                                              degrade-ladder level +
                                              history, pressure, and the
                                              per-reason / per-priority
                                              rejection split
  GET  /v2/fleet                           -> fleet serving tier state:
                                              replica lifecycle states,
                                              residency, router score
                                              inputs + decisions, and
                                              recent failover / drain /
                                              replace events
  GET  /v2/fleet/autoscale                 -> want-more / want-fewer
                                              replica signal derived
                                              from sustained limiter
                                              saturation across the
                                              fleet (ROADMAP item 3's
                                              autoscaling remainder)
  GET  /v2/models/{name}                   -> model metadata
  GET  /v2/models/{name}/ready             -> per-model readiness
  POST /v2/models/{name}/infer             -> run inference
  POST /v2/models/{name}/generate          -> autoregressive generation
                                              (GenerationModel); JSON
                                              response, or SSE token
                                              stream with "stream": true

Failed generation requests embed their RequestTrace (and, for
quarantines/restarts, the flight-recorder snapshot riding the error) in
the error response body — the client holds the postmortem without a
second round trip.

Infer request JSON: {"inputs": [{"name", "shape", "datatype", "data"}]},
response mirrors it — the v2 tensor format with row-major flat data. A
per-request deadline may ride along as ``{"parameters": {"timeout_ms":
N}}`` or the ``X-Request-Timeout-Ms`` header; expired requests are
rejected with 504 before they reach the device.

Status mapping for resilience rejections: queue full / circuit open /
draining -> 503, expired deadline -> 504, backend death -> 500.
Overload rejections (serving/overload.py) are 503s that additionally
carry a ``Retry-After`` header and a structured body (``reason`` =
queue_full / limiter / infeasible / degraded, ``priority``,
``retry_after_s``). A request's priority class rides the generate
body's ``"priority"`` field, the infer request's
``{"parameters": {"priority": ...}}``, or the ``X-Request-Priority``
header.
"""
from __future__ import annotations

import json
import threading
from concurrent.futures import TimeoutError as _FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

import math

from ..obs import (
    GLOBAL_LEDGER,
    GLOBAL_PROGRAMS,
    JourneyIndex,
    JourneyRecorder,
    journey_to_chrome_trace,
    journey_to_otlp,
    parse_traceparent,
    render_prometheus,
)
from ..runtime import faults
from .batcher import DynamicBatcher, make_batcher
from .model import InferenceModel
from .resilience import ResilienceError, http_status, retry_after_s


def _reject_payload(e: ResilienceError) -> dict:
    """Error body for a typed rejection; OverloadedError additionally
    carries the structured reason / priority / retry_after_s fields."""
    payload = {"error": str(e), "type": type(e).__name__}
    for field in ("reason", "priority", "retry_after_s", "predicted_ttft_s"):
        v = getattr(e, field, None)
        if v is not None:
            payload[field] = v
    return payload


def _reject_headers(e: ResilienceError) -> "dict | None":
    """``Retry-After`` for overload rejections (whole seconds, >= 1,
    per RFC 9110)."""
    ra = retry_after_s(e)
    if ra is None:
        return None
    return {"Retry-After": str(max(1, int(math.ceil(ra))))}

_V2_DTYPES = {
    "FP32": np.float32, "FP64": np.float64, "FP16": np.float16,
    "BF16": np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.float32,
    "INT32": np.int32, "INT64": np.int64, "BOOL": np.bool_,
}
_NP_TO_V2 = {
    "float32": "FP32", "float64": "FP64", "float16": "FP16",
    "bfloat16": "BF16", "int32": "INT32", "int64": "INT64", "bool": "BOOL",
}


class InferenceServer:
    """Serves one or more InferenceModels over HTTP with dynamic batching.

    With a ModelRepository attached, the Triton v2 repository lifecycle
    endpoints are live (reference: Triton's model-repository management
    above triton/src/model.cc):

      POST /v2/repository/index                  -> available + loaded state
      POST /v2/repository/models/{name}/load     -> load from disk
      POST /v2/repository/models/{name}/unload   -> stop serving + drop
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_delay_s: float = 0.005,
        repository=None,
        max_queue: int = 256,
        batcher_kwargs: Optional[dict] = None,
    ):
        self.host = host
        self.port = port
        self.models: Dict[str, InferenceModel] = {}
        self.batchers: Dict[str, DynamicBatcher] = {}
        self.generators: Dict[str, "GenerationModel"] = {}  # noqa: F821
        self.max_delay_s = max_delay_s
        self.repository = repository
        # per-model batcher construction knobs (breaker/retry/clock are
        # injectable here so chaos tests run on virtual time); pass
        # breaker/retry as zero-arg FACTORIES on multi-model servers so
        # each model gets its own instance (see make_batcher)
        self._batcher_kwargs = dict(batcher_kwargs or {})
        self._batcher_kwargs.setdefault("max_delay_s", max_delay_s)
        self._batcher_kwargs.setdefault("max_queue", max_queue)
        self._draining = False
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # fleet-wide journeys (ISSUE 20): the HTTP ingress span lane.
        # Contexts are minted here (or joined from an inbound W3C
        # traceparent) only for generators whose journeys are on, so a
        # journeys-off deployment stays inert.
        self.journeys = JourneyRecorder(lane="http")

    def register(self, model: InferenceModel):
        self.models[model.name] = model
        b = make_batcher(model, self._batcher_kwargs)
        self.batchers[model.name] = b
        if self._httpd is not None:
            b.start()

    def unregister(self, name: str) -> bool:
        b = self.batchers.pop(name, None)
        if b is not None:
            b.stop()
        return self.models.pop(name, None) is not None

    def register_generation(self, model: "GenerationModel"):  # noqa: F821
        """Serve a GenerationModel (serving/generation.py) next to the
        batched InferenceModels."""
        self.generators[model.name] = model
        if self._httpd is not None:
            model.start()

    def unregister_generation(self, name: str) -> bool:
        g = self.generators.pop(name, None)
        if g is not None:
            g.stop()
        return g is not None

    # ------------------------------------------------------------- health
    def live(self) -> bool:
        return True

    def ready(self) -> bool:
        """Real readiness, not a constant: serving, not draining, and no
        model's circuit breaker holding traffic."""
        if self._httpd is None or self._draining:
            return False
        # snapshot: repository load/unload mutates the dict concurrently
        return all(b.breaker.ready() for b in list(self.batchers.values())) and all(
            g.breaker.ready() for g in list(self.generators.values())
        )

    def model_ready(self, name: str) -> bool:
        g = self.generators.get(name)
        if g is not None:
            return g.ready()
        b = self.batchers.get(name)
        return b is not None and b.ready()

    def readiness(self) -> Dict:
        """Readiness + rationale: per model, the three health inputs —
        circuit breaker state, watchdog/recovery evidence, and SLO burn.
        The boolean keeps the PR 1 semantics (breaker-driven); the
        rationale explains it, and a breaching SLO shows up as degraded
        without flipping readiness."""
        models: Dict[str, Dict] = {}
        for name, b in list(self.batchers.items()):
            models[name] = {"ready": b.ready(), "breaker": b.breaker.state}
        for name, g in list(self.generators.items()):
            models[name] = g.readiness_rationale()
        return {
            "ready": self.ready(),
            "draining": self._draining,
            "models": models,
        }

    def stats(self) -> Dict:
        """Aggregate /v2/stats payload: batcher counters + generation
        engine throughput/occupancy, one entry per model."""
        return {
            "models": {n: b.stats.snapshot() for n, b in list(self.batchers.items())},
            "generation": {
                n: g.stats.snapshot() for n, g in list(self.generators.items())
            },
        }

    # ------------------------------------------------------ observability
    def _all_stats(self) -> Dict:
        """model name -> ServingStats across both serving paths (the
        /metrics scrape set). Snapshots the dicts: repository load/
        unload mutates them concurrently. Fleet generators contribute
        one entry PER REPLICA under a ``(model, replica)`` key, so every
        serving family renders with a ``replica`` label and Prometheus
        aggregates across it."""
        out = {n: b.stats for n, b in list(self.batchers.items())}
        for n, g in list(self.generators.items()):
            reps = getattr(g, "replicas", None)
            if reps is None:
                out[n] = g.stats
            else:
                for r in list(reps):
                    out[(n, r.id)] = r.model.stats
        return out

    def _fleets(self) -> Dict:
        """model name -> fleet lifecycle metrics (Fleet generators
        only): replica states, failover/migration counters, router
        decisions — the ``fleets=`` input to render_prometheus."""
        return {
            n: g.prom_fleet()
            for n, g in list(self.generators.items())
            if hasattr(g, "prom_fleet")
        }

    def _generation_units(self):
        """(label, GenerationModel) pairs across all generators; a
        fleet contributes one unit per replica, labeled
        ``name/replica`` — the shared iteration for the per-engine
        debug endpoints (traces, timeline, cache, programs,
        predictions, slo)."""
        for name, g in sorted(self.generators.items()):
            reps = getattr(g, "replicas", None)
            if reps is None:
                yield name, g
            else:
                for r in list(reps):
                    yield f"{name}/{r.id}", r.model

    @staticmethod
    def _unit_matches(label: str, model: Optional[str]) -> bool:
        """``?model=`` filter: the plain name matches itself, a fleet
        name matches all its replicas, and ``name/rN`` matches one."""
        return (
            model is None
            or label == model
            or label.split("/", 1)[0] == model
        )

    def _all_anatomy(self) -> Dict:
        """model/(model, replica) -> StepAnatomy.prom_snapshot() across
        the generation path — the ``anatomy=`` input to
        render_prometheus, keyed like _all_stats so the
        ``step_phase_seconds`` family carries the same model/replica
        labels as every other serving family."""
        out: Dict = {}
        for n, g in list(self.generators.items()):
            reps = getattr(g, "replicas", None)
            if reps is None:
                an = getattr(g, "anatomy", None)
                if an is not None and an.enabled:
                    out[n] = an.prom_snapshot()
            else:
                for r in list(reps):
                    an = getattr(r.model, "anatomy", None)
                    if an is not None and an.enabled:
                        out[(n, r.id)] = an.prom_snapshot()
        return out

    def metrics_text(self) -> str:
        return render_prometheus(
            self._all_stats(),
            fault_sites=faults.site_counters(),
            ledger=GLOBAL_LEDGER,
            fleets=self._fleets(),
            anatomy=self._all_anatomy(),
        )

    def debug_traces(
        self,
        request_id: Optional[int] = None,
        model: Optional[str] = None,
        n: int = 32,
    ) -> Dict:
        """Recent finished request traces, most recent first, across the
        generation schedulers and the dynamic batchers."""
        rings = []
        for label, unit in self._generation_units():
            if self._unit_matches(label, model):
                rings.append((label, unit.trace_ring))
        for name, b in list(self.batchers.items()):
            if model is None or name == model:
                rings.append((name, b.trace_ring))
        traces = []
        for name, ring in rings:
            if request_id is not None:
                tr = ring.get(request_id)
                if tr is not None:
                    d = tr.to_dict()
                    d["model"] = d["model"] or name
                    traces.append(d)
                continue
            for tr in ring.recent(n):
                d = tr.to_dict()
                d["model"] = d["model"] or name
                traces.append(d)
        traces.sort(key=lambda d: d.get("t_finish") or 0, reverse=True)
        return {"traces": traces[:n]}

    def debug_timeline(self, model: Optional[str] = None) -> Dict:
        """Flight-recorder dump as chrome://tracing JSON (one pid per
        generation model), plus the recent incident snapshots under a
        non-standard ``incidents`` key chrome ignores."""
        events, incidents = [], []
        for pid, (label, unit) in enumerate(self._generation_units(), start=1):
            if not self._unit_matches(label, model):
                continue
            trace = unit.flight.to_chrome_trace(pid=pid, name=label)
            events.extend(trace["traceEvents"])
            incidents.extend(
                {**inc, "model": label}
                for inc in unit.flight.incident_snapshots()
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "incidents": incidents,
        }

    def debug_cache(self, model: Optional[str] = None) -> Dict:
        """KV-cache block telemetry per generation model: residency
        table, fragmentation, watermarks, pressure, admission waits."""
        return {
            "models": {
                label: unit.cache_report()
                for label, unit in self._generation_units()
                if self._unit_matches(label, model)
            }
        }

    def debug_programs(self, model: Optional[str] = None) -> Dict:
        """Jit program registries: per generation model (prefill
        buckets / decode / verify) plus the process-wide executor
        registry, each with signatures, compile times, and any retrace
        blame."""
        out: Dict = {
            "models": {
                label: {
                    "programs": unit.programs.snapshot(),
                    "retraces": unit.programs.recent_retraces(),
                }
                for label, unit in self._generation_units()
                if self._unit_matches(label, model)
            }
        }
        if model is None:
            out["executor"] = {
                "programs": GLOBAL_PROGRAMS.snapshot(),
                "retraces": GLOBAL_PROGRAMS.recent_retraces(),
            }
        return out

    def debug_predictions(self, model: Optional[str] = None) -> Dict:
        """Cost-model truth: per generation model, the engine ledger's
        (predicted, measured) pairs, relative-error distributions, and
        drift alarms; plus the process-wide ledger (search cost model,
        calibration measurements, executor train programs)."""
        out: Dict = {
            "models": {
                label: unit.ledger.report()
                for label, unit in self._generation_units()
                if self._unit_matches(label, model)
            }
        }
        if model is None:
            out["global"] = GLOBAL_LEDGER.report()
        return out

    def debug_anatomy(
        self, model: Optional[str] = None, capture: Optional[int] = None
    ) -> Dict:
        """Step-anatomy report per generation unit (one entry per fleet
        replica): phase breakdown, device-bubble ratio, classification,
        overlap-headroom projection, capture state, and the two-lane
        chrome://tracing timeline of any captured steps. ``capture=K``
        arms a K-step capture on every matching unit first (the
        timeline fills as the engines step; scrape again to read it)."""
        out: Dict = {"models": {}}
        for label, unit in self._generation_units():
            if not self._unit_matches(label, model):
                continue
            an = unit.anatomy
            armed = an.arm_capture(capture) if capture else None
            payload = {"report": an.report(), "trace": an.to_chrome_trace(name=label)}
            if armed is not None:
                payload["armed"] = armed
            out["models"][label] = payload
        return out

    def slo_report(self) -> Dict:
        """Per-model SLO objectives with multi-window burn rates (one
        entry per fleet replica)."""
        return {
            "models": {
                label: unit.slo.snapshot()
                for label, unit in self._generation_units()
            }
        }

    def overload_report(self, model: Optional[str] = None) -> Dict:
        """GET /v2/overload: per generation unit (one entry per fleet
        replica), the overload controller's state — limiter, ladder
        level + history, pressure, and the per-reason / per-priority
        rejection split."""
        out: Dict = {"models": {}}
        for label, unit in self._generation_units():
            if not self._unit_matches(label, model):
                continue
            try:
                out["models"][label] = unit.overload.report()
            except AttributeError:
                continue  # non-generation unit
        return out

    def fleet_report(self) -> Dict:
        """GET /v2/fleet: per-fleet replica states, residency, router
        score inputs + decisions, and recent lifecycle events."""
        return {
            "models": {
                name: g.report()
                for name, g in sorted(self.generators.items())
                if hasattr(g, "replicas")
            }
        }

    def autoscale_report(self) -> Dict:
        """GET /v2/fleet/autoscale: per-fleet want-more/want-fewer
        replica signal derived from sustained limiter state (the
        ROADMAP item 3 autoscaling remainder)."""
        return {
            "models": {
                name: g.autoscale_report()
                for name, g in sorted(self.generators.items())
                if hasattr(g, "autoscale_report")
            }
        }

    def durable_report(self) -> Dict:
        """GET /v2/durable: per-model WAL/journal/warm-restart state —
        commit watermark, counters, degraded streams, resume-index
        sizes (durable serving, ISSUE 19)."""
        out: Dict = {"models": {}}
        for name, g in sorted(self.generators.items()):
            dur = getattr(g, "durable", None)
            if dur is not None:
                out["models"][name] = dur.report()
            elif hasattr(g, "durable_report"):  # fleet: per-replica view
                rep = g.durable_report()
                if rep is not None:
                    out["models"][name] = rep
        return out

    def durable_lookup(self, durable_id: str):
        """Find the generator + resume state owning a durable stream
        id, across plain models and fleets. Returns ``(model_name,
        ("live", Request) | ("done", dict))`` or None."""
        for name, g in sorted(self.generators.items()):
            dur = getattr(g, "durable", None)
            if dur is not None:
                hit = dur.lookup(durable_id)
                if hit is not None:
                    return name, hit
            elif hasattr(g, "durable_lookup"):
                hit = g.durable_lookup(durable_id)
                if hit is not None:
                    return name, hit
        return None

    # ------------------------------------------------------------ journeys
    def journey_index(self) -> JourneyIndex:
        """A fresh fleet-wide stitcher over the CURRENT topology: the
        HTTP ingress lane, every generator's router + replica lanes
        (retiring replicas included), and every on-disk spool — built
        per query so replica churn can never leave the index stale."""
        idx = JourneyIndex().add(self.journeys)
        for g in list(self.generators.values()):
            recs = getattr(g, "journey_recorders", None)
            if recs is not None:
                for rec in recs():
                    idx.add(rec)
            spools = getattr(g, "journey_spools", None)
            if spools is not None:
                for spool in spools():
                    idx.add_spool(spool)
        return idx

    def debug_journey(self, journey_id: str) -> Optional[Dict]:
        """GET /v2/debug/journey/{id}: the stitched causal timeline —
        spans in parent-chain order with the connectivity verdict, plus
        chrome://tracing (one lane per replica/pool) and OTLP-shaped
        renderings of the same journey."""
        journey = self.journey_index().get(journey_id)
        if journey is None:
            return None
        return {
            "journey": journey,
            "chrome_trace": journey_to_chrome_trace(journey),
            "otlp": journey_to_otlp(journey),
        }

    def debug_journeys(self, slow: Optional[str] = None, n: int = 32) -> Dict:
        """GET /v2/debug/journey[?slow=p99]: known journey ids (newest
        first); with ``slow=``, only the ids the latency windows
        retained as worst-decile exemplars — a bad percentile links
        straight to a stitchable journey."""
        if slow:
            rows = self.debug_slow()
            ids: list = []
            for windows in rows["models"].values():
                for entries in windows.values():
                    for e in entries:
                        if e["journey_id"] not in ids:
                            ids.append(e["journey_id"])
            return {"journeys": ids[:n], "slow": rows["models"]}
        return {"journeys": self.journey_index().journey_ids()[:n]}

    def debug_slow(self, model: Optional[str] = None) -> Dict:
        """GET /v2/debug/slow: per generation unit, each latency
        window's worst-decile samples with their journey ids — the
        tail-latency exemplar table."""
        out: Dict = {"models": {}}
        for label, unit in self._generation_units():
            if not self._unit_matches(label, model):
                continue
            try:
                rows = unit.stats.slow_exemplars()
            except AttributeError:
                continue
            if rows:
                out["models"][label] = rows
        return out

    # ------------------------------------------------------------ control
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, payload: dict, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _text(self, code: int, text: str, content_type: str):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _repository(self, parts):
                repo = server.repository
                if repo is None:
                    return self._json(400, {"error": "no model repository configured"})
                if len(parts) == 4 and parts[3] == "index":
                    return self._json(200, [
                        {
                            "name": n,
                            "state": "READY" if n in server.models else "UNAVAILABLE",
                        }
                        for n in sorted(set(repo.available()) | set(server.models))
                    ])
                if len(parts) == 6 and parts[3] == "models" and parts[5] in ("load", "unload"):
                    name = parts[4]
                    if parts[5] == "load":
                        try:
                            server.register(repo.load(name))
                        except KeyError as e:
                            return self._json(404, {"error": str(e)})
                        except Exception as e:
                            return self._json(500, {"error": str(e)})
                        return self._json(200, {"name": name, "state": "READY"})
                    if not server.unregister(name):
                        return self._json(404, {"error": f"model {name} not loaded"})
                    return self._json(200, {"name": name, "state": "UNAVAILABLE"})
                return self._json(404, {"error": "not found"})

            def do_GET(self):
                url = urlparse(self.path)
                path, query = url.path, parse_qs(url.query)

                def qint(key):
                    try:
                        return int(query[key][0])
                    except (KeyError, IndexError, ValueError):
                        return None

                if path == "/v2/health/live":
                    return self._json(200, {"live": server.live()})
                if path == "/v2/health/ready":
                    payload = server.readiness()
                    return self._json(200 if payload["ready"] else 503, payload)
                if path == "/v2/stats":
                    return self._json(200, server.stats())
                if path == "/metrics":
                    try:
                        text = server.metrics_text()
                    except Exception as e:  # a scrape must fail loudly, not 200-empty
                        return self._json(500, {"error": str(e)})
                    return self._text(200, text, "text/plain; version=0.0.4; charset=utf-8")
                if path == "/v2/debug/traces":
                    return self._json(200, server.debug_traces(
                        request_id=qint("id"),
                        model=(query.get("model") or [None])[0],
                        n=qint("n") or 32,
                    ))
                if path == "/v2/debug/timeline":
                    return self._json(200, server.debug_timeline(
                        model=(query.get("model") or [None])[0]
                    ))
                if path == "/v2/debug/cache":
                    return self._json(200, server.debug_cache(
                        model=(query.get("model") or [None])[0]
                    ))
                if path == "/v2/debug/programs":
                    return self._json(200, server.debug_programs(
                        model=(query.get("model") or [None])[0]
                    ))
                if path == "/v2/debug/predictions":
                    return self._json(200, server.debug_predictions(
                        model=(query.get("model") or [None])[0]
                    ))
                if path == "/v2/debug/anatomy":
                    return self._json(200, server.debug_anatomy(
                        model=(query.get("model") or [None])[0],
                        capture=qint("capture"),
                    ))
                if path.startswith("/v2/debug/journey/"):
                    jid = path[len("/v2/debug/journey/"):]
                    payload = server.debug_journey(jid)
                    if payload is None:
                        return self._json(
                            404, {"error": f"unknown journey {jid}"}
                        )
                    return self._json(200, payload)
                if path == "/v2/debug/journey":
                    return self._json(200, server.debug_journeys(
                        slow=(query.get("slow") or [None])[0],
                        n=qint("n") or 32,
                    ))
                if path == "/v2/debug/slow":
                    return self._json(200, server.debug_slow(
                        model=(query.get("model") or [None])[0]
                    ))
                if path == "/v2/slo":
                    return self._json(200, server.slo_report())
                if path == "/v2/overload":
                    return self._json(200, server.overload_report(
                        model=(query.get("model") or [None])[0]
                    ))
                if path == "/v2/durable":
                    return self._json(200, server.durable_report())
                if path.startswith("/v2/generate/resume/"):
                    return self._resume(
                        path[len("/v2/generate/resume/"):], query
                    )
                if path == "/v2/fleet":
                    return self._json(200, server.fleet_report())
                if path == "/v2/fleet/autoscale":
                    return self._json(200, server.autoscale_report())
                if path == "/v2/models":
                    return self._json(
                        200,
                        {"models": sorted(set(server.models) | set(server.generators))},
                    )
                if path.startswith("/v2/models/"):
                    parts = path.split("/")
                    name = parts[3]
                    m = server.models.get(name) or server.generators.get(name)
                    if m is None:
                        return self._json(404, {"error": f"unknown model {name}"})
                    if len(parts) == 5 and parts[4] == "ready":
                        ok = server.model_ready(name)
                        payload = {"name": name, "ready": ok}
                        g = server.generators.get(name)
                        if g is not None:
                            payload["rationale"] = g.readiness_rationale()
                        return self._json(200 if ok else 503, payload)
                    return self._json(200, m.metadata())
                return self._json(404, {"error": "not found"})

            def _generate(self, name: str):
                """POST /v2/models/{name}/generate — body: {"prompt":
                [ids], "max_new_tokens", "temperature", "top_k",
                "eos_id", "seed", "stream", "parameters": {"timeout_ms"},
                "speculation": {"enabled", "k", "method", "max_ngram",
                "min_ngram", "adaptive"}, "response_format": {"type":
                "json_schema"|"regex", ...}}. The speculation block
                turns on (exact) speculative decoding for this request;
                response_format constrains the stream to a grammar (a
                malformed grammar is THIS request's 400, never the
                batch's).
                Non-streaming: one JSON object. "stream": true: SSE — one
                ``data:`` event per token, then a final done event."""
                gen = server.generators.get(name)
                if gen is None:
                    return self._json(404, {"error": f"unknown generation model {name}"})
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    prompt = [int(t) for t in req["prompt"]]
                    sampling = gen.sampling_from(req)
                    stream = bool(req.get("stream", False))
                    timeout_ms = (req.get("parameters") or {}).get(
                        "timeout_ms", self.headers.get("X-Request-Timeout-Ms")
                    )
                    deadline_s = None if timeout_ms is None else float(timeout_ms) / 1000.0
                    speculation = gen.speculation_from(req)
                    # priority class: body field first, then the
                    # X-Request-Priority header (absent -> standard)
                    priority = req.get(
                        "priority", self.headers.get("X-Request-Priority")
                    )
                    response_format = gen.response_format_from(req)
                    # journey ingress: mint (or join the client's W3C
                    # traceparent) only when the target unit records
                    # journeys — journeys-off deployments stay inert
                    journey = None
                    if getattr(gen, "journeys", None) is not None:
                        journey = server.journeys.mint(
                            parent=parse_traceparent(
                                self.headers.get("traceparent")
                            )
                        )
                        journey.hop(
                            "ingress", transport="http", model=name,
                            stream=stream, prompt_len=len(prompt),
                        )
                    handle = gen.submit(
                        prompt, sampling, deadline_s=deadline_s,
                        speculation=speculation, transport="http",
                        priority=priority, response_format=response_format,
                        journey=journey,
                    )
                except ResilienceError as e:
                    return self._json(
                        http_status(e), _reject_payload(e),
                        headers=_reject_headers(e),
                    )
                except Exception as e:
                    return self._json(400, {"error": str(e)})

                def error_payload(e):
                    """Failed generations ship their postmortem: the
                    request's trace, and (quarantine/engine-failure) the
                    flight-recorder snapshot riding the exception."""
                    payload = _reject_payload(e)
                    tr = handle.trace_dict()
                    if tr:
                        payload["trace"] = tr
                    flight = getattr(e, "flight_snapshot", None)
                    if flight:
                        payload["flight"] = flight
                    return payload

                wait = deadline_s if deadline_s is not None else 300.0
                if not stream:
                    try:
                        tokens = handle.result(timeout=wait)
                    except ResilienceError as e:
                        return self._json(
                            http_status(e), error_payload(e),
                            headers=_reject_headers(e),
                        )
                    except (TimeoutError, _FuturesTimeout):
                        handle.cancel()
                        return self._json(504, {"error": "generation timed out"})
                    except Exception as e:
                        return self._json(500, error_payload(e))
                    body = {"model_name": name, "tokens": tokens,
                            "num_generated": len(tokens)}
                    if journey is not None:
                        body["journey_id"] = journey.journey_id
                        return self._json(
                            200, body,
                            headers={"traceparent": journey.traceparent()},
                        )
                    return self._json(200, body)
                # SSE stream: status/headers are already committed once the
                # first token flushes, so mid-stream failures surface as an
                # error event, not a status code. With durability
                # attached, X-Durable-Id names the stream for
                # GET /v2/generate/resume/{id}, and each token event
                # carries a monotonic SSE id (= token index) so a
                # reconnecting client's Last-Event-ID pins exactly
                # where replay resumes.
                durable_id = handle._request.durable_id
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                if durable_id is not None:
                    self.send_header("X-Durable-Id", durable_id)
                if journey is not None:
                    self.send_header("traceparent", journey.traceparent())
                self.end_headers()

                def event(payload: dict, eid=None):
                    if eid is not None:
                        self.wfile.write(f"id: {eid}\n".encode())
                    self.wfile.write(f"data: {json.dumps(payload)}\n\n".encode())
                    self.wfile.flush()

                count = 0
                try:
                    for tok in handle.tokens(timeout=wait):
                        event({"token": int(tok), "index": count}, eid=count)
                        count += 1
                    done = {"done": True, "tokens": handle.result(timeout=wait)}
                    if durable_id is not None:
                        done["durable_id"] = durable_id
                    if journey is not None:
                        done["journey_id"] = journey.journey_id
                    event(done)
                except Exception as e:
                    handle.cancel()
                    try:
                        event({**error_payload(e), "done": True})
                    except OSError:
                        pass  # client went away mid-stream

            def _resume(self, durable_id: str, query):
                """GET /v2/generate/resume/{durable_id} — SSE replay +
                re-attach (durable serving, ISSUE 19). Journaled tokens
                replay from the resume index (event ids pick up the
                original stream's numbering); if the stream is still
                live the response then follows it to completion
                byte-identically. ``Last-Event-ID`` (header, SSE
                reconnect convention) or ``?last_event_id=`` skips
                events the client already holds."""
                last = self.headers.get("Last-Event-ID")
                if last is None:
                    last = (query.get("last_event_id") or [None])[0]
                try:
                    sent = int(last) + 1 if last is not None else 0
                except ValueError:
                    return self._json(400, {"error": f"bad Last-Event-ID {last!r}"})
                found = server.durable_lookup(durable_id)
                if found is None:
                    return self._json(
                        404, {"error": f"unknown durable stream {durable_id}"}
                    )
                name, (state, obj) = found
                # journey: a resumed live stream keeps its identity — the
                # WAL admission snapshot restored the pre-crash journey id,
                # so the sse_resume hop parent-links into the same trace.
                journey_id = None
                if state == "live" and obj.journey.journey_id is not None:
                    journey_id = obj.journey.journey_id
                    obj.journey.hop(
                        "sse_resume", durable_id=durable_id,
                        last_event_id=last, from_index=sent,
                    )
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("X-Durable-Id", durable_id)
                if journey_id is not None:
                    self.send_header(
                        "traceparent", obj.journey.traceparent()
                    )
                self.end_headers()

                def event(payload: dict, eid=None):
                    if eid is not None:
                        self.wfile.write(f"id: {eid}\n".encode())
                    self.wfile.write(f"data: {json.dumps(payload)}\n\n".encode())
                    self.wfile.flush()

                def drain(tokens):
                    nonlocal sent
                    while sent < len(tokens):
                        event(
                            {"token": int(tokens[sent]), "index": sent,
                             "model_name": name},
                            eid=sent,
                        )
                        sent += 1

                try:
                    if state == "done":
                        tokens = obj["tokens"]
                        drain(tokens)
                        event({"done": True, "tokens": list(tokens),
                               "outcome": obj["outcome"],
                               "durable_id": durable_id})
                        return
                    # live stream: poll the request's generated list —
                    # the handle's token queue belongs to (and was
                    # consumed by) the original connection. List
                    # appends are atomic under the GIL; we only ever
                    # read a prefix the scheduler already extended.
                    req = obj
                    handle = req.handle
                    # ~300 s ceiling without a wall-clock read: each
                    # poll blocks up to 50 ms on the settle future
                    for _ in range(6000):
                        drain(req.generated)
                        if handle.done():
                            break
                        try:
                            handle.future.exception(timeout=0.05)
                        except _FuturesTimeout:
                            pass
                        except Exception:
                            break  # settled (cancelled counts); drain below
                    drain(req.generated)
                    if not handle.done():
                        event({"done": True, "error": "resume timed out",
                               "durable_id": durable_id})
                        return
                    try:
                        tokens = handle.result(timeout=0)
                        event({"done": True, "tokens": tokens,
                               "outcome": "completed",
                               "durable_id": durable_id})
                    except Exception as e:
                        event({**_reject_payload(e), "done": True,
                               "outcome": type(e).__name__,
                               "durable_id": durable_id})
                except OSError:
                    pass  # client went away mid-replay

            def do_POST(self):
                parts = self.path.split("/")
                if len(parts) >= 3 and parts[1] == "v2" and parts[2] == "repository":
                    return self._repository(parts)
                if len(parts) == 5 and parts[1] == "v2" and parts[2] == "models" and parts[4] == "generate":
                    return self._generate(parts[3])
                if len(parts) < 5 or parts[1] != "v2" or parts[2] != "models" or parts[4] != "infer":
                    return self._json(404, {"error": "not found"})
                name = parts[3]
                batcher = server.batchers.get(name)
                model = server.models.get(name)
                if batcher is None or model is None:
                    return self._json(404, {"error": f"unknown model {name}"})
                # request parsing/validation errors -> 400; backpressure /
                # breaker / drain -> 503; expired deadline -> 504;
                # server-side inference failures -> 500 (round-1 conflated
                # them all into 400)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    timeout_ms = (req.get("parameters") or {}).get(
                        "timeout_ms", self.headers.get("X-Request-Timeout-Ms")
                    )
                    deadline_s = None if timeout_ms is None else float(timeout_ms) / 1000.0
                    by_name = {t["name"]: t for t in req["inputs"]}
                    arrays = []
                    for meta in model.inputs:
                        t = by_name.get(meta.name)
                        if t is None:
                            raise ValueError(f"missing input {meta.name}")
                        dt = _V2_DTYPES.get(t.get("datatype", "FP32"), np.float32)
                        arrays.append(np.asarray(t["data"], dtype=dt).reshape(t["shape"]))
                    priority = (req.get("parameters") or {}).get(
                        "priority", self.headers.get("X-Request-Priority")
                    )
                    fut = batcher.submit(
                        arrays, deadline_s=deadline_s, transport="http",
                        priority=priority,
                    )
                except ResilienceError as e:  # backpressure/deadline/breaker/drain
                    return self._json(
                        http_status(e), _reject_payload(e),
                        headers=_reject_headers(e),
                    )
                except RuntimeError as e:  # batcher stopped: server-side
                    return self._json(500, {"error": str(e)})
                except Exception as e:
                    return self._json(400, {"error": str(e)})
                try:
                    # a request-supplied deadline owns the wait; 60s is
                    # only the default for budget-less requests
                    outs = fut.result(timeout=deadline_s if deadline_s is not None else 60.0)
                except ResilienceError as e:
                    return self._json(
                        http_status(e), _reject_payload(e),
                        headers=_reject_headers(e),
                    )
                except (TimeoutError, _FuturesTimeout):
                    # futures.TimeoutError only aliases the builtin from
                    # 3.11 on; cancel so the abandoned request never
                    # occupies space in a later device batch
                    fut.cancel()
                    return self._json(504, {"error": "inference timed out"})
                except Exception as e:
                    return self._json(500, {"error": str(e)})
                resp = {
                    "model_name": name,
                    "outputs": [
                        {
                            "name": meta.name,
                            "shape": list(o.shape),
                            "datatype": _NP_TO_V2.get(str(o.dtype), "FP32"),
                            "data": np.asarray(o, dtype=np.float64 if o.dtype.kind == "f" else o.dtype).reshape(-1).tolist(),
                        }
                        for meta, o in zip(model.outputs, outs)
                    ],
                }
                return self._json(200, resp)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        for b in self.batchers.values():
            b.start()
        for g in self.generators.values():
            g.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True):
        """Graceful by default: readiness flips to 503 first (so load
        balancers stop routing here), queued + in-flight requests finish,
        then the listener closes. ``drain=False`` errors queued work."""
        self._draining = True
        try:
            for b in self.batchers.values():
                b.stop(drain=drain)
            for g in self.generators.values():
                g.stop(drain=drain)
            if self._httpd:
                self._httpd.shutdown()
                self._httpd.server_close()
                self._httpd = None
            if self._thread:
                self._thread.join(timeout=5)
                self._thread = None
        finally:
            self._draining = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
