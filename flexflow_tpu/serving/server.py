"""HTTP inference server speaking the KServe/Triton v2 protocol subset.

Reference: triton/ (SURVEY §2.9) — the reference serves its Legion op
graph as a Triton backend; its wire protocol is Triton's v2 inference
API. This server implements the same surface directly (stdlib only):

  GET  /v2/health/ready                    -> 200 when serving
  GET  /v2/models/{name}                   -> model metadata
  POST /v2/models/{name}/infer             -> run inference

Infer request JSON: {"inputs": [{"name", "shape", "datatype", "data"}]},
response mirrors it — the v2 tensor format with row-major flat data.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from .batcher import DynamicBatcher
from .model import InferenceModel

_V2_DTYPES = {
    "FP32": np.float32, "FP64": np.float64, "FP16": np.float16,
    "BF16": np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.float32,
    "INT32": np.int32, "INT64": np.int64, "BOOL": np.bool_,
}
_NP_TO_V2 = {
    "float32": "FP32", "float64": "FP64", "float16": "FP16",
    "bfloat16": "BF16", "int32": "INT32", "int64": "INT64", "bool": "BOOL",
}


class InferenceServer:
    """Serves one or more InferenceModels over HTTP with dynamic batching.

    With a ModelRepository attached, the Triton v2 repository lifecycle
    endpoints are live (reference: Triton's model-repository management
    above triton/src/model.cc):

      POST /v2/repository/index                  -> available + loaded state
      POST /v2/repository/models/{name}/load     -> load from disk
      POST /v2/repository/models/{name}/unload   -> stop serving + drop
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_delay_s: float = 0.005,
        repository=None,
    ):
        self.host = host
        self.port = port
        self.models: Dict[str, InferenceModel] = {}
        self.batchers: Dict[str, DynamicBatcher] = {}
        self.max_delay_s = max_delay_s
        self.repository = repository
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def register(self, model: InferenceModel):
        self.models[model.name] = model
        b = DynamicBatcher(model, max_delay_s=self.max_delay_s)
        self.batchers[model.name] = b
        if self._httpd is not None:
            b.start()

    def unregister(self, name: str) -> bool:
        b = self.batchers.pop(name, None)
        if b is not None:
            b.stop()
        return self.models.pop(name, None) is not None

    # ------------------------------------------------------------ control
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _repository(self, parts):
                repo = server.repository
                if repo is None:
                    return self._json(400, {"error": "no model repository configured"})
                if len(parts) == 4 and parts[3] == "index":
                    return self._json(200, [
                        {
                            "name": n,
                            "state": "READY" if n in server.models else "UNAVAILABLE",
                        }
                        for n in sorted(set(repo.available()) | set(server.models))
                    ])
                if len(parts) == 6 and parts[3] == "models" and parts[5] in ("load", "unload"):
                    name = parts[4]
                    if parts[5] == "load":
                        try:
                            server.register(repo.load(name))
                        except KeyError as e:
                            return self._json(404, {"error": str(e)})
                        except Exception as e:
                            return self._json(500, {"error": str(e)})
                        return self._json(200, {"name": name, "state": "READY"})
                    if not server.unregister(name):
                        return self._json(404, {"error": f"model {name} not loaded"})
                    return self._json(200, {"name": name, "state": "UNAVAILABLE"})
                return self._json(404, {"error": "not found"})

            def do_GET(self):
                if self.path == "/v2/health/ready":
                    return self._json(200, {"ready": True})
                if self.path == "/v2/models":
                    return self._json(200, {"models": sorted(server.models)})
                if self.path.startswith("/v2/models/"):
                    name = self.path.split("/")[3]
                    m = server.models.get(name)
                    if m is None:
                        return self._json(404, {"error": f"unknown model {name}"})
                    return self._json(200, m.metadata())
                return self._json(404, {"error": "not found"})

            def do_POST(self):
                parts = self.path.split("/")
                if len(parts) >= 3 and parts[1] == "v2" and parts[2] == "repository":
                    return self._repository(parts)
                if len(parts) < 5 or parts[1] != "v2" or parts[2] != "models" or parts[4] != "infer":
                    return self._json(404, {"error": "not found"})
                name = parts[3]
                batcher = server.batchers.get(name)
                model = server.models.get(name)
                if batcher is None or model is None:
                    return self._json(404, {"error": f"unknown model {name}"})
                # request parsing/validation errors -> 400; server-side
                # inference failures -> 500; timeout -> 504 (round-1
                # conflated them all into 400)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    by_name = {t["name"]: t for t in req["inputs"]}
                    arrays = []
                    for meta in model.inputs:
                        t = by_name.get(meta.name)
                        if t is None:
                            raise ValueError(f"missing input {meta.name}")
                        dt = _V2_DTYPES.get(t.get("datatype", "FP32"), np.float32)
                        arrays.append(np.asarray(t["data"], dtype=dt).reshape(t["shape"]))
                    fut = batcher.submit(arrays)
                except RuntimeError as e:  # batcher stopped: server-side
                    return self._json(500, {"error": str(e)})
                except Exception as e:
                    return self._json(400, {"error": str(e)})
                try:
                    outs = fut.result(timeout=60.0)
                except TimeoutError:
                    return self._json(504, {"error": "inference timed out"})
                except Exception as e:
                    return self._json(500, {"error": str(e)})
                resp = {
                    "model_name": name,
                    "outputs": [
                        {
                            "name": meta.name,
                            "shape": list(o.shape),
                            "datatype": _NP_TO_V2.get(str(o.dtype), "FP32"),
                            "data": np.asarray(o, dtype=np.float64 if o.dtype.kind == "f" else o.dtype).reshape(-1).tolist(),
                        }
                        for meta, o in zip(model.outputs, outs)
                    ],
                }
                return self._json(200, resp)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        for b in self.batchers.values():
            b.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for b in self.batchers.values():
            b.stop()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
