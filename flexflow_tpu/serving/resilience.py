"""Serving-resilience primitives: typed rejections, retry with backoff,
and a per-model circuit breaker.

The reference's Triton backend delegates all of this to Triton core
(rate limiting, health endpoints); serving in-framework means owning it
ourselves. Three building blocks, consumed by DynamicBatcher and the
HTTP/gRPC front ends:

* Typed errors that map 1:1 onto protocol status codes, so transports
  can distinguish backpressure (503 / RESOURCE_EXHAUSTED) from expired
  deadlines (504 / DEADLINE_EXCEEDED) from an open breaker
  (503 / UNAVAILABLE) without string matching.
* :class:`RetryPolicy` — exponential backoff with seeded jitter for
  transient device errors (preemption, transport hiccup). Only
  exception types listed in ``retryable`` are retried; poisons
  (bad input, injected FaultInjected) fail fast.
* :class:`CircuitBreaker` — CLOSED→OPEN after ``failure_threshold``
  consecutive device failures; after ``recovery_s`` the next request is
  admitted as a HALF_OPEN probe whose outcome closes or re-opens the
  circuit. The health endpoints (``/v2/health/ready``, ``ServerReady``,
  ``ModelReady``) report this state instead of a constant ``True``.

Clocks and sleeps are injectable so chaos tests run on deterministic
virtual time with no real waiting.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Tuple, Type

from ..runtime.backoff import backoff_delay
from ..runtime.faults import TransientDeviceError


class ResilienceError(RuntimeError):
    """Base for typed serving rejections (subclasses RuntimeError so
    pre-existing catch-all handlers keep working)."""


class QueueFullError(ResilienceError):
    """Backpressure: the bounded request queue is full.
    HTTP 503 / gRPC RESOURCE_EXHAUSTED."""


class OverloadedError(QueueFullError):
    """Priority-aware overload rejection (serving/overload.py): the
    admission layer refused this request BEFORE it consumed queue or
    device capacity. Subclasses :class:`QueueFullError` so every
    pre-existing backpressure handler (HTTP 503, gRPC
    RESOURCE_EXHAUSTED, retry loops catching QueueFullError) keeps
    working; adds the structured fields clients need to back off
    intelligently:

      reason         why load was refused: "queue_full" (bounded queue,
                     possibly after a priority-ordered shed), "limiter"
                     (AdaptiveLimiter throttled admission before the
                     queue filled), "infeasible" (predicted TTFT already
                     exceeds the deadline), or "degraded" (the
                     degradation ladder is shedding this priority class)
      priority       the refused request's priority class
      retry_after_s  server-suggested backoff; rendered as the HTTP
                     ``Retry-After`` header and the gRPC
                     ``retry-after-ms`` trailing metadata
    """

    def __init__(
        self,
        msg: str,
        *,
        reason: str = "queue_full",
        priority: "str | None" = None,
        retry_after_s: "float | None" = None,
    ):
        super().__init__(msg)
        self.reason = reason
        self.priority = priority
        self.retry_after_s = retry_after_s


class InfeasibleError(OverloadedError):
    """Roofline-based infeasibility fast-fail: the request's predicted
    TTFT (PR 7 serving roofline x current queue) already exceeds its
    deadline, so admitting it could only burn capacity on work that is
    guaranteed to expire. Counted separately from sheds
    (``rejected_infeasible``)."""

    def __init__(self, msg: str, *, priority=None, retry_after_s=None,
                 predicted_ttft_s: "float | None" = None):
        super().__init__(msg, reason="infeasible", priority=priority,
                         retry_after_s=retry_after_s)
        self.predicted_ttft_s = predicted_ttft_s


def retry_after_s(err: BaseException) -> "float | None":
    """The server-suggested backoff riding a typed rejection (None when
    the error carries none) — the single helper both transports use to
    render ``Retry-After`` / ``retry-after-ms``."""
    v = getattr(err, "retry_after_s", None)
    try:
        return None if v is None else max(0.0, float(v))
    except (TypeError, ValueError):
        return None


class DeadlineExceededError(ResilienceError):
    """The request's deadline expired before (or while) it could be
    dispatched. HTTP 504 / gRPC DEADLINE_EXCEEDED."""


class CircuitOpenError(ResilienceError):
    """The model's circuit breaker is open; request rejected without
    touching the device. HTTP 503 / gRPC UNAVAILABLE."""


class ShuttingDownError(ResilienceError):
    """The batcher is draining for shutdown; new work is rejected while
    in-flight work completes. HTTP 503 / gRPC UNAVAILABLE."""


def http_status(err: ResilienceError) -> int:
    """The single source of truth for ResilienceError -> HTTP status
    (both front ends consult this instead of hand-maintaining ladders)."""
    return 504 if isinstance(err, DeadlineExceededError) else 503


def grpc_code(err: ResilienceError, grpc):
    """ResilienceError -> grpc.StatusCode (``grpc`` passed in so this
    module stays importable without grpcio)."""
    if isinstance(err, DeadlineExceededError):
        return grpc.StatusCode.DEADLINE_EXCEEDED
    if isinstance(err, QueueFullError):
        return grpc.StatusCode.RESOURCE_EXHAUSTED
    return grpc.StatusCode.UNAVAILABLE


class RetryPolicy:
    """Exponential backoff with seeded jitter for transient errors.

    ``run(fn)`` calls ``fn`` up to ``max_attempts`` times, sleeping
    ``base_delay_s * 2**(attempt-1)`` (capped at ``max_delay_s``, plus
    up to ``jitter`` fractional noise) between attempts. Exceptions not
    in ``retryable`` propagate immediately.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay_s: float = 0.01,
        max_delay_s: float = 0.5,
        jitter: float = 0.25,
        retryable: Tuple[Type[BaseException], ...] = (TransientDeviceError,),
        sleep: Callable[[float], None] = time.sleep,
        seed: int = 0,
    ):
        self.max_attempts = max(1, max_attempts)
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.retryable = tuple(retryable)
        self.sleep = sleep
        self._rng = random.Random(f"retry|{seed}")
        self.last_attempts = 0  # observability: attempts used by last run()

    def would_retry(self, err: BaseException) -> bool:
        """True when :meth:`run` would retry this error — the overlap
        scheduler's pipelined-failure arbitration: a retryable error
        surfacing from an async-dispatched step gets the same
        invisible-retry treatment a synchronous step would have
        received inside ``run()``."""
        return isinstance(err, self.retryable) and self.max_attempts > 1

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        return backoff_delay(
            attempt,
            base_s=self.base_delay_s,
            max_s=self.max_delay_s,
            jitter=self.jitter,
            rng=self._rng,
        )

    def run(self, fn: Callable[[], "object"]):
        attempt = 0
        while True:
            attempt += 1
            try:
                out = fn()
            except self.retryable:
                if attempt >= self.max_attempts:
                    self.last_attempts = attempt
                    raise
                self.sleep(self.delay_for(attempt))
                continue
            self.last_attempts = attempt
            return out


class CircuitBreaker:
    """Per-model circuit breaker.

    CLOSED: everything admitted; ``failure_threshold`` consecutive
    failures open the circuit. OPEN: everything rejected until
    ``recovery_s`` has elapsed, then ONE request is admitted as a
    HALF_OPEN probe. HALF_OPEN: the probe's success closes the circuit,
    its failure re-opens it (fresh recovery window); concurrent requests
    are rejected while the probe is in flight.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        failure_threshold: int = 5,
        recovery_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_s = recovery_s
        self.clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def ready(self) -> bool:
        """Health-endpoint view: not-ready only while OPEN (a HALF_OPEN
        probe in flight counts as recovering, i.e. ready)."""
        with self._lock:
            if self._state != self.OPEN:
                return True
            # an elapsed recovery window means the next request will be
            # admitted as a probe; report ready so traffic returns
            return self.clock() - self._opened_at >= self.recovery_s

    def allow(self) -> bool:
        """Admission check; may transition OPEN→HALF_OPEN (claiming the
        probe slot for the caller)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self.clock()
            if self._state == self.OPEN:
                if now - self._opened_at >= self.recovery_s:
                    self._state = self.HALF_OPEN
                    self._probing = True
                    self._probe_at = now
                    return True
                return False
            # HALF_OPEN: one probe at a time — but a probe whose outcome
            # never got recorded (client abandoned it before dispatch)
            # must not wedge recovery, so it times out after recovery_s
            if not self._probing or now - self._probe_at >= self.recovery_s:
                self._probing = True
                self._probe_at = now
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = self.CLOSED
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or self._failures >= self.failure_threshold:
                self._state = self.OPEN
                self._opened_at = self.clock()
                self._failures = 0
                self._probing = False

    def trip(self) -> None:
        """Force the breaker OPEN immediately, bypassing the consecutive-
        failure count. The generation step watchdog calls this when a
        device step stalls: no request completes (so nothing calls
        record_failure), but health endpoints must stop reporting a hung
        device as ready."""
        with self._lock:
            self._state = self.OPEN
            self._opened_at = self.clock()
            self._failures = 0
            self._probing = False
