"""Serving statistics: per-model counters, latency summaries, and
gauges, surfaced on the HTTP server's ``/v2/stats`` endpoint.

One struct serves both serving paths: the dynamic batcher counts
admissions/rejections/expiries and per-request latency; the generation
engine reports tokens/s and cache occupancy through the same struct via
``gauges`` (zero-arg callables evaluated at snapshot time, so the
endpoint always reads live values without the stats object holding
references into hot-path state).

Thread-safety: counters take a lock (collector threads, HTTP handler
threads, and the generation scheduler all write concurrently);
snapshots are consistent-enough reads for monitoring, not transactions.
"""
from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Callable, Dict, Optional, Sequence, Tuple


class LatencyWindow:
    """Rolling window of the last ``maxlen`` request latencies with
    cheap summary stats (count is cumulative; percentiles are over the
    window). Observations may carry an *exemplar* — an opaque id (the
    fleet journey id) retained alongside the sample so a bad percentile
    links back to one concrete, stitchable request journey."""

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=maxlen)  # guarded-by: _lock
        # (seconds, exemplar-id) pairs, same horizon as the window —
        # only samples that arrived WITH an id (journeys on)
        self._exemplars: deque = deque(maxlen=maxlen)  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock
        self.total_s = 0.0  # guarded-by: _lock
        self.max_s = 0.0  # guarded-by: _lock

    def record(self, seconds: float, exemplar: Optional[str] = None) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)
            self._window.append(seconds)
            if exemplar is not None:
                self._exemplars.append((seconds, exemplar))

    def slow_exemplars(self, k: int = 8) -> list:
        """Up to ``k`` worst-decile samples (>= the window p90, ties
        included) that carried an exemplar id, slowest first, deduped by
        id — the ``/v2/debug/slow`` rows for this window."""
        with self._lock:
            window = sorted(self._window)
            pairs = list(self._exemplars)
        if not window or not pairs:
            return []
        p90 = window[min(len(window) - 1, math.ceil(0.90 * len(window)) - 1)]
        out, seen = [], set()
        for seconds, ex in sorted(pairs, key=lambda p: -p[0]):
            if seconds < p90 or ex in seen:
                continue
            seen.add(ex)
            out.append({"seconds": seconds, "journey_id": ex})
            if len(out) >= k:
                break
        return out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            window = sorted(self._window)
            n = len(window)
            # nearest-rank: ceil(p*n) is the 1-based rank of the p-th
            # percentile sample (int(p*n) biased high on small windows:
            # p50 of 2 samples returned the max)
            pct = lambda p: window[min(n - 1, math.ceil(p * n) - 1)] if n else 0.0
            return {
                "count": self.count,
                "sum_s": self.total_s,
                "mean_s": self.total_s / self.count if self.count else 0.0,
                "max_s": self.max_s,
                "p50_s": pct(0.50),
                "p95_s": pct(0.95),
                "p99_s": pct(0.99),
            }


class Histogram:
    """Fixed-bucket latency histogram in the Prometheus shape:
    cumulative bucket counts keyed by upper bound (``le``), plus running
    sum and count. Buckets are chosen once (seconds, spanning sub-ms
    TTFT on warm engines to multi-second cold paths); observations are
    a bisect + three increments under a lock."""

    DEFAULT_BUCKETS: Tuple[float, ...] = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    )

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        bounds = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        if not bounds or bounds[-1] != math.inf:
            bounds = bounds + (math.inf,)
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * len(bounds)  # per-bucket (non-cumulative); guarded-by: _lock
        self.count = 0  # guarded-by: _lock
        self.sum = 0.0  # guarded-by: _lock

    def observe(self, value: float) -> None:
        # bucket i is the first bound >= value (the last bound is +Inf,
        # so the index always lands in range)
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.count += 1
            self.sum += value
            self._counts[i] += 1

    def snapshot(self) -> Dict:
        """Cumulative (le, count) pairs the exposition format wants."""
        with self._lock:
            counts = list(self._counts)
            total, s = self.count, self.sum
        cum, buckets = 0, []
        for b, c in zip(self.bounds, counts):
            cum += c
            buckets.append((b, cum))
        return {"count": total, "sum": s, "buckets": buckets}


class ServingStats:
    """Counters + latency windows + histograms + live gauges for one
    served model. ``observe(name, s)`` feeds a named window (rolling
    percentiles on /v2/stats) AND a Prometheus histogram (/metrics)
    under the same name — queue_time / ttft / tpot in generation."""

    COUNTERS = ("admitted", "rejected", "expired", "completed", "failed", "cancelled")

    def __init__(self, latency_window: int = 512):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {c: 0 for c in self.COUNTERS}  # guarded-by: _lock
        self.latency = LatencyWindow(latency_window)
        self._window_len = latency_window
        # name -> zero-arg callable returning a number (queue depth,
        # cache occupancy, tokens/s ...), evaluated at snapshot time.
        # Registration and iteration share self._lock: a model loading
        # mid-scrape must not mutate the dict under snapshot()'s feet.
        self.gauges: Dict[str, Callable[[], float]] = {}  # guarded-by: _lock
        self._windows: Dict[str, LatencyWindow] = {}  # guarded-by: _lock
        self._histograms: Dict[str, Histogram] = {}  # guarded-by: _lock

    def incr(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self._counts[counter] = self._counts.get(counter, 0) + n

    def get(self, counter: str) -> int:
        with self._lock:
            return self._counts.get(counter, 0)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def add_gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self.gauges[name] = fn

    def observe(self, name: str, seconds: float,
                exemplar: Optional[str] = None) -> None:
        """Record one observation into the named window + histogram
        (created on first use). ``exemplar`` — a journey id, retained
        for worst-decile samples so tail latency links to a stitched
        journey — is None whenever journeys are off."""
        with self._lock:
            w = self._windows.get(name)
            if w is None:
                w = self._windows[name] = LatencyWindow(self._window_len)
                self._histograms[name] = Histogram()
            h = self._histograms[name]
        w.record(seconds, exemplar=exemplar)
        h.observe(seconds)

    def slow_exemplars(self, k: int = 8) -> Dict[str, list]:
        """Worst-decile exemplars per named window (ttft / tpot /
        queue_time ...), windows with none omitted."""
        with self._lock:
            windows = dict(self._windows)
        out: Dict[str, list] = {}
        for name, w in windows.items():
            rows = w.slow_exemplars(k)
            if rows:
                out[name] = rows
        return out

    def window_p95(self, name: str) -> float:
        """One named window's rolling p95 (0.0 before any observation)
        — the AdaptiveLimiter's queue-time / TTFT pressure inputs,
        without snapshotting every window per control tick."""
        with self._lock:
            w = self._windows.get(name)
        return w.snapshot()["p95_s"] if w is not None else 0.0

    def window_snapshots(self) -> Dict[str, Dict]:
        with self._lock:
            windows = dict(self._windows)
        return {name: w.snapshot() for name, w in windows.items()}

    def histogram_snapshots(self) -> Dict[str, Dict]:
        with self._lock:
            hists = dict(self._histograms)
        return {name: h.snapshot() for name, h in hists.items()}

    def gauge_values(self) -> Dict[str, Optional[float]]:
        """Evaluate every gauge (None for a dying gauge) — the shared
        read path for /v2/stats and /metrics."""
        with self._lock:
            gauges = list(self.gauges.items())
        out: Dict[str, Optional[float]] = {}
        for name, fn in gauges:
            try:
                out[name] = fn()
            except Exception:  # a dying gauge must not kill a scrape
                out[name] = None
        return out

    def snapshot(self) -> Dict:
        out: Dict = dict(self.counters())
        out["latency"] = self.latency.snapshot()
        for name, snap in self.window_snapshots().items():
            out[name] = snap
        out.update(self.gauge_values())
        return out


class SpeculationStats:
    """Speculative-decoding counters for one served model: drafted
    (proposed) vs accepted tokens per verification window, plus the
    derived acceptance rate and mean accepted run length surfaced as
    /v2/stats gauges.

    ``record_window(proposed, accepted)`` is called once per verify
    window per sequence; windows with zero proposals (drafter miss,
    budget cap) still count toward ``windows`` so the mean run length
    reflects what the engine actually did.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.windows = 0  # guarded-by: _lock
        self.proposed = 0  # guarded-by: _lock
        self.accepted = 0  # guarded-by: _lock
        self.emitted = 0  # guarded-by: _lock

    def record_window(self, proposed: int, accepted: int, emitted: int) -> None:
        with self._lock:
            self.windows += 1
            self.proposed += proposed
            self.accepted += accepted
            self.emitted += emitted

    def acceptance_rate(self) -> float:
        with self._lock:
            return self.accepted / self.proposed if self.proposed else 0.0

    def mean_accepted_len(self) -> float:
        """Mean accepted drafts per verification window."""
        with self._lock:
            return self.accepted / self.windows if self.windows else 0.0

    def mean_emitted_len(self) -> float:
        """Mean tokens emitted per verification window (accepted drafts
        + the correction/bonus token) — the tokens-per-engine-step
        multiplier over non-speculative decode."""
        with self._lock:
            return self.emitted / self.windows if self.windows else 0.0

    def counts(self) -> Dict[str, int]:
        """Locked snapshot of the raw counters — the gauge read path
        (gauge callables run on scrape threads while the verify loop is
        mid-record_window)."""
        with self._lock:
            return {
                "windows": self.windows,
                "proposed": self.proposed,
                "accepted": self.accepted,
                "emitted": self.emitted,
            }

    def register_gauges(self, stats: "ServingStats", prefix: str = "spec_") -> None:
        stats.add_gauge(prefix + "windows", lambda: self.counts()["windows"])
        stats.add_gauge(prefix + "tokens_proposed", lambda: self.counts()["proposed"])
        stats.add_gauge(prefix + "tokens_accepted", lambda: self.counts()["accepted"])
        stats.add_gauge(prefix + "acceptance_rate", self.acceptance_rate)
        stats.add_gauge(prefix + "mean_accepted_len", self.mean_accepted_len)
        stats.add_gauge(prefix + "mean_emitted_len", self.mean_emitted_len)


class RecoveryStats:
    """Self-healing counters for one generation engine (supervisor +
    step watchdog, generation/recovery.py), surfaced as /v2/stats
    gauges:

      recoveries       completed engine restart + journal-replay cycles
      step_retries     failed device steps absorbed by the supervisor's
                       single step retry (no restart needed)
      replayed_tokens  generated tokens folded back into prompts for
                       recompute-replay across all recoveries
      quarantined      poisoned requests failed alone (NaN blame or
                       crash bisection) while the rest of the batch
                       kept going
      watchdog_trips   stalled device steps detected by the watchdog
      engine_failures  restart budgets exhausted (engine declared dead)
      kv_imports       handed-off KV payloads committed into this
                       engine's cache (disaggregated decode admission)
      kv_imports_rejected  imported payloads rejected (CRC/geometry/
                       injected fault) and recovered by recompute

    Writers: the scheduler loop thread and the watchdog thread; the
    lock keeps increments exact so chaoscheck can assert counts.
    """

    FIELDS = (
        "recoveries", "step_retries", "replayed_tokens",
        "quarantined", "watchdog_trips", "engine_failures",
        "kv_imports", "kv_imports_rejected",
    )

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)

    def incr(self, field: str, n: int = 1) -> None:
        if field not in self.FIELDS:
            raise ValueError(f"unknown recovery counter {field!r}")
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def register_gauges(self, stats: "ServingStats") -> None:
        for f in self.FIELDS:
            stats.add_gauge(f, lambda f=f: getattr(self, f))


class ConstrainedStats:
    """Constrained-decoding counters for one generation engine
    (generation/constrained/), surfaced as /v2/stats gauges and the
    ``flexflow_serving_constrained_*`` Prometheus families:

      grammar_cache_hits      response_format specs served from the
                              per-model compiled-grammar cache
      grammar_cache_misses    specs that compiled a new token DFA
      grammar_compile_seconds cumulative wall seconds spent compiling
                              grammars (floats accumulate)
      masked_steps            slot-steps that carried a real (non-zero)
                              grammar mask row into decode/verify
      dead_end_failures       constrained streams quarantined because
                              the automaton refused an emitted token or
                              reached an empty mask (injected faults or
                              replay divergence — pruning makes natural
                              dead-ends unreachable)

    Writers: the scheduler loop thread (mask assembly/advance) and
    serving submit threads (the grammar cache); the lock keeps counts
    exact so chaoscheck/genbench can assert them.
    """

    FIELDS = (
        "grammar_cache_hits", "grammar_cache_misses",
        "grammar_compile_seconds", "masked_steps", "dead_end_failures",
    )

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)

    def incr(self, field: str, n=1) -> None:
        if field not in self.FIELDS:
            raise ValueError(f"unknown constrained counter {field!r}")
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def register_gauges(self, stats: "ServingStats") -> None:
        # cumulative counters -> prometheus-conventional _total names
        # (flexflow_serving_constrained_* once prom.py prefixes them)
        for f in self.FIELDS:
            stats.add_gauge(
                f"constrained_{f}_total", lambda f=f: getattr(self, f)
            )


class DurableStats:
    """Durable-serving counters for one generation engine
    (serving/durable.py + runtime/wal.py), surfaced as /v2/stats gauges
    and the ``flexflow_serving_durable_*`` Prometheus families:

      wal_appends          journal records framed into the WAL buffer
      wal_bytes            framed bytes appended (headers included)
      fsyncs               group commits that reached fsync
      replayed_streams     unfinished streams a warm restart re-admitted
      replayed_tokens      journaled tokens those streams carried back
      torn_records         torn tails truncated off the newest segment
                           on open (crash mid-append — expected)
      rolling_restarts     completed rolling-restart cycles this replica
                           came up through
      wal_append_failures  streams degraded to non-durable by a failed
                           journal append (the counted warning — the
                           decode hot path never blocks on the log)

    The wal_* write/commit counters live inside the WriteAheadLog (its
    appends are lock-protected already); set :attr:`wal` and the gauge
    read path merges them live. ``wal_segments`` is a level gauge over
    the segment directory. Writers: the scheduler loop thread (via the
    DurableJournal) and warm-restart/rolling-restart callers; the lock
    keeps replay counters exact so chaoscheck can assert them.
    """

    FIELDS = (
        "replayed_streams", "replayed_tokens", "torn_records",
        "rolling_restarts", "wal_append_failures",
    )
    WAL_FIELDS = ("wal_appends", "wal_bytes", "fsyncs")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        # the attached WriteAheadLog (duck-typed: counters() +
        # segment_count()); None until a Durability wires one in
        self.wal = None

    def incr(self, field: str, n: int = 1) -> None:
        if field not in self.FIELDS:
            raise ValueError(f"unknown durable counter {field!r}")
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def counts(self) -> Dict[str, int]:
        """Locked snapshot merged with the live WAL write counters —
        the gauge read path (scrape threads race the loop thread)."""
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
        wal = self.wal
        wc = wal.counters() if wal is not None else {}
        out["wal_appends"] = wc.get("appends", 0)
        out["wal_bytes"] = wc.get("bytes", 0)
        out["fsyncs"] = wc.get("fsyncs", 0)
        return out

    def segments(self) -> int:
        wal = self.wal
        return wal.segment_count() if wal is not None else 0

    def register_gauges(self, stats: "ServingStats") -> None:
        # cumulative counters -> prometheus-conventional _total names
        # (flexflow_serving_durable_* once prom.py prefixes them), plus
        # the one level gauge (segments on disk right now)
        for f in self.WAL_FIELDS + self.FIELDS:
            stats.add_gauge(f"durable_{f}_total", lambda f=f: self.counts()[f])
        stats.add_gauge("durable_wal_segments", self.segments)


class FleetStats:
    """Fleet-lifecycle counters for one replicated generation service
    (serving/fleet.py), surfaced on ``GET /v2/fleet`` and as the
    ``flexflow_serving_fleet_*`` / ``router_decisions_total`` Prometheus
    families:

      failovers        replica deaths (restart budget exhausted) whose
                       live streams were handed to the fleet for
                       cross-replica journal-replay
      migrated_streams requests journal-replayed onto a surviving (or
                       replacement) replica
      replaced         replicas retired and swapped for a fresh warmed
                       replica (drain completion, drain timeout, or
                       post-failover replacement)
      drains           replicas transitioned to DRAINING by a health
                       signal or operator call
      spawn_failures   replacement spawns that failed (engine factory or
                       warmup error; retried on the next check)
      sheds            fleet-wide sheds: requests refused because EVERY
                       eligible replica was saturated (the router's
                       per-replica spill had nowhere left to go)

    Router decisions are counted by reason ("affinity", "least_loaded",
    "only_candidate", "no_candidate") — the
    ``router_decisions_total{reason}`` counter.

    Writers: replica loop threads (failover sinks) and the fleet
    supervisor; the lock keeps increments exact so chaoscheck can
    assert counts.
    """

    FIELDS = (
        "failovers", "migrated_streams", "replaced", "drains",
        "spawn_failures", "sheds",
    )

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._decisions: Dict[str, int] = {}  # guarded-by: _lock

    def incr(self, field: str, n: int = 1) -> None:
        if field not in self.FIELDS:
            raise ValueError(f"unknown fleet counter {field!r}")
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def note_decision(self, reason: str) -> None:
        with self._lock:
            self._decisions[reason] = self._decisions.get(reason, 0) + 1

    def decisions(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._decisions)

    def snapshot(self) -> Dict:
        with self._lock:
            out: Dict = {f: getattr(self, f) for f in self.FIELDS}
            out["router_decisions"] = dict(self._decisions)
            return out


class GoodputStats:
    """Deadline-goodput accounting for one served model: tokens emitted
    on requests that COMPLETED within their deadline vs all tokens
    emitted (a request with no deadline counts as in-deadline when it
    completes; failed/expired/cancelled requests contribute only to the
    denominator). The honest throughput number — raw tokens/s includes
    work clients never benefited from.

    Written once per finished request by the scheduler's trace-done
    hook (loop or watchdog thread), read by scrape threads.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.tokens_total = 0  # guarded-by: _lock
        self.tokens_good = 0  # guarded-by: _lock
        self.requests_total = 0  # guarded-by: _lock
        self.requests_good = 0  # guarded-by: _lock

    def record(self, n_tokens: int, good: bool) -> None:
        with self._lock:
            self.requests_total += 1
            self.tokens_total += n_tokens
            if good:
                self.requests_good += 1
                self.tokens_good += n_tokens

    def ratio(self) -> float:
        with self._lock:
            return self.tokens_good / self.tokens_total if self.tokens_total else 0.0

    def totals(self) -> Tuple[int, int]:
        """Locked (tokens_total, tokens_good) — the gauge read path."""
        with self._lock:
            return self.tokens_total, self.tokens_good

    def register_gauges(self, stats: "ServingStats") -> None:
        stats.add_gauge("goodput_tokens_total", lambda: self.totals()[0])
        stats.add_gauge("goodput_tokens_good", lambda: self.totals()[1])
        stats.add_gauge("goodput_ratio", self.ratio)


class TokenRate:
    """Windowed tokens/s gauge for the generation engine: record token
    batches as they are emitted; ``rate()`` is tokens over the trailing
    ``window_s`` seconds of the supplied clock."""

    def __init__(self, clock: Callable[[], float], window_s: float = 10.0):
        self._clock = clock
        self._window_s = window_s
        self._lock = threading.Lock()
        self._events: deque = deque()  # (t, n_tokens); guarded-by: _lock
        self.total = 0  # guarded-by: _lock

    def record(self, n_tokens: int) -> None:
        now = self._clock()
        with self._lock:
            self.total += n_tokens
            self._events.append((now, n_tokens))
            self._trim_locked(now)

    def _trim_locked(self, now: float) -> None:
        # caller holds self._lock
        while self._events and now - self._events[0][0] > self._window_s:
            self._events.popleft()

    def rate(self) -> float:
        now = self._clock()
        with self._lock:
            self._trim_locked(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-9)
            n = sum(c for _, c in self._events)
            # a single instantaneous burst has no measurable span; report
            # it over the window instead of a 1e9 spike
            return n / (span if span > 1e-6 else self._window_s)
