"""User-inspectable parallel-tensor metadata.

Reference: ParallelTensorBase (include/flexflow/parallel_tensor.h:36-71,
134-200) — every materialized tensor carries per-dim ``size / degree /
parallel_idx / is_replica_dim`` plus its machine view, and
set_tensor/get_tensor move host data in and out of the partitioned
regions. TPU-native, the same facts live in the compiled strategy
(PartitionSpecs over named mesh axes); this module surfaces them as a
first-class view so users can ask "how is this tensor actually sharded"
without reading GSPMD internals — closing the round-2 gap where shard
state existed only inside the search (_ShardState).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .tensor import TensorSpec
from .types import DataType


@dataclasses.dataclass(frozen=True)
class ParallelDim:
    """One logical dimension's partitioning (parallel_tensor.h:36-71)."""

    size: int  # global extent
    degree: int  # number of shards along this dim
    mesh_axes: Tuple[str, ...]  # mesh axes sharding it (() = unsharded)

    @property
    def shard_size(self) -> int:
        return self.size // max(1, self.degree)


@dataclasses.dataclass(frozen=True)
class ParallelTensorView:
    """How one tensor is laid out over the mesh.

    ``replica_degree`` is the product of mesh axes that do NOT shard any
    dimension — the reference's replica dims (is_replica_dim): a weight
    under data parallelism has replica_degree == dp.
    """

    shape: Tuple[int, ...]
    dtype: DataType
    dims: Tuple[ParallelDim, ...]
    replica_degree: int
    machine_view_hash: int = 0

    @property
    def num_shards(self) -> int:
        n = 1
        for d in self.dims:
            n *= d.degree
        return n

    @property
    def shard_shape(self) -> Tuple[int, ...]:
        return tuple(d.shard_size for d in self.dims)

    def __repr__(self):
        parts = ", ".join(
            f"{d.size}/{d.degree}" + (f"@{'+'.join(d.mesh_axes)}" if d.mesh_axes else "")
            for d in self.dims
        )
        return (
            f"ParallelTensorView([{parts}], replicas={self.replica_degree}, "
            f"dtype={self.dtype.value})"
        )


def view_from_spec(
    spec: TensorSpec,
    partition_spec,  # SpecTuple (parallel/strategy.py) or None
    axis_sizes: Dict[str, int],
    machine_view_hash: int = 0,
) -> ParallelTensorView:
    """Build a view from a strategy PartitionSpec + mesh axis sizes."""
    active = {k: v for k, v in axis_sizes.items() if v > 1}
    used: set = set()
    dims: List[ParallelDim] = []
    for i, size in enumerate(spec.shape):
        axes: Tuple[str, ...] = ()
        if partition_spec is not None and i < len(partition_spec):
            axes = tuple(a for a in partition_spec[i] if active.get(a, 1) > 1)
        degree = 1
        for a in axes:
            degree *= active[a]
            used.add(a)
        dims.append(ParallelDim(size=size, degree=degree, mesh_axes=axes))
    replica = 1
    for a, v in active.items():
        if a not in used:
            replica *= v
    return ParallelTensorView(
        shape=tuple(spec.shape),
        dtype=spec.dtype,
        dims=tuple(dims),
        replica_degree=replica,
        machine_view_hash=machine_view_hash,
    )
