"""Core enums and type definitions.

TPU-native analog of the reference's constant universe
(reference: include/flexflow/ffconst.h) — op types, activation modes,
loss/metrics types, parameter-sync and allreduce-schedule options.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp


class DataType(enum.Enum):
    """Tensor element types (reference: ffconst.h DataType)."""

    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    @property
    def jnp(self):
        return jnp.dtype(self.value)

    @property
    def size_bytes(self) -> int:
        return jnp.dtype(self.value).itemsize

    @classmethod
    def from_jnp(cls, dtype) -> "DataType":
        return cls(jnp.dtype(dtype).name)


class ActiMode(enum.Enum):
    """Fused activation modes (reference: ffconst.h ActiMode)."""

    NONE = "none"
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    GELU = "gelu"


class AggrMode(enum.Enum):
    """Embedding aggregation (reference: ffconst.h AggrMode)."""

    NONE = "none"
    SUM = "sum"
    AVG = "avg"


class PoolType(enum.Enum):
    """Pooling modes (reference: ffconst.h PoolType)."""

    MAX = "max"
    AVG = "avg"


class LossType(enum.Enum):
    """Loss functions (reference: include/flexflow/loss_functions.h:27)."""

    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    MEAN_SQUARED_ERROR_AVG_REDUCE = "mean_squared_error_avg_reduce"
    MEAN_SQUARED_ERROR_SUM_REDUCE = "mean_squared_error_sum_reduce"
    IDENTITY = "identity"


class MetricsType(enum.Enum):
    """Metrics (reference: include/flexflow/metrics_functions.h:27)."""

    ACCURACY = "accuracy"
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"


class CompMode(enum.Enum):
    """Compilation mode (reference: ffconst.h:41-44)."""

    TRAINING = "training"
    INFERENCE = "inference"


class ParameterSyncType(enum.Enum):
    """Gradient sync strategy (reference: ffconst.h:46-50).

    On TPU both lower to XLA collectives over ICI; PS is kept for API
    parity and maps to a single-host reduce + broadcast pattern.
    """

    NONE = "none"
    PS = "ps"
    NCCL = "allreduce"  # TPU: psum over mesh data axes


class ParameterSyncOption(enum.Enum):
    """Per-parameter allreduce schedule (fork feature, ffconst.h:52-57).

    On the ICI torus the XLA runtime picks the physical algorithm; these
    options steer the simulator/cost model and the allreduce-schedule
    optimizer pass (search/allreduce.py).
    """

    DEFAULT = "default"
    RING = "ring"
    BUTTERFLY = "butterfly"
    DOUBLE_BINARY_TREE = "double_binary_tree"


class OpType(enum.Enum):
    """Every operator the framework supports (reference: ffconst.h OperatorType)."""

    INPUT = "input"
    WEIGHT = "weight"
    NOOP = "noop"
    # dense / matmul family
    LINEAR = "linear"
    BATCH_MATMUL = "batch_matmul"
    # conv family
    CONV2D = "conv2d"
    POOL2D = "pool2d"
    FLAT = "flat"
    # attention
    MULTIHEAD_ATTENTION = "multihead_attention"
    # embedding
    EMBEDDING = "embedding"
    # normalization
    LAYERNORM = "layer_norm"
    BATCHNORM = "batch_norm"
    # elementwise binary
    EW_ADD = "add"
    EW_SUB = "subtract"
    EW_MUL = "multiply"
    EW_DIV = "divide"
    EW_MAX = "max"
    EW_MIN = "min"
    # elementwise unary
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    ELU = "elu"
    GELU = "gelu"
    IDENTITY = "identity"
    EXP = "exp"
    SIN = "sin"
    COS = "cos"
    RSQRT = "rsqrt"
    POW = "pow"
    SCALAR_ADD = "scalar_add"
    SCALAR_SUB = "scalar_sub"
    SCALAR_MUL = "scalar_multiply"
    SCALAR_TRUE_DIV = "scalar_true_div"
    # shape ops
    RESHAPE = "reshape"
    TRANSPOSE = "transpose"
    REVERSE = "reverse"
    CONCAT = "concat"
    SPLIT = "split"
    # misc
    SOFTMAX = "softmax"
    DROPOUT = "dropout"
    CAST = "cast"
    GATHER = "gather"
    REDUCE_SUM = "reduce_sum"
    MEAN = "mean"
    # recurrent (reference: nmt/ LSTM/RNN via cudnnRNN)
    RNN = "rnn"
    LSTM = "lstm"
    # MoE family
    TOPK = "topk"
    GROUP_BY = "group_by"
    AGGREGATE = "aggregate"
    AGGREGATE_SPEC = "aggregate_spec"
    CACHE = "cache"
    # batched expert FFN over [n_experts, capacity, d] (TPU-native: one
    # MXU-friendly einsum replaces the reference's n per-expert Dense ops)
    EXPERTS = "experts"
    # fused
    FUSED = "fused"
    # parallel ops (sharding transitions; reference: src/parallel_ops/)
    REPARTITION = "repartition"
    COMBINE = "combine"
    REPLICATE = "replicate"
    REDUCTION = "reduction"
    ALLREDUCE = "allreduce"
    FUSED_PARALLEL = "fused_parallel"
    PIPELINE = "pipeline"


PARALLEL_OP_TYPES = frozenset(
    {
        OpType.REPARTITION,
        OpType.COMBINE,
        OpType.REPLICATE,
        OpType.REDUCTION,
        OpType.ALLREDUCE,
        OpType.FUSED_PARALLEL,
        OpType.PIPELINE,
    }
)
