"""Parallel computation graph (PCG).

TPU-native analog of PCG::Graph (reference: include/flexflow/graph.h:293-377,
src/runtime/graph.cc). Nodes are operator instances (OpType + frozen,
hashable param record); edges carry (src output index, dst input index).
The graph is pure data — hashable, serializable, separable — because the
Unity search memoizes on subgraph hashes (reference: graph.cc:1863
``dp_state_hash``) and the substitution engine rewrites it structurally.

Unlike the reference there is no Legion region attached: physical layout
comes later from a ParallelStrategy (parallel/strategy.py) and XLA GSPMD.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .tensor import TensorSpec
from .types import OpType, PARALLEL_OP_TYPES


@dataclasses.dataclass(frozen=True)
class Node:
    """One operator instance in the PCG (reference: graph.h Node — Op* + guid)."""

    guid: int
    op_type: OpType
    params: Any  # frozen dataclass from ops/<op>.py; hashable
    name: str = ""

    def param_hash(self) -> int:
        """Structural hash ignoring guid (for memoization / dedup)."""
        return hash((self.op_type, self.params))

    def __repr__(self):
        return f"Node({self.guid}:{self.op_type.value}{':' + self.name if self.name else ''})"


@dataclasses.dataclass(frozen=True)
class Edge:
    """Tensor flow edge (reference: graph.h Edge — srcOp/dstOp + srcIdx/dstIdx)."""

    src: int  # producer node guid
    dst: int  # consumer node guid
    src_idx: int = 0  # producer output index
    dst_idx: int = 0  # consumer input index


class PCGraph:
    """Mutable parallel computation graph.

    Reference: PCG::Graph (graph.h:293). Supports the operations the Unity
    search needs: add/remove node+edge, topological order, structural
    hashing, split at a node (graph.h:346 split_at_node), and DOT export.
    """

    _guid_counter = itertools.count(1000)  # guids globally unique, like reference GUIDs

    def __init__(self):
        self.nodes: Dict[int, Node] = {}
        self._in_edges: Dict[int, List[Edge]] = {}
        self._out_edges: Dict[int, List[Edge]] = {}

    # ---------------------------------------------------------------- build
    def new_node(self, op_type: OpType, params: Any, name: str = "") -> Node:
        node = Node(next(PCGraph._guid_counter), op_type, params, name)
        self.add_node(node)
        return node

    def add_node(self, node: Node) -> Node:
        self.nodes[node.guid] = node
        self._in_edges.setdefault(node.guid, [])
        self._out_edges.setdefault(node.guid, [])
        return node

    def add_edge(self, src: Node | int, dst: Node | int, src_idx: int = 0, dst_idx: int = 0):
        s = src.guid if isinstance(src, Node) else src
        d = dst.guid if isinstance(dst, Node) else dst
        if s not in self.nodes or d not in self.nodes:
            raise KeyError(f"edge endpoints must be in graph: {s}->{d}")
        e = Edge(s, d, src_idx, dst_idx)
        self._out_edges[s].append(e)
        self._in_edges[d].append(e)
        return e

    def remove_node(self, guid: int):
        for e in list(self._in_edges.get(guid, [])):
            self._out_edges[e.src].remove(e)
        for e in list(self._out_edges.get(guid, [])):
            self._in_edges[e.dst].remove(e)
        self._in_edges.pop(guid, None)
        self._out_edges.pop(guid, None)
        self.nodes.pop(guid, None)

    def remove_edge(self, e: Edge):
        self._out_edges[e.src].remove(e)
        self._in_edges[e.dst].remove(e)

    def replace_edge_src(self, e: Edge, new_src: Node | int, new_src_idx: int = 0):
        self.remove_edge(e)
        self.add_edge(new_src, e.dst, new_src_idx, e.dst_idx)

    # ---------------------------------------------------------------- query
    def in_edges(self, n: Node | int) -> List[Edge]:
        g = n.guid if isinstance(n, Node) else n
        return sorted(self._in_edges.get(g, []), key=lambda e: e.dst_idx)

    def out_edges(self, n: Node | int) -> List[Edge]:
        g = n.guid if isinstance(n, Node) else n
        return sorted(self._out_edges.get(g, []), key=lambda e: (e.src_idx, e.dst))

    def predecessors(self, n: Node | int) -> List[Node]:
        return [self.nodes[e.src] for e in self.in_edges(n)]

    def successors(self, n: Node | int) -> List[Node]:
        return [self.nodes[e.dst] for e in self.out_edges(n)]

    def source_nodes(self) -> List[Node]:
        return [self.nodes[g] for g in self.nodes if not self._in_edges[g]]

    def sink_nodes(self) -> List[Node]:
        return [self.nodes[g] for g in self.nodes if not self._out_edges[g]]

    def __len__(self):
        return len(self.nodes)

    def __contains__(self, n: Node | int):
        return (n.guid if isinstance(n, Node) else n) in self.nodes

    def topo_order(self) -> List[Node]:
        """Deterministic topological order (stable across runs: by guid)."""
        indeg = {g: len(self._in_edges[g]) for g in self.nodes}
        ready = sorted([g for g, d in indeg.items() if d == 0])
        order: List[Node] = []
        while ready:
            g = ready.pop(0)
            order.append(self.nodes[g])
            nxt = []
            for e in self._out_edges[g]:
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    nxt.append(e.dst)
            ready = sorted(set(ready) | set(nxt))
        if len(order) != len(self.nodes):
            raise ValueError("graph has a cycle")
        return order

    # --------------------------------------------------------------- hashing
    def structural_hash(self) -> int:
        """Guid-independent hash for DP memoization (reference: graph.cc:1863)."""
        order = self.topo_order()
        canon = {n.guid: i for i, n in enumerate(order)}
        node_sig = tuple((canon[n.guid], n.op_type, n.params) for n in order)
        edge_sig = tuple(
            sorted(
                (canon[e.src], canon[e.dst], e.src_idx, e.dst_idx)
                for g in self.nodes
                for e in self._out_edges[g]
            )
        )
        return hash((node_sig, edge_sig))

    # ----------------------------------------------------------------- algos
    def copy(self) -> "PCGraph":
        g = PCGraph()
        g.nodes = dict(self.nodes)
        g._in_edges = {k: list(v) for k, v in self._in_edges.items()}
        g._out_edges = {k: list(v) for k, v in self._out_edges.items()}
        return g

    def subgraph(self, guids: Iterable[int]) -> "PCGraph":
        keep = set(guids)
        g = PCGraph()
        for guid in keep:
            g.add_node(self.nodes[guid])
        for guid in keep:
            for e in self._out_edges[guid]:
                if e.dst in keep:
                    g._out_edges[e.src].append(e)
                    g._in_edges[e.dst].append(e)
        return g

    def split_at_node(self, bottleneck: Node) -> Tuple["PCGraph", "PCGraph"]:
        """Split into (ancestors+node, node+descendants) at a bottleneck.

        Reference: Graph::split_at_node (graph.h:346, graph.cc). The
        bottleneck node appears in both halves (as sink of the first,
        source of the second), mirroring the reference's convention.
        """
        anc = self.ancestors(bottleneck) | {bottleneck.guid}
        first = self.subgraph(anc)
        rest = (set(self.nodes) - anc) | {bottleneck.guid}
        second = self.subgraph(rest)
        return first, second

    def ancestors(self, n: Node | int) -> set:
        g = n.guid if isinstance(n, Node) else n
        seen: set = set()
        stack = [e.src for e in self._in_edges[g]]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(e.src for e in self._in_edges[cur])
        return seen

    def descendants(self, n: Node | int) -> set:
        g = n.guid if isinstance(n, Node) else n
        seen: set = set()
        stack = [e.dst for e in self._out_edges[g]]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(e.dst for e in self._out_edges[cur])
        return seen

    def bottleneck_nodes(self) -> List[Node]:
        """Nodes whose removal separates the graph into before/after.

        Used by the DP search's sequential split
        (reference: SearchHelper::find_optimal_sequence_graph_time graph.cc:115).
        A node is a bottleneck if every other node is either its ancestor
        or its descendant.
        """
        total = set(self.nodes)
        out = []
        for n in self.topo_order():
            anc = self.ancestors(n)
            desc = self.descendants(n)
            if len(anc) + len(desc) + 1 == len(total) and not (anc & desc):
                out.append(n)
        return out

    # ----------------------------------------------------------------- serde
    def to_json(self) -> str:
        order = self.topo_order()
        nodes = []
        for n in order:
            p = dataclasses.asdict(n.params) if dataclasses.is_dataclass(n.params) else n.params
            nodes.append(
                {"guid": n.guid, "op_type": n.op_type.value, "name": n.name, "params": _jsonable(p)}
            )
        edges = [
            dataclasses.asdict(e)
            for g in sorted(self.nodes)
            for e in self._out_edges[g]
        ]
        return json.dumps({"nodes": nodes, "edges": edges}, indent=1)

    @classmethod
    def from_json(cls, text: str) -> "PCGraph":
        """Inverse of to_json: params dataclasses are rebuilt from the op
        registry with enum/tuple fields coerced from their field types
        (enables graph persistence for the serving model repository)."""
        from ..ops.base import get_op_def

        d = json.loads(text)
        g = cls()
        for nd in d["nodes"]:
            op_type = OpType(nd["op_type"])
            params_cls = get_op_def(op_type).params_cls
            raw = nd["params"] or {}
            kwargs = {}
            for f in dataclasses.fields(params_cls):
                if f.name not in raw:
                    continue
                kwargs[f.name] = _coerce_field(f.type, raw[f.name])
            g.add_node(Node(nd["guid"], op_type, params_cls(**kwargs), nd.get("name", "")))
        for e in d["edges"]:
            g.add_edge(e["src"], e["dst"], e.get("src_idx", 0), e.get("dst_idx", 0))
        return g

    def to_dot(self, label_fn: Optional[Callable[[Node], str]] = None) -> str:
        """DOT export (reference: --compgraph export, graph.h:339)."""
        lines = ["digraph PCG {"]
        for n in self.topo_order():
            label = label_fn(n) if label_fn else f"{n.op_type.value}\\n{n.name or n.guid}"
            shape = "ellipse" if n.op_type in PARALLEL_OP_TYPES else "box"
            lines.append(f'  n{n.guid} [label="{label}", shape={shape}];')
        for g in sorted(self.nodes):
            for e in self._out_edges[g]:
                lines.append(f"  n{e.src} -> n{e.dst};")
        lines.append("}")
        return "\n".join(lines)


def _coerce_field(field_type, value):
    """Rebuild a params field from its JSON form using the dataclass's
    resolved type hint: enums from .value, tuples from lists, everything
    else passed through."""
    import enum
    import typing

    if isinstance(field_type, str):
        # ops modules use `from __future__ import annotations`; resolve
        # the string against the core.types namespace
        from . import types as _types

        field_type = getattr(_types, field_type, None) or {
            "int": int, "float": float, "str": str, "bool": bool, "tuple": tuple
        }.get(field_type, None)
    origin = typing.get_origin(field_type)
    if isinstance(field_type, type) and issubclass(field_type, enum.Enum):
        return field_type(value)
    if field_type is tuple or origin is tuple:
        return tuple(
            tuple(v) if isinstance(v, list) else v for v in value
        ) if isinstance(value, list) else value
    if isinstance(value, list):
        return tuple(tuple(v) if isinstance(v, list) else v for v in value)
    return value


def _jsonable(x):
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, (OpType,)):
        return x.value
    if hasattr(x, "value") and isinstance(x, object) and x.__class__.__module__.endswith("types"):
        return getattr(x, "value", str(x))
    if isinstance(x, (int, float, str, bool)) or x is None:
        return x
    return str(x)
