"""Logical and parallel tensor specifications.

TPU-native analog of the reference's Tensor / ParallelTensor split
(reference: include/flexflow/tensor.h, include/flexflow/parallel_tensor.h:36-200).

The reference's ParallelTensor carries, per dimension, a shard ``degree``,
a ``parallel_idx`` into the machine view, and an ``is_replica_dim`` marker,
and owns Legion region handles. Here the same per-dim sharding metadata is
pure data; physical placement is expressed as a mapping from parallel dims
to ``jax.sharding.Mesh`` axes, and XLA (GSPMD) materializes the layout.
Tensors are row-major with dim 0 outermost (NumPy order), unlike the
reference's Legion-style reversed dims.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

from .types import DataType, ParameterSyncOption, ParameterSyncType

MAX_TENSOR_DIM = 6  # parity with reference MAX_TENSOR_DIM


@dataclasses.dataclass(frozen=True)
class ParallelDim:
    """One dimension of a parallel tensor (reference: parallel_tensor.h:36-71).

    size        -- logical (global) extent of this dim
    degree      -- number of shards this dim is split into
    mesh_axis   -- mesh axis name carrying the shards (None = unsharded);
                   replaces the reference's ``parallel_idx`` index into a
                   MachineView
    is_replica  -- replica dim: does not exist in the logical tensor, it
                   encodes pure replication (parallel_tensor.h:70)
    """

    size: int
    degree: int = 1
    mesh_axis: Optional[str] = None
    is_replica: bool = False

    def __post_init__(self):
        if self.size <= 0:
            raise ValueError(f"dim size must be positive, got {self.size}")
        if self.degree <= 0:
            raise ValueError(f"degree must be positive, got {self.degree}")
        if self.size % self.degree != 0:
            raise ValueError(
                f"size {self.size} not divisible by degree {self.degree}"
            )
        if self.is_replica and self.size != self.degree:
            raise ValueError("replica dim must have size == degree")


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Logical (unsharded) tensor: shape + dtype (reference: tensor.h TensorBase)."""

    shape: Tuple[int, ...]
    dtype: DataType = DataType.FLOAT

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(int(s) for s in self.shape))
        if len(self.shape) > MAX_TENSOR_DIM:
            raise ValueError(f"too many dims: {self.shape}")

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def num_elements(self) -> int:
        return math.prod(self.shape) if self.shape else 1

    @property
    def size_bytes(self) -> int:
        return self.num_elements * self.dtype.size_bytes

    def with_shape(self, shape: Sequence[int]) -> "TensorSpec":
        return TensorSpec(tuple(shape), self.dtype)

    def with_dtype(self, dtype: DataType) -> "TensorSpec":
        return TensorSpec(self.shape, dtype)


@dataclasses.dataclass(frozen=True)
class ParallelTensorSpec:
    """Sharded tensor spec: per-dim shard/replica info + gradient sync.

    Reference: ParallelTensorBase (parallel_tensor.h:134-200) including the
    fork's per-parameter ``sync_option`` / ``should_add_barrier``
    (parallel_tensor.h:184-185).
    """

    dims: Tuple[ParallelDim, ...]
    dtype: DataType = DataType.FLOAT
    sync_type: ParameterSyncType = ParameterSyncType.NONE
    sync_option: ParameterSyncOption = ParameterSyncOption.DEFAULT

    @classmethod
    def from_spec(cls, spec: TensorSpec) -> "ParallelTensorSpec":
        return cls(tuple(ParallelDim(s) for s in spec.shape), spec.dtype)

    @property
    def logical_shape(self) -> Tuple[int, ...]:
        return tuple(d.size for d in self.dims if not d.is_replica)

    @property
    def local_shape(self) -> Tuple[int, ...]:
        """Per-shard shape (what one device holds)."""
        return tuple(d.size // d.degree for d in self.dims if not d.is_replica)

    @property
    def total_degree(self) -> int:
        return math.prod(d.degree for d in self.dims)

    @property
    def replica_degree(self) -> int:
        return math.prod(d.degree for d in self.dims if d.is_replica)

    @property
    def logical_spec(self) -> TensorSpec:
        return TensorSpec(self.logical_shape, self.dtype)

    @property
    def num_elements(self) -> int:
        return math.prod(self.logical_shape) if self.logical_shape else 1

    @property
    def size_bytes(self) -> int:
        return self.num_elements * self.dtype.size_bytes

    def get_sharding_tuple(self) -> Tuple[Tuple[Optional[str], ...], ...]:
        """Per-logical-dim mesh axes, in PartitionSpec form."""
        out = []
        for d in self.dims:
            if d.is_replica:
                continue
            out.append((d.mesh_axis,) if d.mesh_axis else ())
        return tuple(out)
