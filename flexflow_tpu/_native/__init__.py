"""ctypes binding to the native ffcore runtime library.

The C API (native/include/ffcore.h) is the TPU-native analog of the
reference's C API (python/flexflow_c.h): there, C wraps the C++ FFModel
for Python cffi; here, C wraps the native search/runtime engine
(taskgraph simulator, machine models, allreduce schedule optimizer,
dataloader kernels) for the Python/JAX host.

Importing this module loads ``libffcore.so`` if present, auto-building
it from native/ with g++ when possible (disable with
FF_NATIVE_DISABLE=1). All consumers treat ImportError / RuntimeError
from here as "use the pure-Python fallback".
"""
from __future__ import annotations

import ctypes
import math
import os
import pathlib
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

_HERE = pathlib.Path(__file__).resolve().parent
_REPO = _HERE.parent.parent
_NATIVE_DIR = _REPO / "native"
_LIB_PATH = _HERE / "libffcore.so"
_SOURCES = [
    _NATIVE_DIR / "src" / "simulator.cc",
    _NATIVE_DIR / "src" / "machine_model.cc",
    _NATIVE_DIR / "src" / "allreduce.cc",
    _NATIVE_DIR / "src" / "dataloader.cc",
    _NATIVE_DIR / "src" / "pcg_search.cc",
    _NATIVE_DIR / "src" / "model_capi.cc",
]
_HEADERS = [
    _NATIVE_DIR / "include" / "ffcore.h",
    _NATIVE_DIR / "src" / "ffcore_internal.h",
]

_build_lock = threading.Lock()


def _needs_build() -> bool:
    if not all(s.exists() for s in _SOURCES):
        return False  # installed without sources: use .so as-is
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    return any(p.stat().st_mtime > lib_mtime for p in _SOURCES + _HEADERS)


def _build() -> None:
    # compile to a per-process temp path, then rename atomically so a
    # concurrent process never dlopens a half-written library
    tmp = _LIB_PATH.with_suffix(f".so.tmp{os.getpid()}")
    import sysconfig

    def cmd_for(sources):
        return [
            os.environ.get("CXX", "g++"),
            "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-I", str(_NATIVE_DIR / "include"),
            # model_capi.cc embeds CPython (reference analog:
            # python/main.cc); symbols resolve from the hosting process
            # or the -lpython of a pure-C embedder, so no -lpython here
            "-I", sysconfig.get_path("include"),
            *[str(s) for s in sources],
            "-o", str(tmp),
        ]

    try:
        try:
            subprocess.run(cmd_for(_SOURCES), check=True, capture_output=True, timeout=120)
        except subprocess.CalledProcessError:
            # no CPython dev headers: drop the embedded-interpreter model
            # C API but keep every other native component (simulator,
            # search, allreduce, dataloader) instead of losing them all
            slim = [s for s in _SOURCES if s.name != "model_capi.cc"]
            subprocess.run(cmd_for(slim), check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    finally:
        if tmp.exists():
            tmp.unlink()


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("FF_NATIVE_DISABLE"):
        return None
    try:
        with _build_lock:
            if _needs_build():
                _build()
        if not _LIB_PATH.exists():
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
    except Exception:
        return None
    # signatures
    lib.ffc_version.restype = ctypes.c_char_p
    lib.ffc_taskgraph_create.restype = ctypes.c_void_p
    lib.ffc_taskgraph_destroy.argtypes = [ctypes.c_void_p]
    lib.ffc_taskgraph_add_tasks.restype = ctypes.c_int64
    lib.ffc_taskgraph_add_tasks.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.ffc_taskgraph_add_deps.restype = ctypes.c_int32
    lib.ffc_taskgraph_add_deps.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ffc_taskgraph_simulate.restype = ctypes.c_double
    lib.ffc_taskgraph_simulate.argtypes = [ctypes.c_void_p]
    lib.ffc_mm_create_simple.restype = ctypes.c_void_p
    lib.ffc_mm_create_simple.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
    ]
    lib.ffc_mm_create_networked.restype = ctypes.c_void_p
    lib.ffc_mm_create_networked.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_int32, ctypes.c_int32,
    ]
    lib.ffc_mm_destroy.argtypes = [ctypes.c_void_p]
    lib.ffc_mm_num_devices.restype = ctypes.c_int32
    lib.ffc_mm_num_devices.argtypes = [ctypes.c_void_p]
    lib.ffc_mm_comm_time.restype = ctypes.c_double
    lib.ffc_mm_comm_time.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_double,
    ]
    lib.ffc_mm_get_routes.restype = ctypes.c_int32
    lib.ffc_mm_get_routes.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.c_int32,
    ]
    lib.ffc_allreduce_simulate.restype = ctypes.c_double
    lib.ffc_allreduce_simulate.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.c_double, ctypes.c_int32,
    ]
    lib.ffc_allreduce_optimize.restype = ctypes.c_int32
    lib.ffc_allreduce_optimize.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.c_double, ctypes.POINTER(ctypes.c_double),
    ]
    lib.ffc_batch_gather.restype = ctypes.c_int32
    lib.ffc_batch_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.ffc_shuffle_indices.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64,
    ]
    lib.ffc_pcg_create.restype = ctypes.c_void_p
    lib.ffc_pcg_destroy.argtypes = [ctypes.c_void_p]
    lib.ffc_pcg_add_op.restype = ctypes.c_int64
    lib.ffc_pcg_add_op.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_double, ctypes.c_char_p,
    ]
    lib.ffc_pcg_add_edge.restype = ctypes.c_int32
    lib.ffc_pcg_add_edge.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.ffc_pcg_set_chip.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_double, ctypes.c_double,
    ]
    lib.ffc_pcg_optimize.restype = ctypes.c_double
    lib.ffc_pcg_optimize.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.ffc_pcg_op_set_parallel_attrs.restype = ctypes.c_int32
    lib.ffc_pcg_op_set_parallel_attrs.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.ffc_pcg_propose_hybrid.restype = ctypes.c_int32
    lib.ffc_pcg_propose_hybrid.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_double,
        ctypes.c_int64, ctypes.c_double, ctypes.c_void_p,
    ]
    lib.ffc_pcg_uniform_best.restype = ctypes.c_double
    lib.ffc_pcg_uniform_best.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    return lib


_lib = _load()

if _lib is None:
    raise ImportError("native ffcore library unavailable")


def version() -> str:
    return _lib.ffc_version().decode()


# ------------------------------------------------------------ simulator


def simulate_taskgraph(tasks) -> float:
    """Native replay of a search/simulator.py TaskManager task list."""
    n = len(tasks)
    kinds = (ctypes.c_int32 * n)(*[t.kind for t in tasks])
    devices = (ctypes.c_int64 * n)(*[t.device for t in tasks])
    run_times = (ctypes.c_double * n)(*[t.run_time for t in tasks])
    srcs: List[int] = []
    dsts: List[int] = []
    for i, t in enumerate(tasks):
        srcs.extend([i] * len(t.next_tasks))
        dsts.extend(t.next_tasks)
    tg = _lib.ffc_taskgraph_create()
    try:
        _lib.ffc_taskgraph_add_tasks(tg, n, kinds, devices, run_times)
        nd = len(srcs)
        if nd:
            csrc = (ctypes.c_int64 * nd)(*srcs)
            cdst = (ctypes.c_int64 * nd)(*dsts)
            if _lib.ffc_taskgraph_add_deps(tg, nd, csrc, cdst) != 0:
                raise RuntimeError("bad dependency ids")
        makespan = _lib.ffc_taskgraph_simulate(tg)
    finally:
        _lib.ffc_taskgraph_destroy(tg)
    if makespan < 0:
        raise ValueError("task graph deadlock")
    return makespan


# --------------------------------------------------------- machine model


class NativeMachineModel:
    """Owns an ffc_mm handle; constructed from the Python machine models."""

    def __init__(self, handle):
        self._h = handle

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and _lib is not None:
            _lib.ffc_mm_destroy(h)

    @classmethod
    def simple(cls, num_nodes: int, devices_per_node: int,
               ici_latency: float, ici_bandwidth: float,
               dcn_latency: float, dcn_bandwidth: float) -> "NativeMachineModel":
        h = _lib.ffc_mm_create_simple(
            num_nodes, devices_per_node,
            ici_latency, ici_bandwidth, dcn_latency, dcn_bandwidth)
        if not h:
            raise RuntimeError("ffc_mm_create_simple failed")
        return cls(h)

    @classmethod
    def networked(cls, num_nodes: int, num_switches: int, devices_per_node: int,
                  conn: Sequence[Sequence[int]], link_latency: float,
                  link_bandwidth: float, ici_latency: float,
                  ici_bandwidth: float, routing: str = "weighted_shortest",
                  ecmp_max_paths: int = 4) -> "NativeMachineModel":
        e = num_nodes + num_switches
        flat = (ctypes.c_int32 * (e * e))(*[conn[i][j] for i in range(e) for j in range(e)])
        rid = {"shortest": 0, "weighted_shortest": 1, "ecmp": 2}.get(routing, 1)
        h = _lib.ffc_mm_create_networked(
            num_nodes, num_switches, devices_per_node, flat,
            link_latency, link_bandwidth, ici_latency, ici_bandwidth,
            rid, ecmp_max_paths)
        if not h:
            raise RuntimeError("ffc_mm_create_networked failed")
        return cls(h)

    @classmethod
    def from_python(cls, mm) -> "NativeMachineModel":
        """Mirror a search/machine_model.py model into the native engine."""
        from ..search.machine_model import NetworkedMachineModel, SimpleMachineModel

        if isinstance(mm, SimpleMachineModel):
            c = mm.machine.chip
            return cls.simple(
                mm.machine.num_nodes, mm.machine.devices_per_node,
                c.ici_latency, c.ici_bandwidth, c.dcn_latency, c.dcn_bandwidth)
        if isinstance(mm, NetworkedMachineModel):
            from ..search.machine_model import (
                ECMPRouting, ShortestPathRouting, WeightedShortestPathRouting)

            topo = mm.topo
            if isinstance(mm.routing, ECMPRouting):
                routing, k = "ecmp", mm.routing.max_paths
            elif isinstance(mm.routing, WeightedShortestPathRouting):
                routing, k = "weighted_shortest", 4
            elif isinstance(mm.routing, ShortestPathRouting):
                routing, k = "shortest", 4
            else:
                raise TypeError(f"unsupported routing {type(mm.routing)}")
            c = mm.machine.chip
            return cls.networked(
                topo.num_nodes, topo.num_switches, topo.devices_per_node,
                topo.conn, topo.link_latency, topo.link_bandwidth,
                c.ici_latency, c.ici_bandwidth, routing, k)
        raise TypeError(f"no native mirror for {type(mm)}")

    def num_devices(self) -> int:
        return _lib.ffc_mm_num_devices(self._h)

    def comm_time(self, src_dev: int, dst_dev: int, nbytes: float) -> float:
        return _lib.ffc_mm_comm_time(self._h, src_dev, dst_dev, nbytes)

    def get_routes(self, src_node: int, dst_node: int,
                   max_paths: int = 8, max_len: int = 64) -> List[List[int]]:
        out = (ctypes.c_int32 * (max_paths * max_len))()
        lens = (ctypes.c_int32 * max_paths)()
        np_ = _lib.ffc_mm_get_routes(self._h, src_node, dst_node, out, lens,
                                     max_paths, max_len)
        if np_ < 0:
            raise RuntimeError("not a networked machine model")
        return [[out[p * max_len + i] for i in range(lens[p])] for p in range(np_)]

    # ------------------------------------------------------- allreduce
    _PATTERN_IDS = {"ring": 0, "butterfly": 1, "double_binary_tree": 2}

    def allreduce_time(self, participants: Sequence[int], nbytes: float,
                       pattern: str) -> float:
        n = len(participants)
        parts = (ctypes.c_int32 * n)(*participants)
        t = _lib.ffc_allreduce_simulate(
            self._h, parts, n, nbytes, self._PATTERN_IDS[pattern])
        if t < 0:
            raise ValueError(f"bad pattern {pattern}")
        return t

    def allreduce_optimize(self, participants: Sequence[int],
                           nbytes: float) -> Tuple[str, dict]:
        n = len(participants)
        parts = (ctypes.c_int32 * n)(*participants)
        times = (ctypes.c_double * 3)()
        best = _lib.ffc_allreduce_optimize(self._h, parts, n, nbytes, times)
        names = ["ring", "butterfly", "double_binary_tree"]
        return names[best], dict(zip(names, list(times)))


# ------------------------------------------------------------ dataloader


def batch_gather(src, dst, indices, num_threads: int = 0) -> None:
    """dst[i] = src[indices[i]] row gather via the native threaded kernel.

    src/dst are C-contiguous numpy arrays whose first axis is the row
    axis; dst must have len(indices) rows.
    """
    import numpy as np

    src = np.ascontiguousarray(src)
    if not dst.flags["C_CONTIGUOUS"]:
        raise ValueError("dst must be C-contiguous")
    n = len(indices)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if dst.shape[0] != n or dst.dtype != src.dtype or dst.shape[1:] != src.shape[1:]:
        raise ValueError("dst shape/dtype mismatch")
    idx = np.ascontiguousarray(np.asarray(indices, dtype=np.int64))
    if n and (idx.min() < 0 or idx.max() >= src.shape[0]):
        raise IndexError("gather index out of range")
    rc = _lib.ffc_batch_gather(
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, row_bytes, num_threads)
    if rc != 0:
        raise RuntimeError("ffc_batch_gather failed")


def shuffle_indices(n: int, seed: int):
    """Deterministic permutation of range(n) from the native shuffler."""
    import numpy as np

    idx = np.arange(n, dtype=np.int64)
    _lib.ffc_shuffle_indices(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, seed)
    return idx


# ------------------------------------------------------------ pcg search


class NativePcg:
    """Native PCG + DP view-assignment search (reference: the C API
    python/flexflow_c.h exposing the model/search engine; ffc_pcg_*).

    Ops are added in topological order with cost primitives; optimize()
    returns (best simulated step seconds, per-op shard degrees).
    """

    def __init__(self):
        self._h = _lib.ffc_pcg_create()
        self._n = 0

    def __del__(self):
        if getattr(self, "_h", None):
            _lib.ffc_pcg_destroy(self._h)
            self._h = None

    def add_op(self, flops: float, bytes_: float, weight_bytes: float = 0.0,
               output_bytes: float = 0.0, name: str = "") -> int:
        self._n += 1
        return _lib.ffc_pcg_add_op(
            self._h, float(flops), float(bytes_), float(weight_bytes),
            float(output_bytes), name.encode())

    def add_edge(self, src: int, dst: int) -> None:
        if _lib.ffc_pcg_add_edge(self._h, src, dst) != 0:
            raise ValueError(f"bad edge {src}->{dst}")

    def set_chip(self, peak_flops: float, mxu_eff: float = 0.55,
                 hbm_bandwidth: float = 0.82e12, hbm_eff: float = 0.8,
                 per_op_overhead: float = 2e-6) -> None:
        _lib.ffc_pcg_set_chip(self._h, peak_flops, mxu_eff, hbm_bandwidth,
                              hbm_eff, per_op_overhead)

    def optimize(self, machine_model, batch: int = 0, max_degree: int = 0):
        out = (ctypes.c_int32 * self._n)()
        cost = _lib.ffc_pcg_optimize(
            self._h, machine_model._h, batch, max_degree, out)
        return cost, list(out)

    def uniform_best(self, machine_model, batch: int = 0, max_degree: int = 0):
        """(cost, degree) of the best SHARED degree — the DP leaf scan
        (dp_search.py _leaf_cost) as a native fast path."""
        out = ctypes.c_int32(1)
        cost = _lib.ffc_pcg_uniform_best(
            self._h, machine_model._h, batch, max_degree, ctypes.byref(out))
        return cost, int(out.value)

    def set_parallel_attrs(self, op: int, repeat_idx: int = -1,
                           is_attention: bool = False,
                           tp_shardable_bytes: float = 0.0,
                           tp_dim_size: int = 0,
                           pipe_tp_ok: bool = True) -> None:
        """Structural attributes for hybrid candidates (which repeated
        block the op belongs to, ring-attention capability, Megatron-
        shardable weight inventory; pipe_tp_ok = the conservative
        in-stage tp lowering can shard this op's weights)."""
        if _lib.ffc_pcg_op_set_parallel_attrs(
            self._h, op, repeat_idx, int(bool(is_attention)),
            float(tp_shardable_bytes), int(tp_dim_size),
            int(bool(pipe_tp_ok)),
        ) != 0:
            raise ValueError(f"bad op id {op}")

    def propose_hybrid(self, machine_model, batch: int,
                       boundary_bytes: float = 0.0, seq_len: int = 0,
                       capacity: float = 0.0) -> dict:
        """Hybrid winner across dp / pipeline / context-parallel
        candidates with divisor-degree sweeps — the native mirror of
        unity.py's proposers + feasible-cheapest-first walk (reference:
        one search engine behind every API entry, graph.cc:2047)."""
        class _Hybrid(ctypes.Structure):
            _fields_ = [
                ("kind", ctypes.c_int32), ("dp", ctypes.c_int32),
                ("pp", ctypes.c_int32), ("tp", ctypes.c_int32),
                ("cp", ctypes.c_int32), ("n_microbatches", ctypes.c_int32),
                ("cost", ctypes.c_double), ("mem_per_device", ctypes.c_double),
            ]

        out = _Hybrid()
        rc = _lib.ffc_pcg_propose_hybrid(
            self._h, machine_model._h, batch, float(boundary_bytes),
            int(seq_len), float(capacity), ctypes.byref(out))
        if rc != 0:
            raise RuntimeError("ffc_pcg_propose_hybrid failed")
        return {
            "kind": ("dp", "pipeline", "cp")[out.kind],
            "dp": out.dp, "pp": out.pp, "tp": out.tp, "cp": out.cp,
            "n_microbatches": out.n_microbatches,
            "cost": out.cost, "mem_per_device": out.mem_per_device,
        }


def _pipeline_repeats(graph, specs, batch=None):
    """Repeat structure the GPipe executor could actually RUN, plus the
    boundary bytes — mirrors _propose_pipeline's legality rejections
    (unity.py): no stateful / aux-loss ops inside the stack, and every
    carry entry microbatchable (leading dim == batch, when known).
    Returns ([], 0.0) when the graph has no runnable pipelined form."""
    from ..core.types import OpType

    try:
        from ..parallel.pipeline import boundary_structure, detect_repeats

        _, repeats, _ = detect_repeats(graph)
        if len(repeats) < 2:
            return [], 0.0
        for rep in repeats:
            for node in rep:
                if node.op_type == OpType.BATCHNORM:
                    return [], 0.0
                if node.op_type in (OpType.AGGREGATE, OpType.AGGREGATE_SPEC) and getattr(
                    node.params, "lambda_bal", 0.0
                ) > 0.0:
                    return [], 0.0
        rotating_in, shared, _ = boundary_structure(graph, repeats)
        if batch is not None:
            for g, i in rotating_in + shared:
                shape = specs[g][i].shape
                if not shape or shape[0] != batch:
                    return [], 0.0
        boundary = sum(specs[g][i].size_bytes for g, i in rotating_in + shared)
        return repeats, boundary
    except Exception:
        return [], 0.0


def pcg_from_graph(graph, machine=None, batch=None, specs=None, repeats=None):
    """Build a NativePcg from a flexflow_tpu PCGraph using the op
    library's cost() (the host supplies the op math; the native engine
    searches). Structural attrs for the hybrid proposer are tagged in
    the same pass; pass ``batch`` to restrict repeat tagging to
    executor-legal pipelines."""
    from ..core.types import OpType, PARALLEL_OP_TYPES
    from ..ops.base import get_op_def
    from ..parallel.propagation import infer_all_specs
    from ..parallel.strategy import megatron_weight_dims, tp_shardable_nodes

    pcg = NativePcg()
    if machine is not None:
        chip = machine.chip
        pcg.set_chip(chip.bf16_flops, 0.55, chip.hbm_bandwidth, 0.8, 2e-6)
    if specs is None:
        specs = infer_all_specs(graph)
    if repeats is None:
        repeats, _ = _pipeline_repeats(graph, specs, batch)
    rep_idx = {n.guid: ri for ri, rep in enumerate(repeats) for n in rep}
    # pipeline tp legality is the CONSERVATIVE set pipeline_strategy can
    # shard (complete column->row pairs) — computed for EVERY repeat
    # instance (each block holds distinct nodes), so the native sharded
    # inventory matches unity's block_sharded_bytes * R, not 1/R of it.
    # For outer ops (cp x tp is GSPMD territory) the full megatron name
    # set applies.
    shardable_block = set()
    for rep in repeats:
        shardable_block |= tp_shardable_nodes(graph, rep)
    idx = {}
    for node in graph.topo_order():
        in_specs = [specs[e.src][e.src_idx] for e in graph.in_edges(node)]
        out_specs = specs[node.guid]
        flops = bytes_ = wbytes = 0.0
        wspecs = []
        if node.op_type not in PARALLEL_OP_TYPES and node.op_type not in (
            OpType.INPUT, OpType.WEIGHT, OpType.NOOP
        ):
            op_def = get_op_def(node.op_type)
            c = op_def.cost(node.params, in_specs, out_specs)
            flops, bytes_ = c.flops, c.bytes_accessed
            try:
                wspecs = op_def.weight_specs(node.params, in_specs)
            except Exception:
                wspecs = []
            wbytes = sum(w.spec.size_bytes for w in wspecs)
        out_bytes = sum(s.size_bytes for s in out_specs)
        op = pcg.add_op(flops, bytes_, wbytes, out_bytes, node.name)
        idx[node.guid] = op

        shard_b, dim_sz = 0.0, 0
        wdims = megatron_weight_dims(node)
        if wdims:
            by_name = {w.name: w.spec for w in wspecs}
            sizes = [
                (by_name[wn].shape[dim], by_name[wn].size_bytes)
                for wn, dim in wdims.items()
                if wn in by_name
            ]
            shard_b = sum(b for _, b in sizes)
            # tp divides the op iff it divides every shardable dim —
            # equivalently iff it divides their gcd
            dim_sz = math.gcd(*[int(s) for s, _ in sizes]) if sizes else 0
        pcg.set_parallel_attrs(
            op,
            repeat_idx=rep_idx.get(node.guid, -1),
            is_attention=(node.op_type == OpType.MULTIHEAD_ATTENTION),
            tp_shardable_bytes=shard_b,
            tp_dim_size=dim_sz,
            pipe_tp_ok=(node.guid not in rep_idx or node.guid in shardable_block),
        )
    for node in graph.topo_order():
        for e in graph.in_edges(node):
            pcg.add_edge(idx[e.src], idx[e.dst])
    return pcg, idx


def native_hybrid_search(graph, machine, batch: int, capacity: float = 0.0):
    """Run the NATIVE hybrid proposer (dp / pipeline / cp winner walk)
    on a flexflow_tpu PCGraph — the ffcore.h path to the same candidate
    families unity.py proposes (VERDICT r4 missing #4: the C search must
    not be strictly weaker than the Python one). Returns the winner dict
    from NativePcg.propose_hybrid."""
    from ..core.types import OpType
    from ..parallel.propagation import infer_all_specs

    specs = infer_all_specs(graph)
    # ONE repeat/boundary analysis shared with pcg_from_graph
    repeats, boundary = _pipeline_repeats(graph, specs, batch)
    pcg, _ = pcg_from_graph(graph, machine, batch=batch, specs=specs,
                            repeats=repeats)
    # block attention sequence length ([B, S, E] convention)
    seq_len = 0
    for node in graph.topo_order():
        if node.op_type == OpType.MULTIHEAD_ATTENTION:
            a_in = [specs[e.src][e.src_idx] for e in graph.in_edges(node)]
            if a_in and a_in[0].ndim == 3:
                seq_len = a_in[0].shape[1]
            break
    chip = machine.chip
    mm = NativeMachineModel.simple(
        machine.num_nodes, machine.devices_per_node,
        chip.ici_latency, chip.ici_bandwidth,
        chip.dcn_latency, chip.dcn_bandwidth,
    )
    return pcg.propose_hybrid(
        mm, batch, boundary_bytes=boundary, seq_len=seq_len,
        capacity=capacity,
    )
