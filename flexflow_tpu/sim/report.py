"""Per-run report for the fleet digital twin, plus the honesty hooks.

The report answers the capacity questions (TTFT/TPOT percentiles,
goodput, shed rates per priority, overload activations, autoscale
signal trace) and carries the determinism fingerprint (event count +
trace digest). The honesty hooks close the loop with the PR 7 truth
telemetry: :meth:`SimReport.register_predictions` writes the twin's
latency percentiles into a PredictionLedger under ``sim:`` keys with
sim provenance, and :func:`measure_live` pairs them with a live run's
measurements — so a lying twin shows up on ``GET
/v2/debug/predictions`` (and in drift alarms) exactly like a lying
roofline.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

from .costs import SimCosts
from .events import EventLoop
from .virtual import SimRequest, VirtualFleet

SIM_PROVENANCE = "fleet digital twin (discrete-event sim)"
PRIORITIES = ("interactive", "standard", "best_effort")
# the percentile keys the honesty loop pairs between sim and live runs
METRIC_KEYS = ("ttft_p50_s", "ttft_p95_s", "ttft_p99_s", "tpot_p50_s")


def _pct(xs: Sequence[float], p: float) -> Optional[float]:
    # nearest-rank, the repo-wide percentile rule (serving.stats /
    # loadgen agree), so sim and live percentiles are comparable
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, math.ceil(p * len(xs)) - 1)]


class SimReport:
    """One simulated scenario's outcome."""

    def __init__(
        self,
        *,
        requests: List[SimRequest],
        fleet: VirtualFleet,
        loop: EventLoop,
        costs: SimCosts,
        duration_s: float,
        scenario: Optional[Dict] = None,
    ):
        self.requests = requests
        self.fleet = fleet
        self.loop = loop
        self.costs = costs
        self.duration_s = float(duration_s)
        self.scenario = dict(scenario or {})

    # ------------------------------------------------------------- metrics
    def completed(self) -> List[SimRequest]:
        return [r for r in self.requests if r.outcome == "completed"]

    def ttft_values(self) -> List[float]:
        return [r.ttft_s() for r in self.completed() if r.ttft_s() is not None]

    def tpot_values(self) -> List[float]:
        return [r.tpot_s() for r in self.completed() if r.tpot_s() is not None]

    def metrics(self) -> Dict[str, Optional[float]]:
        ttfts = self.ttft_values()
        return {
            "ttft_p50_s": _pct(ttfts, 0.50),
            "ttft_p95_s": _pct(ttfts, 0.95),
            "ttft_p99_s": _pct(ttfts, 0.99),
            "tpot_p50_s": _pct(self.tpot_values(), 0.50),
        }

    def shed_rate(self) -> float:
        n = len(self.requests)
        return (
            sum(1 for r in self.requests if r.outcome == "shed") / n
            if n else 0.0
        )

    def render(self) -> Dict:
        per: Dict[str, Dict] = {}
        for p in PRIORITIES:
            rs = [r for r in self.requests if r.priority == p]
            ttfts = [
                r.ttft_s() for r in rs
                if r.outcome == "completed" and r.ttft_s() is not None
            ]
            per[p] = {
                "submitted": len(rs),
                "completed": sum(1 for r in rs if r.outcome == "completed"),
                "shed": sum(1 for r in rs if r.outcome == "shed"),
                "expired": sum(1 for r in rs if r.outcome == "expired"),
                "failed": sum(1 for r in rs if r.outcome == "failed"),
                "tokens": sum(r.tokens for r in rs),
                "ttft_p50_s": _pct(ttfts, 0.50),
                "ttft_p95_s": _pct(ttfts, 0.95),
            }
        tokens = sum(r.tokens for r in self.requests)
        good = sum(r.tokens for r in self.completed())
        makespan = max(
            [r.t_finish for r in self.requests if r.t_finish is not None]
            or [self.duration_s]
        )
        out = {
            "mode": "sim",
            "arm": self.fleet.arm,
            "engines": self.fleet.engines(),
            "duration_s": self.duration_s,
            "makespan_s": makespan,
            "submitted": len(self.requests),
            "completed": len(self.completed()),
            "shed_rate": self.shed_rate(),
            "tokens_per_s": tokens / max(1e-9, self.duration_s),
            "goodput_tokens_per_s": good / max(1e-9, self.duration_s),
            "per_priority": per,
            "overload": self.fleet.activations(),
            "autoscale": self.fleet.autoscale_summary(),
            "costs": self.costs.describe(),
            "events": self.loop.events_run,
            "trace_digest": self.loop.trace_digest(),
        }
        out.update(self.metrics())
        if self.scenario:
            out["scenario"] = self.scenario
        return out

    # ------------------------------------------------------------- honesty
    def register_predictions(self, ledger, *, prefix: str,
                             alarm: bool = True) -> List[str]:
        """Write the twin's percentile predictions into ``ledger``
        under ``sim:{prefix}:{metric}`` with sim provenance; a live
        replay of the same scenario then :func:`measure_live`-pairs
        them, and drift telemetry flags a lying twin. Returns the keys
        registered."""
        keys: List[str] = []
        for metric, value in self.metrics().items():
            if value is None:
                continue
            key = f"sim:{prefix}:{metric}"
            ledger.predict(
                key, value,
                label=f"sim {self.fleet.arm} {metric}",
                provenance=SIM_PROVENANCE,
                alarm=alarm,
            )
            keys.append(key)
        return keys


def measure_live(ledger, *, prefix: str,
                 live_metrics: Dict[str, Optional[float]]) -> List[str]:
    """Pair a live run's measured percentiles with the twin's
    registered ``sim:`` predictions (keys that were never predicted
    are skipped — the ledger would count them as unpredicted work,
    which is drift noise, not twin error)."""
    keys: List[str] = []
    for metric in METRIC_KEYS:
        value = live_metrics.get(metric)
        if value is None:
            continue
        key = f"sim:{prefix}:{metric}"
        ledger.measure(key, value)
        keys.append(key)
    return keys
