"""Discrete-event core for the fleet digital twin: a virtual clock and
a deterministic (time, seq) event heap.

Determinism is the whole point — two runs of the same schedule + seed
must produce byte-identical event traces, so capacity answers are
reviewable artifacts rather than measurements. Three rules make it so:

* time is virtual: the clock only moves when the loop dispatches an
  event (flexlint forbids every real clock in this package, including
  ``perf_counter``);
* ties are broken by a monotone sequence number, so same-instant
  events dispatch in scheduling order, never hash or heap order;
* every dispatched event is appended to ``trace`` and folded into a
  SHA-256 ``trace_digest`` — the identity tests and the ``simfleet``
  report pin.
"""
from __future__ import annotations

import hashlib
import heapq
from typing import Callable, List, Optional, Tuple


class SimClock:
    """Callable virtual clock (the same read interface as the
    injectable ``time.monotonic``-shaped clocks the serving stack
    already takes), advanced only by the event loop."""

    __slots__ = ("_t",)

    def __init__(self, t0: float = 0.0):
        self._t = float(t0)

    def __call__(self) -> float:
        return self._t

    def _advance_to(self, t: float) -> None:
        if t < self._t - 1e-12:
            raise ValueError(
                f"virtual time cannot run backwards ({t} < {self._t})"
            )
        self._t = max(self._t, float(t))


class EventLoop:
    """Deterministic event heap over a :class:`SimClock`.

    ``at(t, kind, fn)`` / ``after(delay, kind, fn)`` schedule
    ``fn(t)``; ``run()`` dispatches in (time, seq) order until the heap
    drains. ``detail`` strings join the trace so digests distinguish
    e.g. which request an event belonged to.
    """

    def __init__(self, clock: Optional[SimClock] = None):
        self.clock = clock or SimClock()
        self._heap: List[Tuple[float, int, str, str, Callable]] = []
        self._seq = 0
        self.events_run = 0
        self.trace: List[Tuple[float, int, str, str]] = []

    def at(self, t: float, kind: str, fn: Callable[[float], None],
           detail: str = "") -> int:
        if t < self.clock() - 1e-12:
            raise ValueError(
                f"cannot schedule {kind!r} in the past "
                f"({t} < {self.clock()})"
            )
        self._seq += 1
        heapq.heappush(self._heap, (float(t), self._seq, kind, detail, fn))
        return self._seq

    def after(self, delay: float, kind: str, fn: Callable[[float], None],
              detail: str = "") -> int:
        return self.at(self.clock() + max(0.0, float(delay)), kind, fn, detail)

    def run(self, until: Optional[float] = None,
            max_events: int = 2_000_000) -> int:
        """Dispatch until the heap drains (or ``until``); returns the
        number of events run. ``max_events`` is a runaway backstop — a
        zero-cost iteration loop would otherwise spin forever at one
        virtual instant."""
        while self._heap:
            t = self._heap[0][0]
            if until is not None and t > until:
                break
            t, seq, kind, detail, fn = heapq.heappop(self._heap)
            self.clock._advance_to(t)
            self.events_run += 1
            if self.events_run > max_events:
                raise RuntimeError(
                    f"simulation exceeded {max_events} events at t={t}; "
                    "a zero-duration iteration is likely looping"
                )
            self.trace.append((round(t, 9), seq, kind, detail))
            fn(t)
        return self.events_run

    def trace_digest(self) -> str:
        """SHA-256 over the dispatched-event trace — the determinism
        fingerprint two runs of the same scenario must share."""
        h = hashlib.sha256()
        for entry in self.trace:
            h.update(repr(entry).encode())
        return h.hexdigest()
