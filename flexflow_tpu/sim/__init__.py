"""Fleet digital twin: a deterministic discrete-event simulator for
the serving tier (ISSUE 17 / ROADMAP item 5).

The FlexFlow papers' defining move is simulator-guided optimization:
search a configuration space against a simulated execution timeline
built from a calibrated machine model, then deploy the winner. This
package applies that move to the *fleet* layer: it replays a
``tools/loadgen.py`` arrival schedule against a virtual fleet —
replicas, KV-block pools, priority queues, the PR 14 AIMD limiter /
degrade ladder / autoscale advisor (the REAL control classes, run on
virtual time), and the PR 16 prefill/decode pools with block handoffs
— whose per-step costs come from the calibrated serving roofline and
the PredictionLedger, never from wall clocks.

Honesty loop: a simulated scenario that also ran live registers its
latency predictions in the ledger under ``sim:`` keys, so the PR 7
drift telemetry (and the ``simcheck`` CI gate) flags a lying twin the
same way it flags a lying roofline.

Modules:

* :mod:`events`  — the DES core: virtual clock + (time, seq) event
  heap + replayable trace digest. Purely virtual time (flexlint
  forbids ALL real clocks under ``flexflow_tpu/sim/``).
* :mod:`costs`   — where step durations come from: a ledger export
  (``tools/obsreport.py predict --export``, cross-device loads
  refused), the serving roofline, or a fixed per-iteration tick that
  mirrors ``loadgen.drive_virtual`` for sim-vs-live gating.
* :mod:`virtual` — the virtual fleet: replicas that mirror the
  continuous-batching scheduler's iteration shape and reuse the real
  ``OverloadController`` / ``AutoscaleAdvisor``.
* :mod:`report`  — per-run percentiles/goodput/shed report + the
  ``sim:`` ledger registration.
* :mod:`sweep`   — scenario sweeps with ranked configurations.
"""
from .costs import SimCosts
from .events import EventLoop, SimClock
from .report import SimReport
from .sweep import Scenario, run_scenario, scale_schedule, sweep
from .virtual import SimRequest, VirtualFleet, VirtualReplica

__all__ = [
    "EventLoop",
    "SimClock",
    "SimCosts",
    "SimReport",
    "SimRequest",
    "Scenario",
    "VirtualFleet",
    "VirtualReplica",
    "run_scenario",
    "scale_schedule",
    "sweep",
]
