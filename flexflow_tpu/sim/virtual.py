"""The virtual fleet: replicas that mirror the continuous-batching
scheduler's iteration shape, driven by the discrete-event loop.

Fidelity choices, in order of importance:

* **The control plane is real, not modeled.** Each virtual replica
  instantiates the actual :class:`~flexflow_tpu.serving.overload.
  OverloadController` (AIMD limiter + degrade ladder) on the sim
  clock, fed by the same signal shapes the live scheduler wires in
  (queue depth, rolling queue-time/TTFT p95 windows from
  ``serving.stats.LatencyWindow``, KV-pool pressure, the roofline TTFT
  predictor). Threshold sweeps therefore exercise the exact code that
  will run in production, at virtual speed.
* **The iteration mirrors ``_step_impl``**: expire, then admit as many
  queued requests as fit this iteration (each admission is one
  prefill, and the prefill emits the first token), then ONE decode
  step that emits one token for every active stream — including the
  just-admitted ones, which is why a unified replica's TTFT couples to
  its decode cost and a dedicated prefill replica's does not (the
  PR 16 disagg win the twin must reproduce).
* **Two time models** (:class:`~flexflow_tpu.sim.costs.SimCosts`):
  cost mode prices each iteration from the table and runs replicas as
  busy/idle event chains; tick mode replays ``loadgen.drive_virtual``
  exactly — one iteration per fixed ``dt`` with effects stamped at the
  tick — so the simcheck gate compares like with like. Tick mode also
  models the live scheduler's overlapped decode (ISSUE 13, on by
  default): steady-state iterations keep one decode step in flight
  (dispatch N+1, consume N), so the first iteration after any drain
  event — an admission, a finish, an expiry — is a refill bubble that
  emits no tokens, and the drain iteration itself consumes the
  in-flight step on top of its sequential decode. Without this the
  twin services ~20% faster than the engine it claims to mirror and
  the simcheck divergence gate catches it.

Simplifications (documented, not hidden): deadline expiry covers
queued requests only (the live reaper also kills running streams);
speculation is not simulated (ladder levels 1-2 are QoS no-ops here);
KV blocks are reserved conservatively for prompt + max_new at
admission, the scheduler's worst-case envelope.
"""
from __future__ import annotations

import bisect
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..serving.overload import (
    AutoscaleAdvisor,
    OverloadConfig,
    OverloadController,
    Priority,
)
from ..serving.stats import LatencyWindow
from .costs import SimCosts
from .events import EventLoop

# free-fraction floor below which the virtual KV pool reads as "under
# pressure" — obs.capacity.CacheTelemetry's default pressure_threshold
CACHE_PRESSURE_FRAC = 0.10


class SimRequest:
    """One simulated request: the arrival spec plus its lifecycle
    timestamps. ``outcome`` lands in {completed, shed, expired,
    failed}; a shed also records which gate refused it."""

    __slots__ = (
        "rid", "seq", "t", "priority", "prompt_len", "max_new",
        "deadline_s", "t_submit", "t_first_token", "t_finish", "tokens",
        "blocks", "outcome", "shed_reason", "replica", "decode_replica",
    )

    def __init__(self, *, rid: str, seq: int, t: float, priority: str,
                 prompt_len: int, max_new: int,
                 deadline_s: Optional[float] = None):
        self.rid = rid
        self.seq = seq
        self.t = float(t)
        self.priority = Priority.parse(priority)
        self.prompt_len = int(prompt_len)
        self.max_new = max(1, int(max_new))
        self.deadline_s = deadline_s
        self.t_submit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_finish: Optional[float] = None
        self.tokens = 0
        self.blocks = 0
        self.outcome: Optional[str] = None
        self.shed_reason: Optional[str] = None
        self.replica: Optional[str] = None
        self.decode_replica: Optional[str] = None

    @classmethod
    def from_arrival(cls, a, seq: int) -> "SimRequest":
        """Adapt a ``tools/loadgen.py`` Arrival (or any mapping /
        object with t, priority, prompt|prompt_len, max_new,
        deadline_s) without importing the tools package."""
        get = (lambda k, d=None: a.get(k, d)) if isinstance(a, dict) \
            else (lambda k, d=None: getattr(a, k, d))
        prompt = get("prompt")
        prompt_len = len(prompt) if prompt is not None else int(get("prompt_len", 1))
        return cls(
            rid=f"sim-{seq}", seq=seq, t=float(get("t", 0.0)),
            priority=get("priority", Priority.STANDARD),
            prompt_len=prompt_len, max_new=int(get("max_new", 1)),
            deadline_s=get("deadline_s"),
        )

    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None or self.t_submit is None:
            return None
        return self.t_first_token - self.t_submit

    def tpot_s(self) -> Optional[float]:
        if (self.t_finish is None or self.t_first_token is None
                or self.tokens <= 1):
            return None
        return (self.t_finish - self.t_first_token) / (self.tokens - 1)


class BlockPool:
    """The virtual KV-block pool: conservative whole-request
    reservations against a fixed block budget, with the cache-pressure
    read the limiter consumes."""

    def __init__(self, num_blocks: int, block_size: int):
        self.num_blocks = max(1, int(num_blocks))
        self.block_size = max(1, int(block_size))
        self.used = 0

    def blocks_for(self, tokens: int) -> int:
        return max(1, -(-max(1, tokens) // self.block_size))

    def can_alloc(self, n: int) -> bool:
        return self.used + n <= self.num_blocks

    def alloc(self, n: int) -> None:
        if not self.can_alloc(n):
            raise RuntimeError(
                f"virtual block pool overcommitted ({self.used}+{n} > "
                f"{self.num_blocks})"
            )
        self.used += n

    def free(self, n: int) -> None:
        self.used = max(0, self.used - n)

    @property
    def free_fraction(self) -> float:
        return 1.0 - self.used / self.num_blocks

    @property
    def under_pressure(self) -> bool:
        return self.free_fraction <= CACHE_PRESSURE_FRAC


class VirtualReplica:
    """One replica of the twin. ``role`` is "unified" (admit +
    decode), "prefill" (admit, emit first token, hand off), or
    "decode" (adopt handed-off streams, decode only)."""

    def __init__(
        self,
        name: str,
        *,
        loop: EventLoop,
        costs: SimCosts,
        slots: int,
        max_queue: int,
        num_blocks: int,
        block_size: int = 8,
        role: str = "unified",
        index: int = 0,
        overload: Optional[OverloadConfig] = None,
        handoff_sink: Optional[Callable] = None,
        on_terminal: Optional[Callable] = None,
    ):
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        self.name = name
        self.loop = loop
        self.costs = costs
        self.slots = max(1, int(slots))
        self.role = role
        self.index = index
        self.pool = BlockPool(num_blocks, block_size)
        self.queue: List[Tuple[int, int, SimRequest]] = []
        self.imported: deque = deque()
        self.running: List[SimRequest] = []
        self.handoff_sink = handoff_sink
        self.on_terminal = on_terminal or (lambda req: None)
        self._busy = False
        # tick mode's overlap-pipeline frontier: the decode step that
        # has been dispatched but not yet consumed (None = drained)
        self._pipe: Optional[List[SimRequest]] = None
        self.iterations = 0
        self._queue_w = LatencyWindow(512)
        self._ttft_w = LatencyWindow(512)
        self.ctl = OverloadController(
            clock=loop.clock,
            slots=self.slots,
            max_queue=max_queue,
            queue_depth=lambda: len(self.queue) + len(self.imported),
            queue_p95=lambda: self._queue_w.snapshot()["p95_s"],
            ttft_p95=lambda: self._ttft_w.snapshot()["p95_s"],
            cache_pressure=lambda: self.pool.under_pressure,
            # the live scheduler's roofline TTFT predictor shape:
            # (queue ahead + me) prefills back to back
            ttft_predictor=lambda n, depth: (depth + 1) * costs.prefill(n),
            config=overload,
        )

    # ------------------------------------------------------------- routing
    def load(self) -> int:
        return self.ctl.limiter.inflight

    def would_admit(self, priority: str) -> bool:
        return self.ctl.would_admit(priority)

    # -------------------------------------------------------------- submit
    def submit(self, req: SimRequest, now: float) -> bool:
        """Mirror of ``ContinuousBatchingScheduler.submit``'s overload
        gate order: degraded refusal, roofline infeasibility,
        queue-full displacement, AIMD limiter, then enqueue."""
        ctl = self.ctl
        req.t_submit = now
        req.replica = self.name
        if ctl.degraded_reject(req.priority):
            return self._refuse(req, "degraded", now)
        if ctl.infeasible(req.prompt_len, req.deadline_s) is not None:
            ctl.note_rejection("infeasible", req.priority)
            req.outcome = "shed"
            req.shed_reason = "infeasible"
            req.t_finish = now
            return False
        if self.pool.blocks_for(req.prompt_len + req.max_new) > self.pool.num_blocks:
            # can never fit this pool, no matter how long it waits
            ctl.note_rejection("infeasible", req.priority)
            req.outcome = "shed"
            req.shed_reason = "infeasible"
            req.t_finish = now
            return False
        if len(self.queue) >= ctl.max_queue:
            victim = self._displacement_victim(req)
            if victim is not None and ctl.limiter.can_admit(req.priority, freed=1):
                self._shed_queued(victim, now, reason="queue_full")
            else:
                return self._refuse(req, "queue_full", now)
        if not ctl.limiter.try_acquire(req.priority):
            ctl.note_rejection("limiter", req.priority)
            req.outcome = "shed"
            req.shed_reason = "limiter"
            req.t_finish = now
            return False
        cap = ctl.max_new_cap(req.priority)
        if cap is not None:
            req.max_new = max(1, min(req.max_new, cap))
        bisect.insort(self.queue, (Priority.rank(req.priority), req.seq, req))
        if self.costs.tick_s is None:
            self._kick()
        return True

    def _refuse(self, req: SimRequest, reason: str, now: float) -> bool:
        self.ctl.note_rejection(reason, req.priority)
        req.outcome = "shed"
        req.shed_reason = reason
        req.t_finish = now
        return False

    def _displacement_victim(self, req: SimRequest) -> Optional[SimRequest]:
        """The youngest queued request of the lowest class strictly
        below the newcomer's, else None (spill/refuse instead)."""
        rank = Priority.rank(req.priority)
        best = None
        for r, seq, queued in self.queue:
            if r > rank and (best is None or (r, seq) > best[:2]):
                best = (r, seq, queued)
        return best[2] if best else None

    def _shed_queued(self, victim: SimRequest, now: float,
                     reason: str) -> None:
        self.queue = [e for e in self.queue if e[2] is not victim]
        self.ctl.note_rejection(reason, victim.priority, shed=True)
        self.ctl.limiter.release()
        victim.outcome = "shed"
        victim.shed_reason = reason
        victim.t_finish = now
        self.on_terminal(victim)

    def adopt(self, req: SimRequest, now: float) -> None:
        """Disaggregated handoff delivery: the stream was admitted (and
        emitted its first token) on a prefill replica; its load joins
        this pool forcibly, the live fleet-adopt rule."""
        req.decode_replica = self.name
        self.ctl.limiter.acquire_forced()
        self.imported.append(req)
        if self.costs.tick_s is None:
            self._kick()

    # ----------------------------------------------------------- iteration
    def expire_queued(self, now: float) -> None:
        keep = []
        for entry in self.queue:
            req = entry[2]
            if (req.deadline_s is not None
                    and now - req.t_submit >= req.deadline_s):
                self.ctl.limiter.release()
                req.outcome = "expired"
                req.t_finish = now
                self.on_terminal(req)
            else:
                keep.append(entry)
        self.queue = keep

    def _plan(self, now: float):
        """One iteration's work: returns (cost_s, admits, imported,
        decoders) or None when idle. Mutates queue/pool at plan time —
        the reservation happens when the iteration starts."""
        self.expire_queued(now)
        admits: List[SimRequest] = []
        cost = 0.0
        while (self.role != "decode" and self.queue
               and len(self.running) + len(admits) < self.slots):
            _, _, req = self.queue[0]
            need = self.pool.blocks_for(req.prompt_len + req.max_new)
            if not self.pool.can_alloc(need):
                break  # head-of-line: the live admit loop stops here too
            self.queue.pop(0)
            self.pool.alloc(need)
            req.blocks = need
            admits.append(req)
            cost += self.costs.prefill(req.prompt_len)
        imported: List[SimRequest] = []
        while (self.role == "decode" and self.imported
               and len(self.running) + len(imported) < self.slots):
            req = self.imported[0]
            need = self.pool.blocks_for(req.prompt_len + req.max_new)
            if not self.pool.can_alloc(need):
                break
            self.imported.popleft()
            self.pool.alloc(need)
            req.blocks = need
            imported.append(req)
            cost += self.costs.kv_swap_in_s
        decoders: List[SimRequest] = []
        if self.role != "prefill":
            decoders = (
                self.running
                + [a for a in admits if a.max_new > 1]
                + imported
            )
            if decoders:
                cost += self.costs.decode_s
        if not admits and not imported and not decoders:
            return None
        if self.costs.tick_s is not None:
            cost = self.costs.tick_s
        self.iterations += 1
        return cost, admits, imported, decoders

    def _apply(self, admits, imported, decoders, teff: float) -> None:
        """Iteration effects at ``teff``: first tokens for admissions
        (prefill emits the first token), handoffs for a prefill
        replica, one decode token per active stream, finishes."""
        for req in admits:
            self._queue_w.record(max(0.0, teff - req.t_submit))
            req.t_first_token = teff
            req.tokens = 1
            self._ttft_w.record(max(0.0, teff - req.t_submit))
            if self.role == "prefill":
                # stream leaves this replica: blocks travel with the
                # handoff payload, the limiter slot frees at send
                self.pool.free(req.blocks)
                self.ctl.limiter.release()
                if self.handoff_sink is not None:
                    self.handoff_sink(req, teff)
                else:
                    self._finish(req, teff)
            elif req.max_new <= 1:
                self._finish(req, teff)
        survivors: List[SimRequest] = []
        for req in decoders:
            req.tokens += 1
            if req.tokens >= req.max_new:
                self._finish(req, teff)
            else:
                survivors.append(req)
        self.running = survivors

    def _finish(self, req: SimRequest, teff: float) -> None:
        req.t_finish = teff
        req.outcome = "completed"
        self.pool.free(req.blocks)
        req.blocks = 0
        self.ctl.limiter.release()
        self.on_terminal(req)

    # cost mode: busy/idle event chain -----------------------------------
    def _kick(self) -> None:
        if self._busy:
            return
        now = self.loop.clock()
        plan = self._plan(now)
        if plan is None:
            return
        cost, admits, imported, decoders = plan
        self._busy = True
        self._control_tick(now)

        def done(t: float) -> None:
            self._apply(admits, imported, decoders, t)
            self._busy = False
            self._kick()

        self.loop.after(max(cost, 1e-9), "iter", done, detail=self.name)

    # tick mode: one synchronous iteration per fleet tick ----------------
    def step_once(self, now: float) -> None:
        """``drive_virtual`` twin: all iteration effects land at the
        tick instant (the live virtual-clock drive performs the whole
        scheduler step before advancing the clock), and the control
        plane ticks on every step call, working or idle — exactly
        ``_step_impl``'s epilogue.

        Mirrors the overlapped-decode cadence: a non-steady iteration
        (possible admission, queue expiry) drains the in-flight step —
        its tokens ride this iteration — then runs the sequential body
        (admit + one decode); a steady iteration dispatches the next
        step and consumes the previous one, which after a drain means
        a refill bubble that emits nothing."""
        if self._nonsteady_tick(now):
            prev, self._pipe = self._pipe, None
            if prev:
                self._consume(prev, now)
            plan = self._plan(now)
            if plan is not None:
                _, admits, imported, decoders = plan
                self._apply(admits, imported, decoders, now)
        else:
            # steady state: dispatch step N+1 over the slots still
            # under budget (a slot with its pipelined token pending at
            # max_new is excluded — the live budget-predicted finish),
            # then consume step N
            prev = self._pipe
            covered = set(id(r) for r in prev) if prev else set()
            live = [
                r for r in self.running
                if r.tokens + (1 if id(r) in covered else 0) < r.max_new
            ]
            self._pipe = live or None
            if prev:
                self._consume(prev, now)
            if prev or live:
                self.iterations += 1
        self._control_tick(now)

    def _nonsteady_tick(self, now: float) -> bool:
        """Tick-mode mirror of ``_step_impl._nonsteady``: the iteration
        must run the sequential path when an admission could place
        (backlog + a free slot) or a queued deadline has passed. The
        live reaper's running-stream expiry is not simulated
        (documented simplification)."""
        for _, _, req in self.queue:
            if (req.deadline_s is not None
                    and now - req.t_submit >= req.deadline_s):
                return True
        backlog = self.imported if self.role == "decode" else self.queue
        return bool(backlog) and len(self.running) < self.slots

    def _control_tick(self, now: float) -> None:
        """``_overload_tick``'s twin: limiter AIMD + ladder fold, plus
        the ladder's level-4 action — shed every queued best-effort
        request (never-streamed work only; in the sim all queued work
        is never-streamed)."""
        self.ctl.tick()
        if self.ctl.ladder.shed_best_effort():
            victims = [
                e[2] for e in self.queue
                if e[2].priority == Priority.BEST_EFFORT
            ]
            for v in victims:
                self._shed_queued(v, now, reason="degraded")

    def _consume(self, entries: List[SimRequest], now: float) -> None:
        """Consume one in-flight pipelined decode step: a token for
        every covered stream, finishes at budget."""
        covered = set(id(r) for r in entries)
        survivors: List[SimRequest] = []
        for req in self.running:
            if id(req) in covered:
                req.tokens += 1
                if req.tokens >= req.max_new:
                    self._finish(req, now)
                    continue
            survivors.append(req)
        self.running = survivors

    def idle_control_tick(self, now: float) -> None:
        """Cost-mode housekeeping between iterations (fleet poll): an
        idle replica's limiter still probes upward and its ladder still
        descends — the live scheduler loop spins and ticks even with
        no work."""
        if not self._busy:
            self.expire_queued(now)
            self._control_tick(now)

    def activations(self) -> Dict:
        out = self.ctl.activations()
        out["iterations"] = self.iterations
        out["max_degrade_level"] = self.ctl.ladder.max_level_seen
        return out


class VirtualFleet:
    """A fleet of virtual replicas plus the real autoscale advisor.

    ``arm="unified"`` builds ``replicas`` interchangeable replicas;
    ``arm="disagg"`` builds a prefill pool and a decode pool joined by
    a handoff wire priced per block (PR 16's shape: TTFT is decided at
    the prefill replica, TPOT at the decode replica, and the transfer
    sits between first and second token).
    """

    def __init__(
        self,
        *,
        loop: EventLoop,
        costs: SimCosts,
        arm: str = "unified",
        replicas: int = 2,
        n_prefill: int = 1,
        n_decode: int = 1,
        slots: int = 4,
        max_queue: int = 16,
        num_blocks: int = 64,
        block_size: int = 8,
        overload: Optional[OverloadConfig] = None,
        poll_s: float = 0.05,
        name: str = "sim",
    ):
        if arm not in ("unified", "disagg"):
            raise ValueError(f"unknown arm {arm!r}")
        self.loop = loop
        self.costs = costs
        self.arm = arm
        self.name = name
        self.poll_s = float(poll_s)
        self.overload_cfg = overload or OverloadConfig()
        self.outstanding = 0
        self.terminal: List[SimRequest] = []
        self.more_arrivals: Callable[[], bool] = lambda: False
        self.autoscale = AutoscaleAdvisor.from_config(
            self.overload_cfg, clock=loop.clock
        )
        self.autoscale_timeline: List[Tuple[float, int, float, float]] = []

        def mk(role: str, i: int) -> VirtualReplica:
            return VirtualReplica(
                f"{name}-{role[0]}{i}", loop=loop, costs=costs, slots=slots,
                max_queue=max_queue, num_blocks=num_blocks,
                block_size=block_size, role=role, index=i,
                overload=self.overload_cfg,
                handoff_sink=self._handoff if role == "prefill" else None,
                on_terminal=self._terminal,
            )

        if arm == "unified":
            self.replicas = [mk("unified", i) for i in range(max(1, replicas))]
            self.prefill_pool = self.replicas
            self.decode_pool: List[VirtualReplica] = []
        else:
            self.prefill_pool = [mk("prefill", i) for i in range(max(1, n_prefill))]
            self.decode_pool = [mk("decode", i) for i in range(max(1, n_decode))]
            self.replicas = self.prefill_pool + self.decode_pool

    # -------------------------------------------------------------- traffic
    def submit(self, req: SimRequest, now: float) -> bool:
        """Route like the fleet router: prefer replicas whose overload
        gates would admit, least-loaded first; with nowhere to spill,
        the least-loaded replica's own gates shed (the fleet-wide
        shed)."""
        pool = self.prefill_pool
        cands = [r for r in pool if r.would_admit(req.priority)] or pool
        rep = min(cands, key=lambda r: (r.load(), r.index))
        ok = rep.submit(req, now)
        if ok:
            self.outstanding += 1
        return ok

    def _terminal(self, req: SimRequest) -> None:
        self.outstanding -= 1
        self.terminal.append(req)

    def _handoff(self, req: SimRequest, t: float) -> None:
        """Prefill -> decode block transfer: priced per block, then
        adopted by the least-loaded decode replica."""
        delay = self.costs.handoff_s(req.blocks)

        def deliver(tt: float) -> None:
            rep = min(self.decode_pool, key=lambda r: (r.load(), r.index))
            rep.adopt(req, tt)

        self.loop.after(delay, "handoff", deliver, detail=req.rid)

    # -------------------------------------------------------- control plane
    def start_polling(self) -> None:
        """Begin the fleet supervisor twin: one autoscale observation
        (and cost-mode idle control tick) every ``poll_s`` of virtual
        time, self-terminating when traffic drains."""
        self.loop.at(self.loop.clock(), "poll", self._poll, detail=self.name)

    def _poll(self, t: float) -> None:
        eligible = self.replicas
        if not eligible:
            sat, util = 1.0, 1.0
        else:
            saturated = 0
            util = 0.0
            for r in eligible:
                util += r.ctl.limiter.utilization()
                if (not r.ctl.would_admit(Priority.STANDARD)
                        or r.ctl.ladder.level >= 1):
                    saturated += 1
            sat = saturated / len(eligible)
            util /= len(eligible)
        sig = self.autoscale.observe(sat, util)
        self.autoscale_timeline.append(
            (round(t, 9), sig, round(sat, 6), round(util, 6))
        )
        if self.costs.tick_s is None:
            for r in self.replicas:
                r.idle_control_tick(t)
        if self.outstanding > 0 or self.more_arrivals():
            self.loop.after(self.poll_s, "poll", self._poll, detail=self.name)

    def step_all(self, now: float) -> None:
        """Tick mode: one synchronous iteration per replica per tick
        (prefill pool first, so same-tick handoffs are in flight before
        the decode pool steps)."""
        for r in self.prefill_pool:
            r.step_once(now)
        for r in self.decode_pool:
            r.step_once(now)

    # ------------------------------------------------------------ reporting
    def engines(self) -> int:
        return len(self.replicas)

    def activations(self) -> Dict:
        per = {r.name: r.activations() for r in self.replicas}
        agg: Dict[str, int] = {}
        for acts in per.values():
            for k, v in acts.items():
                if k == "degrade_level":
                    continue
                if k == "max_degrade_level":
                    agg[k] = max(agg.get(k, 0), int(v))
                else:
                    agg[k] = agg.get(k, 0) + int(v)
        return {"total": agg, "per_replica": per}

    def autoscale_summary(self) -> Dict:
        signals = [s for _, s, _, _ in self.autoscale_timeline]
        changes = sum(
            1 for a, b in zip(signals, signals[1:]) if a != b
        )
        # a flap is a direct want-more <-> want-fewer reversal with no
        # settled (0) observation between — the hysteresis test pins 0
        flaps = sum(
            1 for a, b in zip(signals, signals[1:])
            if a != 0 and b != 0 and a != b
        )
        return {
            "observations": len(signals),
            "max_signal": max(signals) if signals else 0,
            "min_signal": min(signals) if signals else 0,
            "signal_changes": changes,
            "flaps": flaps,
            "timeline": [
                {"t": t, "signal": s, "saturated_frac": f, "mean_util": u}
                for t, s, f, u in self.autoscale_timeline
            ],
        }
