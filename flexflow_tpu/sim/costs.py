"""Where the twin's step durations come from.

A discrete-event fleet simulator is only as honest as its cost table.
This module gives the twin three sources, strongest first:

* :meth:`SimCosts.from_ledger_export` — measured p50s from a
  PredictionLedger snapshot (``tools/obsreport.py predict --export``),
  i.e. what the live engine actually observed for ``prefill[bucket]``
  / ``decode`` / ``verify`` / ``kv_swap_in``. Loads are refused across
  device kinds, the same rule ``apply_recalibration`` enforces: one
  device's measurements are never folded into another device's table.
* :meth:`SimCosts.from_roofline` / :meth:`SimCosts.from_strategy` —
  the calibrated serving roofline (``obs.capacity.ServingFlops``), or
  the strategy-search cost model (``search.serving_strategy``) when
  the question is a tensor-parallel degree per pool: the same plumbing
  that prices TP candidates for live layout choice prices them for the
  twin, collectives included.
* :meth:`SimCosts.fixed_tick` — every working iteration costs exactly
  ``dt``, mirroring ``loadgen.drive_virtual``'s virtual-clock tick
  loop. This is the sim-vs-live gating mode (``simfleet simcheck``):
  the live storm runs on the same virtual tick, so divergence measures
  the twin's *queueing/control* fidelity, not its cost table.

No clocks in here — costs are data, never measurements.
"""
from __future__ import annotations

import json
from typing import Dict, Optional, Sequence, Tuple


def _slug(kind: str) -> str:
    # calibration.py's device slug, duplicated rather than imported:
    # that module pulls in jax for device detection and the sim must
    # stay importable (and lintable) as pure host code
    return "".join(
        c if c.isalnum() else "_" for c in kind.lower()
    ).strip("_") or "unknown"


class SimCosts:
    """Per-step durations for the virtual fleet.

    ``prefill_s`` maps prompt buckets to seconds (lookup rounds a
    prompt up to its bucket, exactly like the engine pads); ``decode_s``
    is one fixed-shape decode step; ``handoff_per_block_s`` prices the
    disaggregated KV handoff wire per block; ``kv_swap_in_s`` is the
    decode pool's per-stream KV adoption cost. ``tick_s`` non-None
    switches the replicas into tick mode (see module docstring).
    """

    def __init__(
        self,
        *,
        device_kind: str,
        prefill_s: Dict[int, float],
        decode_s: float,
        verify_s: Optional[float] = None,
        kv_swap_in_s: float = 0.0,
        handoff_per_block_s: float = 0.0,
        tick_s: Optional[float] = None,
        source: str = "synthetic",
    ):
        if not prefill_s and tick_s is None:
            raise ValueError("a cost table needs at least one prefill bucket")
        self.device_kind = device_kind
        self.prefill_s = {int(k): float(v) for k, v in prefill_s.items()}
        self.decode_s = float(decode_s)
        self.verify_s = float(verify_s) if verify_s is not None else self.decode_s
        self.kv_swap_in_s = float(kv_swap_in_s)
        self.handoff_per_block_s = float(handoff_per_block_s)
        self.tick_s = float(tick_s) if tick_s is not None else None
        self.source = source

    # ------------------------------------------------------------- lookups
    @property
    def buckets(self) -> Tuple[int, ...]:
        return tuple(sorted(self.prefill_s))

    def prefill(self, prompt_len: int) -> float:
        """Cost of one prefill: the smallest bucket that fits the
        prompt (the engine's padding rule); prompts past the largest
        bucket pay the largest bucket's cost."""
        if self.tick_s is not None:
            return self.tick_s
        for b in self.buckets:
            if prompt_len <= b:
                return self.prefill_s[b]
        return self.prefill_s[self.buckets[-1]]

    def handoff_s(self, blocks: int) -> float:
        return self.handoff_per_block_s * max(0, blocks)

    def describe(self) -> Dict:
        return {
            "device_kind": self.device_kind,
            "source": self.source,
            "mode": "tick" if self.tick_s is not None else "cost",
            "tick_s": self.tick_s,
            "prefill_s": {str(k): v for k, v in sorted(self.prefill_s.items())},
            "decode_s": self.decode_s,
            "verify_s": self.verify_s,
            "kv_swap_in_s": self.kv_swap_in_s,
            "handoff_per_block_s": self.handoff_per_block_s,
        }

    # ---------------------------------------------------------- constructors
    @classmethod
    def fixed_tick(cls, dt: float, device_kind: str = "virtual") -> "SimCosts":
        """Every working iteration costs exactly ``dt`` — the
        ``drive_virtual`` twin used by the simcheck gate."""
        if dt <= 0:
            raise ValueError(f"tick must be positive, got {dt}")
        return cls(
            device_kind=device_kind,
            prefill_s={},
            decode_s=dt,
            tick_s=dt,
            source=f"fixed tick ({dt}s/iteration, drive_virtual twin)",
        )

    @classmethod
    def from_roofline(
        cls,
        cfg,
        *,
        buckets: Sequence[int],
        slots: int = 4,
        decode_context: Optional[int] = None,
        chip=None,
        device_kind: Optional[str] = None,
        kv_swap_in_s: float = 0.0,
        handoff_per_block_s: float = 0.0,
    ) -> "SimCosts":
        """Price steps with the serving roofline (``ServingFlops``) for
        a TransformerConfig-shaped ``cfg`` — the same model the engine
        registers as the PREDICT side of every ledger pair. The decode
        step is fixed-shape: all ``slots`` lanes attend to
        ``decode_context`` positions each (default: half the largest
        bucket, a steady-state midpoint)."""
        from ..obs.capacity import ServingFlops

        fm = ServingFlops.from_config(cfg, chip=chip)
        ctx = decode_context if decode_context is not None else max(buckets) // 2
        ctx_sum = max(1, slots) * max(1, ctx)
        decode_s = fm.roofline_s(
            fm.decode_flops(slots, ctx_sum), fm.decode_bytes(slots, ctx_sum)
        )
        return cls(
            device_kind=device_kind or f"chip:{fm.chip.name}",
            prefill_s={
                int(b): fm.roofline_s(fm.prefill_flops(b), fm.prefill_bytes(b))
                for b in buckets
            },
            decode_s=decode_s,
            verify_s=decode_s,
            kv_swap_in_s=kv_swap_in_s,
            handoff_per_block_s=handoff_per_block_s,
            source="serving roofline (ServingFlops x chip peak)",
        )

    @classmethod
    def from_strategy(
        cls,
        cfg,
        *,
        tp: int,
        mesh_devices: int,
        buckets: Sequence[int],
        slots: int = 4,
        calibration=None,
        kv_swap_in_s: float = 0.0,
        handoff_per_block_s: float = 0.0,
    ) -> "SimCosts":
        """Price steps for a tensor-parallel degree with the strategy
        search's cost plumbing (``score_serving_layouts``: graph build +
        per-op roofline + collective costs) — the twin answers "what TP
        per pool" with the same arithmetic the live layout chooser
        uses. Imports lazily; this path touches jax for device
        detection, so it belongs to the CLI, not the inner sim loop."""
        from ..search.serving_strategy import score_serving_layouts

        prefill_s: Dict[int, float] = {}
        decode_s = None
        for b in buckets:
            scored = score_serving_layouts(
                cfg, mesh_devices, max_batch_slots=slots,
                prefill_len=int(b), calibration=calibration,
            )
            row = next((c for c in scored if c["tp_degree"] == tp), None)
            if row is None:
                raise ValueError(
                    f"tp={tp} is not a candidate for {cfg.num_heads} heads "
                    f"over {mesh_devices} device(s) "
                    f"(candidates: {[c['tp_degree'] for c in scored]})"
                )
            prefill_s[int(b)] = float(row["prefill_s"])
            decode_s = float(row["decode_s"])
        return cls(
            device_kind=f"tp{tp}x{mesh_devices}",
            prefill_s=prefill_s,
            decode_s=decode_s,
            kv_swap_in_s=kv_swap_in_s,
            handoff_per_block_s=handoff_per_block_s,
            source=f"strategy-search cost model (tp={tp}/{mesh_devices})",
        )

    @classmethod
    def from_ledger_export(
        cls,
        export,
        *,
        model: Optional[str] = None,
        expect_device: Optional[str] = None,
        kv_swap_in_s: Optional[float] = None,
        handoff_per_block_s: float = 0.0,
    ) -> "SimCosts":
        """Build from an ``obsreport predict --export`` snapshot (path
        or parsed dict). Measured p50s win over predictions when a key
        has pairs; keys used: ``prefill[N]``, ``decode``, ``verify``,
        ``kv_swap_in``.

        ``expect_device`` refuses cross-device loads (ValueError) —
        the ``apply_recalibration`` rule: never fold one device's
        measurements into another device's table.
        """
        if isinstance(export, str):
            with open(export) as f:
                doc = json.load(f)
        else:
            doc = dict(export)
        if doc.get("schema") != "flexflow-ledger-export-v1":
            raise ValueError(
                f"not a ledger export (schema={doc.get('schema')!r}); "
                "produce one with: tools/obsreport.py predict --export FILE"
            )
        models = doc.get("models") or {}
        if not models:
            raise ValueError("ledger export contains no models")
        if model is None:
            if len(models) > 1:
                raise ValueError(
                    f"export has {sorted(models)}; pass model= to pick one"
                )
            model = next(iter(models))
        if model not in models:
            raise ValueError(f"model {model!r} not in export ({sorted(models)})")
        snap = models[model]
        device = snap.get("device_kind") or "unknown"
        if expect_device is not None and _slug(expect_device) != _slug(device):
            raise ValueError(
                f"refusing to load {device!r} measurements into a "
                f"{expect_device!r} cost table: one device's measurements "
                "are never folded into another device's table "
                "(the apply_recalibration rule)"
            )

        def seconds(entry) -> Optional[float]:
            if entry.get("pairs", 0) > 0 and entry.get("measured_p50_s") is not None:
                return float(entry["measured_p50_s"])
            if entry.get("predicted_s") is not None:
                return float(entry["predicted_s"])
            return None

        prefill_s: Dict[int, float] = {}
        decode_s = verify_s = swap_s = None
        for entry in snap.get("entries", []):
            key = entry.get("key", "")
            s = seconds(entry)
            if s is None:
                continue
            if key.startswith("prefill[") and key.endswith("]"):
                try:
                    prefill_s[int(key[len("prefill["):-1])] = s
                except ValueError:
                    continue
            elif key == "decode":
                decode_s = s
            elif key == "verify":
                verify_s = s
            elif key == "kv_swap_in":
                swap_s = s
        if not prefill_s or decode_s is None:
            raise ValueError(
                f"export for {model!r} is missing prefill[*]/decode keys "
                f"(has {[e.get('key') for e in snap.get('entries', [])]}); "
                "the engine must serve traffic before its ledger can "
                "calibrate a twin"
            )
        return cls(
            device_kind=device,
            prefill_s=prefill_s,
            decode_s=decode_s,
            verify_s=verify_s,
            kv_swap_in_s=(
                kv_swap_in_s if kv_swap_in_s is not None else (swap_s or 0.0)
            ),
            handoff_per_block_s=handoff_per_block_s,
            source=f"ledger export ({model} @ {device})",
        )
