"""Scenario runner + sweep ranking: the twin's answer surface.

``run_scenario`` replays one arrival schedule against one fleet
configuration and returns a :class:`~flexflow_tpu.sim.report.SimReport`;
``sweep`` runs a scenario list and ranks the configurations that meet
the operator's targets (TTFT p99 bound, shed-rate bound) by engine
cost then latency — "how many replicas do I need for this SLO at N×
traffic" becomes an offline table instead of a load test.

Schedules are ``tools/loadgen.py`` arrivals: pass the live objects, a
parsed ``flexflow-load-schedule-v1`` document, or a path to one (the
``--record-schedule`` artifact) — the same canned storm drives live
runs, A/B gates, and the twin.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Union

from ..serving.overload import OverloadConfig
from .costs import SimCosts
from .events import EventLoop
from .report import SimReport
from .virtual import SimRequest, VirtualFleet

SCHEDULE_SCHEMA = "flexflow-load-schedule-v1"


@dataclasses.dataclass
class Scenario:
    """One fleet configuration to simulate."""

    name: str
    arm: str = "unified"            # "unified" | "disagg"
    replicas: int = 2               # unified pool width
    n_prefill: int = 1              # disagg pool widths
    n_decode: int = 1
    slots: int = 4
    max_queue: int = 16
    num_blocks: int = 64
    block_size: int = 8
    overload: Optional[OverloadConfig] = None
    poll_s: float = 0.05
    traffic_x: float = 1.0          # arrival-time compression (N x rate)

    def engines(self) -> int:
        return (
            self.replicas if self.arm == "unified"
            else self.n_prefill + self.n_decode
        )

    def describe(self) -> Dict:
        out = {
            "name": self.name,
            "arm": self.arm,
            "engines": self.engines(),
            "slots": self.slots,
            "max_queue": self.max_queue,
            "num_blocks": self.num_blocks,
            "traffic_x": self.traffic_x,
        }
        if self.arm == "unified":
            out["replicas"] = self.replicas
        else:
            out["n_prefill"] = self.n_prefill
            out["n_decode"] = self.n_decode
        if self.overload is not None:
            cfg = self.overload
            out["overload"] = {
                "limiter_interval_s": cfg.limiter_interval_s,
                "min_limit": cfg.min_limit,
                "min_queue_frac": cfg.min_queue_frac,
                "hard_queue_frac": cfg.hard_queue_frac,
                "up_threshold": cfg.up_threshold,
                "up_hold_s": cfg.up_hold_s,
                "down_threshold": cfg.down_threshold,
                "down_hold_s": cfg.down_hold_s,
                "autoscale_up_hold_s": cfg.autoscale_up_hold_s,
                "autoscale_down_hold_s": cfg.autoscale_down_hold_s,
            }
        return out


# ------------------------------------------------------------- schedules
@dataclasses.dataclass(frozen=True)
class ArrivalSpec:
    """Schedule-row shape the sim consumes (a loadgen Arrival without
    the token ids — the twin prices prompts by length)."""

    t: float
    priority: str
    prompt_len: int
    max_new: int
    deadline_s: Optional[float] = None


def coerce_schedule(schedule) -> List[ArrivalSpec]:
    """Accept loadgen ``Arrival`` objects, schedule-document dicts, a
    path to a recorded schedule, or ArrivalSpec rows; return sorted
    specs."""
    if isinstance(schedule, str):
        schedule = load_schedule(schedule)
    if isinstance(schedule, dict):
        if schedule.get("schema") != SCHEDULE_SCHEMA:
            raise ValueError(
                f"not a load schedule (schema={schedule.get('schema')!r})"
            )
        schedule = schedule.get("arrivals", [])
    specs: List[ArrivalSpec] = []
    for a in schedule:
        if isinstance(a, ArrivalSpec):
            specs.append(a)
            continue
        get = (lambda k, d=None: a.get(k, d)) if isinstance(a, dict) \
            else (lambda k, d=None: getattr(a, k, d))
        prompt = get("prompt")
        plen = len(prompt) if prompt is not None else int(get("prompt_len", 1))
        specs.append(ArrivalSpec(
            t=float(get("t", 0.0)),
            priority=str(get("priority", "standard")),
            prompt_len=plen,
            max_new=int(get("max_new", 1)),
            deadline_s=get("deadline_s"),
        ))
    specs.sort(key=lambda s: (s.t,))
    return specs


def load_schedule(path: str) -> List[Dict]:
    """Read a ``flexflow-load-schedule-v1`` document (the
    ``loadgen --record-schedule`` artifact) without importing the
    tools package."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEDULE_SCHEMA:
        raise ValueError(
            f"{path}: not a load schedule (schema={doc.get('schema')!r}); "
            "record one with: tools/loadgen.py --record-schedule FILE"
        )
    return doc["arrivals"]


def scale_schedule(specs: Sequence[ArrivalSpec],
                   x: float) -> List[ArrivalSpec]:
    """N x traffic: compress arrival times by ``x`` (the same requests,
    offered ``x`` times faster — the ROADMAP's "at N x traffic"
    question without re-drawing the workload)."""
    if x <= 0:
        raise ValueError(f"traffic multiplier must be positive, got {x}")
    if x == 1.0:
        return list(specs)
    return [dataclasses.replace(s, t=s.t / x) for s in specs]


# --------------------------------------------------------------- running
def run_scenario(
    schedule,
    costs: SimCosts,
    scenario: Scenario,
) -> SimReport:
    """Replay ``schedule`` against one virtual fleet. Deterministic:
    the only inputs are the schedule, the cost table, and the scenario
    — two calls return byte-identical event traces and reports."""
    specs = scale_schedule(coerce_schedule(schedule), scenario.traffic_x)
    duration = specs[-1].t if specs else 0.0
    loop = EventLoop()
    fleet = VirtualFleet(
        loop=loop, costs=costs, arm=scenario.arm,
        replicas=scenario.replicas, n_prefill=scenario.n_prefill,
        n_decode=scenario.n_decode, slots=scenario.slots,
        max_queue=scenario.max_queue, num_blocks=scenario.num_blocks,
        block_size=scenario.block_size, overload=scenario.overload,
        poll_s=scenario.poll_s, name=scenario.name,
    )
    requests: List[SimRequest] = [
        SimRequest.from_arrival(s, i) for i, s in enumerate(specs)
    ]
    submitted = [0]  # arrival cursor, shared with the poll terminator
    fleet.more_arrivals = lambda: submitted[0] < len(requests)

    if costs.tick_s is not None:
        dt = costs.tick_s

        def tick(t: float) -> None:
            # drive_virtual's loop as events: submit the arrivals now
            # due, step every replica once, advance by dt — arrival
            # times quantize to tick boundaries exactly like the live
            # virtual-clock drive
            while submitted[0] < len(requests) and \
                    requests[submitted[0]].t <= t + 1e-12:
                fleet.submit(requests[submitted[0]], t)
                submitted[0] += 1
            fleet.step_all(t)
            if submitted[0] < len(requests) or fleet.outstanding > 0:
                loop.after(dt, "tick", tick)

        loop.at(0.0, "tick", tick)
    else:
        for req in requests:
            def arrive(t: float, r: SimRequest = req) -> None:
                submitted[0] += 1
                fleet.submit(r, t)

            loop.at(req.t, "arrival", arrive, detail=req.rid)
    fleet.start_polling()
    loop.run()
    for req in requests:
        if req.outcome is None:
            req.outcome = "failed"  # starved in-sim: surface, don't hide
    return SimReport(
        requests=requests, fleet=fleet, loop=loop, costs=costs,
        duration_s=duration, scenario=scenario.describe(),
    )


# --------------------------------------------------------------- ranking
def sweep(
    schedule,
    costs: SimCosts,
    scenarios: Sequence[Scenario],
    *,
    target_ttft_p99_s: Optional[float] = None,
    target_shed_rate: float = 0.0,
) -> Dict:
    """Run every scenario and rank: configurations that meet the
    targets first (fewest engines, then lowest TTFT p99), then the
    misses (closest first). Returns the ranked rows plus full reports
    keyed by scenario name."""
    rows: List[Dict] = []
    reports: Dict[str, Dict] = {}
    for sc in scenarios:
        rep = run_scenario(schedule, costs, sc).render()
        reports[sc.name] = rep
        ttft_p99 = rep.get("ttft_p99_s")
        shed = rep.get("shed_rate", 0.0)
        feasible = shed <= target_shed_rate + 1e-12 and (
            target_ttft_p99_s is None
            or (ttft_p99 is not None and ttft_p99 <= target_ttft_p99_s)
        )
        rows.append({
            "scenario": sc.name,
            "arm": rep["arm"],
            "engines": rep["engines"],
            "traffic_x": sc.traffic_x,
            "feasible": feasible,
            "ttft_p50_s": rep.get("ttft_p50_s"),
            "ttft_p95_s": rep.get("ttft_p95_s"),
            "ttft_p99_s": ttft_p99,
            "tpot_p50_s": rep.get("tpot_p50_s"),
            "shed_rate": shed,
            "goodput_tokens_per_s": rep.get("goodput_tokens_per_s"),
            "max_degrade_level":
                rep["overload"]["total"].get("max_degrade_level", 0),
            "autoscale_max_signal": rep["autoscale"]["max_signal"],
        })
    big = 1e18
    rows.sort(key=lambda r: (
        not r["feasible"],
        r["engines"] if r["feasible"] else 0,
        r["ttft_p99_s"] if r["ttft_p99_s"] is not None else big,
        r["shed_rate"],
        r["scenario"],
    ))
    for i, r in enumerate(rows):
        r["rank"] = i + 1
    return {
        "targets": {
            "ttft_p99_s": target_ttft_p99_s,
            "shed_rate": target_shed_rate,
        },
        "ranked": rows,
        "reports": reports,
    }
