"""flexflow_tpu: a TPU-native auto-parallelizing deep-learning framework.

A ground-up rebuild of the capabilities of FlexFlow/Unity (reference:
napplesty/FlexFlow) for TPUs: layer-level model API, parallel computation
graph with per-dim shard/replica degrees, Unity-style joint search over
graph substitutions and device placements against a calibrated cost
model + simulator, and execution via XLA/pjit/GSPMD with Pallas kernels
and ICI/DCN collectives (no CUDA, no Legion, no NCCL).
"""

from .config import FFConfig, FFIterationConfig
from .core.types import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OpType,
    ParameterSyncOption,
    ParameterSyncType,
    PoolType,
)
from .model import FFModel, Tensor
from .runtime.optimizers import AdamOptimizer, Optimizer, SGDOptimizer

__version__ = "0.1.0"

__all__ = [
    "FFConfig",
    "FFIterationConfig",
    "FFModel",
    "Tensor",
    "ActiMode",
    "AggrMode",
    "CompMode",
    "DataType",
    "LossType",
    "MetricsType",
    "OpType",
    "PoolType",
    "ParameterSyncType",
    "ParameterSyncOption",
    "SGDOptimizer",
    "AdamOptimizer",
    "Optimizer",
    # lazy (see __getattr__): round-3 user-facing additions
    "ElasticTrainer",
    "ParallelDim",
    "ParallelTensorView",
    "initialize_distributed",
]


def __getattr__(name):
    # lazy: these pull in orbax / jax.distributed machinery only when used
    if name == "ElasticTrainer":
        from .runtime.elastic import ElasticTrainer

        return ElasticTrainer
    if name in ("ParallelTensorView", "ParallelDim"):
        from .core import parallel_tensor

        return getattr(parallel_tensor, name)
    if name == "initialize_distributed":
        from .parallel.distributed import initialize_distributed

        return initialize_distributed
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + __all__))
