"""FFModel: the central model-building + training API.

Reference: FFModel (include/flexflow/model.h:328-554 — ~60 layer
builders; src/runtime/model.cc:5195 LoC). API names and argument orders
mirror the reference so FlexFlow programs port mechanically; semantics
are TPU-native: building a layer records a PCG node (the reference's
lazy Layer graph, src/runtime/layer.cc), and ``compile`` lowers the PCG
through the Unity search to a single jitted, mesh-sharded train step
instead of Legion task launches.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .config import FFConfig, FFIterationConfig
from .core.graph import Node, PCGraph
from .core.tensor import TensorSpec
from .core.types import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OpType,
    PoolType,
)
from .ops import io_ops, linear as linear_mod, conv as conv_mod
from .ops.attention import MultiHeadAttentionParams
from .ops.batch_matmul import BatchMatmulParams
from .ops.elementwise import ElementBinaryParams, ElementUnaryParams
from .ops.embedding import EmbeddingParams
from .ops.moe_ops import (
    AggregateParams,
    AggregateSpecParams,
    CacheParams,
    GroupByParams,
    TopKParams,
)
from .ops.norm import BatchNormParams, LayerNormParams
from .ops.reduction_ops import GatherParams, MeanParams, ReduceSumParams
from .ops.shape_ops import (
    CastParams,
    ConcatParams,
    FlatParams,
    ReshapeParams,
    ReverseParams,
    SplitParams,
    TransposeParams,
)
from .ops.softmax import DropoutParams, SoftmaxParams
from .parallel.propagation import infer_all_specs
from .runtime.executor import CompiledExecutor
from .runtime.metrics import PerfMetrics
from .runtime.optimizers import Optimizer, SGDOptimizer


class Tensor:
    """Frontend tensor handle: (graph node, output index) + logical spec.

    Reference: the Tensor/TensorBase frontend objects (tensor.h) created
    eagerly by layer calls and resolved at compile.
    """

    def __init__(self, model: "FFModel", node: Node, idx: int, spec: TensorSpec):
        self._model = model
        self.node = node
        self.idx = idx
        self.spec = spec

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.spec.shape

    @property
    def dtype(self) -> DataType:
        return self.spec.dtype

    @property
    def ndim(self) -> int:
        return self.spec.ndim

    def __repr__(self):
        return f"Tensor(shape={self.shape}, dtype={self.dtype.value}, node={self.node.guid})"

    # numpy-ish sugar
    def __add__(self, other):
        return self._model.add(self, other)

    def __sub__(self, other):
        return self._model.subtract(self, other)

    def __mul__(self, other):
        return self._model.multiply(self, other)


class FFModel:
    """Model builder + trainer (reference: model.h:328)."""

    def __init__(self, config: Optional[FFConfig] = None, seed: int = 0):
        self.config = config or FFConfig()
        self.graph = PCGraph()
        self._num_inputs = 0
        self._seed = seed
        self.iter_config = FFIterationConfig()
        self.executor: Optional[CompiledExecutor] = None
        self.strategy = None
        self.mesh = None
        self.label_spec: Optional[TensorSpec] = None
        self._outputs: List[Tensor] = []
        self._search_result = None

    # ------------------------------------------------------------ helpers
    def _add(self, op_type: OpType, params, inputs: Sequence[Tensor], name: str = "") -> List[Tensor]:
        node = self.graph.new_node(op_type, params, name)
        for i, t in enumerate(inputs):
            self.graph.add_edge(t.node, node, t.idx, i)
        from .ops.base import get_op_def

        out_specs = get_op_def(op_type).infer_output_specs(params, [t.spec for t in inputs])
        return [Tensor(self, node, i, s) for i, s in enumerate(out_specs)]

    def _one(self, *args, **kw) -> Tensor:
        return self._add(*args, **kw)[0]

    # ----------------------------------------------------- tensor creation
    def create_tensor(self, shape: Sequence[int], dtype: DataType = DataType.FLOAT, name: str = "") -> Tensor:
        """An input placeholder (reference: FFModel::create_tensor)."""
        params = io_ops.InputParams(tuple(int(s) for s in shape), dtype, self._num_inputs)
        self._num_inputs += 1
        return self._one(OpType.INPUT, params, [], name=name or f"input{params.input_index}")

    def create_weight(self, shape: Sequence[int], dtype: DataType = DataType.FLOAT, initializer: str = "glorot_uniform", name: str = "") -> Tensor:
        params = io_ops.WeightParams(tuple(int(s) for s in shape), dtype, initializer)
        return self._one(OpType.WEIGHT, params, [], name=name)

    # ------------------------------------------------------------- layers
    @staticmethod
    def _acti(activation) -> ActiMode:
        """Accept ActiMode, its string value ("relu"), or None."""
        if activation is None:
            return ActiMode.NONE
        if isinstance(activation, ActiMode):
            return activation
        return ActiMode(activation)

    def dense(
        self,
        input: Tensor,
        out_dim: int,
        activation: ActiMode = ActiMode.NONE,
        use_bias: bool = True,
        datatype: Optional[DataType] = None,
        kernel_initializer: str = "glorot_uniform",
        bias_initializer: str = "zeros",
        name: str = "",
    ) -> Tensor:
        # datatype None inherits the input dtype (the reference's DT_NONE
        # default, model.h dense) — a bf16 model's dense layers must not
        # silently compute and store f32 because the caller omitted it
        p = linear_mod.LinearParams(
            out_dim, use_bias, self._acti(activation), datatype or input.dtype,
            kernel_initializer, bias_initializer,
        )
        return self._one(OpType.LINEAR, p, [input], name=name)

    def conv2d(
        self,
        input: Tensor,
        out_channels: int,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int,
        padding_w: int,
        activation: ActiMode = ActiMode.NONE,
        groups: int = 1,
        use_bias: bool = True,
        name: str = "",
    ) -> Tensor:
        p = conv_mod.Conv2DParams(
            out_channels,
            (kernel_h, kernel_w),
            (stride_h, stride_w),
            (padding_h, padding_w),
            groups,
            use_bias,
            self._acti(activation),
            input.dtype,
        )
        return self._one(OpType.CONV2D, p, [input], name=name)

    def pool2d(
        self,
        input: Tensor,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int,
        padding_w: int,
        pool_type: PoolType = PoolType.MAX,
        activation: ActiMode = ActiMode.NONE,
        name: str = "",
    ) -> Tensor:
        p = conv_mod.Pool2DParams((kernel_h, kernel_w), (stride_h, stride_w), (padding_h, padding_w), pool_type, self._acti(activation))
        return self._one(OpType.POOL2D, p, [input], name=name)

    def embedding(
        self,
        input: Tensor,
        num_entries: int,
        out_dim: int,
        aggr: AggrMode = AggrMode.NONE,
        datatype: DataType = DataType.FLOAT,
        kernel_initializer: str = "glorot_uniform",
        name: str = "",
    ) -> Tensor:
        p = EmbeddingParams(num_entries, out_dim, aggr, datatype, kernel_initializer)
        return self._one(OpType.EMBEDDING, p, [input], name=name)

    def multihead_attention(
        self,
        query: Tensor,
        key: Tensor,
        value: Tensor,
        embed_dim: int,
        num_heads: int,
        kdim: int = 0,
        vdim: int = 0,
        dropout: float = 0.0,
        bias: bool = False,
        add_bias_kv: bool = False,
        add_zero_attn: bool = False,
        causal: bool = False,
        name: str = "",
    ) -> Tensor:
        if add_bias_kv or add_zero_attn:
            raise NotImplementedError("add_bias_kv / add_zero_attn are not supported")
        p = MultiHeadAttentionParams(embed_dim, num_heads, kdim, vdim, dropout, bias, causal, query.dtype)
        return self._one(OpType.MULTIHEAD_ATTENTION, p, [query, key, value], name=name)

    def rnn(
        self,
        input: Tensor,
        hidden_size: int,
        initial_state: Optional[Tensor] = None,
        activation: ActiMode = ActiMode.TANH,
        name: str = "",
    ) -> Tuple[Tensor, Tensor]:
        """Elman RNN over [B, T, D] -> (sequence [B, T, H], final_h [B, H]).
        Reference: nmt/ RNN mode."""
        from .ops.recurrent import RecurrentParams

        p = RecurrentParams(hidden_size, input.dtype, self._acti(activation))
        ins = [input] + ([initial_state] if initial_state is not None else [])
        outs = self._add(OpType.RNN, p, ins, name=name)
        return outs[0], outs[1]

    def lstm(
        self,
        input: Tensor,
        hidden_size: int,
        initial_h: Optional[Tensor] = None,
        initial_c: Optional[Tensor] = None,
        name: str = "",
    ) -> Tuple[Tensor, Tensor, Tensor]:
        """LSTM over [B, T, D] -> (sequence, final_h, final_c).
        Reference: nmt/lstm.cc (cudnnRNN LSTM mode)."""
        from .ops.recurrent import RecurrentParams

        p = RecurrentParams(hidden_size, input.dtype)
        if initial_c is not None and initial_h is None:
            raise ValueError("lstm: initial_c requires initial_h (pass zeros for h explicitly)")
        ins = [input]
        if initial_h is not None:
            ins.append(initial_h)
            if initial_c is not None:
                ins.append(initial_c)
        outs = self._add(OpType.LSTM, p, ins, name=name)
        return outs[0], outs[1], outs[2]

    def layer_norm(
        self,
        input: Tensor,
        axes: Optional[Sequence[int]] = None,
        elementwise_affine: bool = True,
        eps: float = 1e-5,
        name: str = "",
    ) -> Tensor:
        if axes is None:
            axes = [input.ndim - 1]
        p = LayerNormParams(tuple(axes), elementwise_affine, eps, input.dtype)
        return self._one(OpType.LAYERNORM, p, [input], name=name)

    def batch_norm(self, input: Tensor, relu: bool = True, eps: float = 1e-5, name: str = "") -> Tensor:
        p = BatchNormParams(relu=relu, eps=eps, dtype=input.dtype)
        return self._one(OpType.BATCHNORM, p, [input], name=name)

    def batch_matmul(
        self,
        A: Tensor,
        B: Tensor,
        a_seq_length_dim: int = -1,
        b_seq_length_dim: int = -1,
        name: str = "",
    ) -> Tensor:
        p = BatchMatmulParams(a_seq_length_dim, b_seq_length_dim)
        return self._one(OpType.BATCH_MATMUL, p, [A, B], name=name)

    # --------------------------------------------------------- elementwise
    def _binary(self, op: OpType, x: Tensor, y: Tensor, inplace_a: bool = False, name: str = "") -> Tensor:
        return self._one(op, ElementBinaryParams(op, inplace_a), [x, y], name=name)

    def add(self, x, y, inplace_a=False, name=""):
        return self._binary(OpType.EW_ADD, x, y, inplace_a, name)

    def subtract(self, x, y, inplace_a=False, name=""):
        return self._binary(OpType.EW_SUB, x, y, inplace_a, name)

    def multiply(self, x, y, inplace_a=False, name=""):
        return self._binary(OpType.EW_MUL, x, y, inplace_a, name)

    def divide(self, x, y, inplace_a=False, name=""):
        return self._binary(OpType.EW_DIV, x, y, inplace_a, name)

    def max(self, x, y, inplace_a=False, name=""):
        return self._binary(OpType.EW_MAX, x, y, inplace_a, name)

    def min(self, x, y, inplace_a=False, name=""):
        return self._binary(OpType.EW_MIN, x, y, inplace_a, name)

    def _unary(self, op: OpType, x: Tensor, scalar: float = 0.0, inplace: bool = False, name: str = "") -> Tensor:
        return self._one(op, ElementUnaryParams(op, scalar, inplace), [x], name=name)

    def relu(self, x, inplace=True, name=""):
        return self._unary(OpType.RELU, x, inplace=inplace, name=name)

    def sigmoid(self, x, name=""):
        return self._unary(OpType.SIGMOID, x, name=name)

    def tanh(self, x, name=""):
        return self._unary(OpType.TANH, x, name=name)

    def elu(self, x, inplace=True, name=""):
        return self._unary(OpType.ELU, x, inplace=inplace, name=name)

    def gelu(self, x, name=""):
        return self._unary(OpType.GELU, x, name=name)

    def identity(self, x, name=""):
        return self._unary(OpType.IDENTITY, x, name=name)

    def exp(self, x, name=""):
        return self._unary(OpType.EXP, x, name=name)

    def sin(self, x, name=""):
        return self._unary(OpType.SIN, x, name=name)

    def cos(self, x, name=""):
        return self._unary(OpType.COS, x, name=name)

    def rsqrt(self, x, name=""):
        return self._unary(OpType.RSQRT, x, name=name)

    def pow(self, x, exponent: float, name=""):
        return self._unary(OpType.POW, x, scalar=exponent, name=name)

    def scalar_add(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OpType.SCALAR_ADD, x, scalar=scalar, inplace=inplace, name=name)

    def scalar_sub(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OpType.SCALAR_SUB, x, scalar=scalar, inplace=inplace, name=name)

    def scalar_multiply(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OpType.SCALAR_MUL, x, scalar=scalar, inplace=inplace, name=name)

    def scalar_true_divide(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OpType.SCALAR_TRUE_DIV, x, scalar=scalar, inplace=inplace, name=name)

    # ----------------------------------------------------------- shape ops
    def reshape(self, input: Tensor, shape: Sequence[int], name: str = "") -> Tensor:
        return self._one(OpType.RESHAPE, ReshapeParams(tuple(shape)), [input], name=name)

    def transpose(self, input: Tensor, perm: Sequence[int], name: str = "") -> Tensor:
        return self._one(OpType.TRANSPOSE, TransposeParams(tuple(perm)), [input], name=name)

    def reverse(self, input: Tensor, axis: int, name: str = "") -> Tensor:
        return self._one(OpType.REVERSE, ReverseParams(axis), [input], name=name)

    def flat(self, input: Tensor, name: str = "") -> Tensor:
        return self._one(OpType.FLAT, FlatParams(), [input], name=name)

    def concat(self, tensors: Sequence[Tensor], axis: int, name: str = "") -> Tensor:
        return self._one(OpType.CONCAT, ConcatParams(axis, len(tensors)), list(tensors), name=name)

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]], axis: int, name: str = "") -> List[Tensor]:
        if isinstance(sizes, int):
            total = input.shape[axis]
            if total % sizes != 0:
                raise ValueError(f"split: dim {axis} of size {total} not divisible into {sizes} chunks")
            sizes = [total // sizes] * sizes
        if sum(sizes) != input.shape[axis]:
            raise ValueError(f"split sizes {sizes} do not sum to dim size {input.shape[axis]}")
        return self._add(OpType.SPLIT, SplitParams(tuple(sizes), axis), [input], name=name)

    def cast(self, input: Tensor, dtype: DataType, name: str = "") -> Tensor:
        return self._one(OpType.CAST, CastParams(dtype), [input], name=name)

    # ---------------------------------------------------------------- misc
    def softmax(self, input: Tensor, axis: int = -1, name: str = "") -> Tensor:
        return self._one(OpType.SOFTMAX, SoftmaxParams(axis), [input], name=name)

    def dropout(self, input: Tensor, rate: float, seed: int = 0, name: str = "") -> Tensor:
        return self._one(OpType.DROPOUT, DropoutParams(rate, seed), [input], name=name)

    def gather(self, input: Tensor, index: Tensor, axis: int, name: str = "") -> Tensor:
        return self._one(OpType.GATHER, GatherParams(axis), [input, index], name=name)

    def reduce_sum(self, input: Tensor, axes: Sequence[int], keepdims: bool = False, name: str = "") -> Tensor:
        return self._one(OpType.REDUCE_SUM, ReduceSumParams(tuple(axes), keepdims), [input], name=name)

    def mean(self, input: Tensor, dims: Sequence[int], keepdims: bool = False, name: str = "") -> Tensor:
        return self._one(OpType.MEAN, MeanParams(tuple(dims), keepdims), [input], name=name)

    # ----------------------------------------------------------- MoE layers
    def top_k(self, input: Tensor, k: int, sorted: bool = True, name: str = "") -> Tuple[Tensor, Tensor]:
        outs = self._add(OpType.TOPK, TopKParams(k, sorted), [input], name=name)
        return outs[0], outs[1]

    def group_by(
        self, input: Tensor, assign: Tensor, n: int, alpha: float, stacked: bool = False, name: str = ""
    ) -> Union[List[Tensor], Tensor]:
        outs = self._add(OpType.GROUP_BY, GroupByParams(n, alpha, stacked), [input, assign], name=name)
        return outs[0] if stacked else outs

    def experts(
        self,
        grouped: Tensor,
        num_exp: int,
        hidden_size: int,
        out_dim: int,
        activation: ActiMode = ActiMode.RELU,
        name: str = "",
    ) -> Tensor:
        """Batched expert FFN over stacked [n, cap, D] (TPU-native: the
        expert dim shards over the mesh for real expert parallelism)."""
        from .ops.moe_ops import ExpertsParams

        p = ExpertsParams(num_exp, hidden_size, out_dim, activation, grouped.dtype)
        return self._one(OpType.EXPERTS, p, [grouped], name=name)

    def aggregate(
        self, gate_preds: Tensor, gate_assign: Tensor, exp_preds: Sequence[Tensor], n: int, lambda_bal: float, name: str = ""
    ) -> Tensor:
        p = AggregateParams(n, lambda_bal)
        return self._one(OpType.AGGREGATE, p, [gate_preds, gate_assign] + list(exp_preds), name=name)

    def aggregate_spec(
        self, gate_preds: Tensor, gate_assign: Tensor, exp_preds: Sequence[Tensor], n: int, lambda_bal: float, name: str = ""
    ) -> Tensor:
        p = AggregateSpecParams(n, lambda_bal)
        return self._one(OpType.AGGREGATE_SPEC, p, [gate_preds, gate_assign] + list(exp_preds), name=name)

    def cache(self, input: Tensor, num_batches: int = 1, trigger_threshold: float = 0.0, name: str = "") -> Tensor:
        return self._one(OpType.CACHE, CacheParams(num_batches, trigger_threshold), [input], name=name)

    def moe(
        self,
        input: Tensor,
        num_exp: int,
        num_select: int,
        expert_hidden_size: int,
        alpha: float = 2.0,
        lambda_bal: float = 0.04,
        batched: bool = True,
        name: str = "",
    ) -> Tensor:
        """Composite MoE layer (reference: FFModel::moe, src/ops/moe.cc:20):
        dense gate -> topk -> group_by -> experts -> aggregate.

        batched=True (default, TPU-native): ONE stacked dispatch + ONE
        batched Experts op — constant HLO size at any expert count, and
        the expert dim shards over the mesh (real expert parallelism).
        batched=False reproduces the reference's n separate per-expert
        Dense ops."""
        gate = self.dense(input, num_exp, ActiMode.NONE, name=f"{name}_gate")
        gate = self.softmax(gate, name=f"{name}_gate_sm")
        topk_vals, topk_idx = self.top_k(gate, num_select, name=f"{name}_topk")
        if batched:
            grouped = self.group_by(input, topk_idx, num_exp, alpha, stacked=True, name=f"{name}_groupby")
            expert_out = self.experts(
                grouped, num_exp, expert_hidden_size, input.shape[-1], name=f"{name}_experts"
            )
            return self.aggregate(topk_vals, topk_idx, [expert_out], num_exp, lambda_bal, name=f"{name}_agg")
        grouped = self.group_by(input, topk_idx, num_exp, alpha, name=f"{name}_groupby")
        expert_outs = []
        for e, g in enumerate(grouped):
            h = self.dense(g, expert_hidden_size, ActiMode.RELU, name=f"{name}_exp{e}")
            h = self.dense(h, input.shape[-1], ActiMode.NONE, name=f"{name}_exp{e}_out")
            expert_outs.append(h)
        return self.aggregate(topk_vals, topk_idx, expert_outs, num_exp, lambda_bal, name=f"{name}_agg")

    def residual(self, x: Tensor, fx: Tensor, name: str = "") -> Tensor:
        return self.add(x, fx, name=name)

    # -------------------------------------------------------------- compile
    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type: Optional[LossType] = None,
        metrics: Sequence[MetricsType] = (),
        comp_mode: CompMode = CompMode.TRAINING,
        outputs: Optional[Sequence[Tensor]] = None,
        strategy=None,
    ):
        """Search for a parallelization strategy and build the compiled
        executable (reference: FFModel::compile, model.cc:2811 — search
        task, convert_graph_to_operators, NCCL init all collapse into
        strategy selection + one jit)."""
        if optimizer is None:
            optimizer = SGDOptimizer(lr=self.config.learning_rate, weight_decay=self.config.weight_decay)
        self.optimizer = optimizer
        self.loss_type = loss_type
        self.metrics = list(metrics)
        self.comp_mode = comp_mode
        self._outputs = list(outputs) if outputs else [self._default_output()]
        from .parallel.distributed import maybe_initialize_from_env
        from .parallel.mesh import build_mesh
        from .parallel.strategy import data_parallel_strategy

        # multi-host entry (reference: GASNet multi-node; here one process
        # per host joins via jax.distributed when the env declares a job).
        # Must run BEFORE anything touches the backend — config.num_devices
        # may call jax.devices(), and jax.distributed.initialize refuses
        # to run after backend init.
        maybe_initialize_from_env()
        num_devices = self.config.num_devices

        if strategy is not None:
            self.strategy = strategy
        elif self.config.import_strategy_file:
            from .parallel.strategy import ParallelStrategy

            with open(self.config.import_strategy_file) as f:
                self.strategy = ParallelStrategy.from_json(f.read())
        elif self.config.pipeline_stages > 1:
            from .parallel.strategy import pipeline_strategy

            pp = self.config.pipeline_stages
            if num_devices % pp != 0:
                raise ValueError(f"{num_devices} devices not divisible by pipeline_stages={pp}")
            self.strategy = pipeline_strategy(
                self.graph,
                pp=pp,
                dp=num_devices // pp,
                n_microbatches=self.config.pipeline_microbatches,
            )
        elif self.config.only_data_parallel or self.config.search_budget <= 0:
            self.strategy = data_parallel_strategy(self.graph, num_devices)
        else:
            from .search.unity import unity_optimize

            self.strategy, self._search_result = unity_optimize(self.graph, self.config)
            # adopt the rewritten PCG (reference: convert_graph_to_operators
            # model.cc:2856-2858); compute-node guids survive rewrites, so
            # frontend Tensor handles remain valid
            if self._search_result.graph is not None:
                self.graph = self._search_result.graph
        # a strategy built for (or exported from) a DIFFERENT graph has
        # guids matching nothing here; the GSPMD path would silently run
        # fully replicated (every sharding lookup misses) — the bench's
        # tp/hybrid measurements did exactly that until this guard; only
        # the pipeline path's stage_of validation caught its own case.
        # Strategies carry layer names (the reference's strategy files
        # are name-keyed, triton strategy.cc), so a structurally
        # identical rebuild remaps cleanly; anything else is an error.
        remapped = self.strategy.remap_to(self.graph)
        if remapped is None:
            raise ValueError(
                "strategy was built for a different graph: its node guids "
                "match nothing here and name-based remapping failed "
                "(missing or ambiguous layer names); rebuild or re-export "
                "the strategy against THIS model's graph"
            )
        self.strategy = remapped
        if self.config.export_strategy_file:
            with open(self.config.export_strategy_file, "w") as f:
                f.write(self.strategy.to_json())
        if self.config.export_strategy_computation_graph_file:
            with open(self.config.export_strategy_computation_graph_file, "w") as f:
                f.write(self.graph.to_dot())
        self.mesh = build_mesh(self.strategy.axis_sizes)
        self.executor = CompiledExecutor(
            graph=self.graph,
            strategy=self.strategy,
            mesh=self.mesh,
            loss_type=loss_type,
            metric_types=tuple(metrics),
            optimizer=optimizer if comp_mode == CompMode.TRAINING else None,
            outputs=[(t.node.guid, t.idx) for t in self._outputs],
            backend=jax.default_backend(),
            comp_mode=comp_mode,
            remat_blocks=self.config.remat_blocks,
            zero_optimizer=self.config.zero_optimizer,
            grad_accum_steps=self.config.grad_accum_steps,
        )
        self.executor.initialize(jax.random.key(self._seed))
        return self

    def _default_output(self) -> Tensor:
        sinks = self.graph.sink_nodes()
        if len(sinks) != 1:
            raise ValueError(f"model has {len(sinks)} sink nodes; pass outputs= to compile()")
        specs = infer_all_specs(self.graph)
        n = sinks[0]
        return Tensor(self, n, 0, specs[n.guid][0])

    # ----------------------------------------------------------------- fit
    @staticmethod
    def _as_batches(x, y):
        """Normalize dataset inputs: keep real (np/jnp) arrays as-is —
        device-resident data must not bounce through the host — and
        materialize anything else (lists, tuples) as numpy so the
        windowed slicing/reshape paths work on every accepted input."""

        def arr(a):
            return a if isinstance(a, (np.ndarray, jnp.ndarray)) else np.asarray(a)

        xs = [x] if isinstance(x, (np.ndarray, jnp.ndarray)) else list(x)
        return [arr(xx) for xx in xs], arr(y)

    @staticmethod
    def _iter_windows(xs, y, bs: int, steps: int, tw: int):
        """Yield (step, k, window_xs, window_y): full tw-step windows as
        stacked [k, bs, ...] arrays, tail steps (k == 1) as plain
        batches for the already-compiled eager program."""
        step = 0
        while step < steps:
            k = tw if steps - step >= tw else 1
            lo = step * bs
            if k > 1:
                hi = lo + k * bs
                yield step, k, [
                    xx[lo:hi].reshape((k, bs) + xx.shape[1:]) for xx in xs
                ], y[lo:hi].reshape((k, bs) + y.shape[1:])
            else:
                yield step, 1, [jnp.asarray(xx[lo:lo + bs]) for xx in xs], jnp.asarray(y[lo:lo + bs])
            step += k

    def fit(
        self,
        x: Union[np.ndarray, Sequence[np.ndarray]],
        y: np.ndarray,
        epochs: Optional[int] = None,
        batch_size: Optional[int] = None,
        verbose: bool = True,
        trace_window: Optional[int] = None,
    ) -> PerfMetrics:
        """Training loop (reference: FFModel.fit flexflow_cffi.py:2044).

        ``trace_window`` > 1 is the analog of the reference's Legion
        iteration tracing (begin_trace/end_trace, flexflow_cffi.py:
        2079-2086): that many steps run as ONE XLA program (lax.scan
        over stacked batches, executor.train_window), paying host
        dispatch once per window. Defaults to FFConfig.trace_window.
        Note: the windowed path derives per-step rng keys by splitting
        one per-window key, so models with rng-dependent training ops
        (dropout) follow a different — equally valid — randomness stream
        than the eager loop; deterministic models train identically.
        """
        assert self.executor is not None, "call compile() first"
        xs, y = self._as_batches(x, y)
        epochs = epochs or self.config.epochs
        bs = batch_size or self.config.batch_size
        tw = max(1, trace_window or self.config.trace_window)
        n = xs[0].shape[0]
        steps = n // bs
        rng = jax.random.key(self._seed + 1)
        perf = PerfMetrics()
        if self.config.profiling:  # reference: --profiling per-op timings
            self.profile(x=[xx[:bs] for xx in xs])
        interval = max(1, self.config.printing_interval)
        # fit()'s ELAPSED TIME report mirrors the reference CLI's wall
        # time; the training loop is not scheduler-plane code and has
        # no injectable clock to honor
        t0 = time.time()  # flexlint: disable=clock-discipline
        for epoch in range(epochs):
            # full windows run traced; tail steps (k == 1) run eagerly on
            # the already-compiled single-step program rather than paying
            # a whole extra XLA compile for a once-per-epoch window size
            for step, k, batch_x, batch_y in self._iter_windows(xs, y, bs, steps, tw):
                rng, sub = jax.random.split(rng)
                if k > 1:
                    wmets = self.executor.train_window(batch_x, batch_y, sub)
                    host = {kk: np.asarray(v) for kk, v in wmets.items()}
                    for i in range(k):
                        perf.update({kk: float(v[i]) for kk, v in host.items() if kk != "loss"})
                        if verbose and (step + i) % interval == 0:
                            print(
                                f"epoch {epoch} step {step + i}/{steps} "
                                f"loss {float(host.get('loss', np.zeros(k))[i]):.4f} acc {perf.accuracy:.4f}"
                            )
                else:
                    mets = self.executor.train_batch(batch_x, batch_y, sub)
                    perf.update({kk: float(v) for kk, v in mets.items() if kk != "loss"})
                    if verbose and step % interval == 0:
                        loss = float(mets.get("loss", 0.0))
                        print(f"epoch {epoch} step {step}/{steps} loss {loss:.4f} acc {perf.accuracy:.4f}")
        elapsed = time.time() - t0  # flexlint: disable=clock-discipline
        thru = epochs * steps * bs / max(1e-9, elapsed)
        if verbose:
            print(f"ELAPSED TIME = {elapsed:.4f}s THROUGHPUT = {thru:.2f} samples/s")
        self.last_elapsed = elapsed
        self.last_throughput = thru
        return perf

    def evaluate(
        self, x, y, batch_size: Optional[int] = None, trace_window: Optional[int] = None
    ) -> PerfMetrics:
        assert self.executor is not None
        xs, y = self._as_batches(x, y)
        bs = batch_size or self.config.batch_size
        tw = max(1, trace_window or self.config.trace_window)
        steps = xs[0].shape[0] // bs
        perf = PerfMetrics()
        for _, k, batch_x, batch_y in self._iter_windows(xs, y, bs, steps, tw):
            if k > 1:
                wmets = self.executor.eval_window(batch_x, batch_y)
                host = {kk: np.asarray(v) for kk, v in wmets.items()}
                for i in range(k):
                    perf.update({kk: float(v[i]) for kk, v in host.items() if kk != "loss"})
            else:
                mets = self.executor.eval_batch(batch_x, batch_y)
                perf.update({kk: float(v) for kk, v in mets.items() if kk != "loss"})
        return perf

    def predict(self, x) -> jax.Array:
        xs = [x] if isinstance(x, (np.ndarray, jnp.ndarray)) else list(x)
        return self.executor.predict([jnp.asarray(xx) for xx in xs])[0]

    # --------------------------------------------- checkpoint / dataloader
    def save_checkpoint(self, path: str, step: int = 0) -> None:
        """Save weights + optimizer state + strategy (new capability vs the
        reference, which only had weight get/set — SURVEY.md §5)."""
        from .runtime.checkpoint import save_checkpoint

        assert self.executor is not None, "compile() first"
        save_checkpoint(path, self.executor, step=step, strategy=self.strategy)

    def load_checkpoint(self, path: str) -> int:
        from .runtime.checkpoint import restore_checkpoint

        assert self.executor is not None, "compile() first"
        return restore_checkpoint(path, self.executor)

    def create_data_loader(self, x, y, batch_size: Optional[int] = None, shuffle: bool = True):
        """Reference: FFModel.create_data_loader (flexflow_cffi.py:2178).
        Batches land pre-sharded per the compiled strategy when available."""
        from .runtime.dataloader import DataLoader

        xs = [x] if isinstance(x, (np.ndarray, jnp.ndarray)) else list(x)
        shardings = label_sharding = None
        if self.executor is not None:
            shardings, label_sharding = self.executor.input_shardings()
        return DataLoader(
            xs,
            y,
            batch_size or self.config.batch_size,
            shuffle=shuffle,
            shardings=shardings,
            label_sharding=label_sharding,
        )

    def profile(self, x=None, verbose: bool = True):
        """Per-op forward timing table (reference: --profiling cudaEvent
        brackets in every kernel, e.g. linear_kernels.cu:95-118)."""
        from .runtime.profiling import format_profiles, profile_step

        assert self.executor is not None, "call compile() first"
        if x is None:
            specs = infer_all_specs(self.graph)
            ins = sorted(
                (n for n in self.graph.nodes.values() if n.op_type == OpType.INPUT),
                key=lambda n: n.params.input_index,
            )
            rs = np.random.RandomState(0)
            x = []
            for n in ins:
                s = specs[n.guid][0]
                if s.dtype.jnp in (jnp.int32, jnp.int64):
                    x.append(rs.randint(0, 2, s.shape).astype(np.int32))
                else:
                    x.append(rs.randn(*s.shape).astype(np.float32))
        profiles = profile_step(self.executor, x)
        if verbose:
            print(format_profiles(profiles))
        return profiles

    def recompile_on_condition(self, trigger, alter):
        """Reference: FFModel::recompile_on_condition (model.cc:2430)."""
        from .runtime.recompile import RecompileState

        assert self.executor is not None, "call compile() first"
        return RecompileState(trigger, alter, self)

    # ------------------------------------------------------- introspection
    def parallel_tensor(self, tensor: Tensor):
        """How ``tensor`` is sharded under the compiled strategy
        (reference: ParallelTensorBase's per-dim degree / replica dims,
        parallel_tensor.h:36-71 — here surfaced from the strategy's
        PartitionSpecs instead of Legion partitions)."""
        from .core.parallel_tensor import view_from_spec

        assert self.strategy is not None, "compile() first"
        sh = self.strategy.node_shardings.get(tensor.node.guid)
        spec = self.strategy.output_spec(tensor.node.guid, tensor.idx)
        return view_from_spec(
            tensor.spec,
            spec,
            self.strategy.axis_sizes,
            machine_view_hash=sh.machine_view_hash if sh else 0,
        )

    def parallel_weight(self, tensor: Tensor, name: str):
        """Sharding view of one of ``tensor``'s op's weights."""
        from .core.parallel_tensor import view_from_spec
        from .ops.base import get_op_def

        assert self.strategy is not None, "compile() first"
        node = tensor.node
        specs = infer_all_specs(self.graph)
        in_specs = [specs[e.src][e.src_idx] for e in self.graph.in_edges(node)]
        wspecs = {w.name: w for w in get_op_def(node.op_type).weight_specs(node.params, in_specs)}
        if name not in wspecs:
            raise KeyError(f"op {node} has no weight {name!r}; has {sorted(wspecs)}")
        sh = self.strategy.node_shardings.get(node.guid)
        return view_from_spec(
            wspecs[name].spec,
            self.strategy.weight_spec(node.guid, name),
            self.strategy.axis_sizes,
            machine_view_hash=sh.machine_view_hash if sh else 0,
        )

    def get_weight(self, tensor: Tensor, name: str) -> np.ndarray:
        """Gather one weight to host (reference:
        ParallelTensorBase::get_tensor, parallel_tensor.h:165-169, the
        cffi get-weights path)."""
        assert self.executor is not None, "compile() first"
        key = f"{tensor.node.op_type.value}_{tensor.node.guid}"
        have = []
        for store in (self.executor.params, self.executor.state):
            group = store.get(key) or {}
            if name in group:
                return np.asarray(jax.device_get(group[name]))
            have.extend(group)
        raise KeyError(f"no weight {name!r} on {key}; has {sorted(have)}")

    def set_weight(self, tensor: Tensor, name: str, value) -> None:
        """Write one weight from host data, preserving its sharding
        (reference: ParallelTensorBase::set_tensor)."""
        assert self.executor is not None, "compile() first"
        key = f"{tensor.node.op_type.value}_{tensor.node.guid}"
        for store in (self.executor.params, self.executor.state):
            group = store.get(key)
            if group is not None and name in group:
                cur = group[name]
                arr = np.asarray(value, dtype=np.asarray(cur).dtype)
                if arr.shape != cur.shape:
                    raise ValueError(f"shape {arr.shape} != {cur.shape} for {key}.{name}")
                group[name] = jax.device_put(arr, cur.sharding)
                return
        raise KeyError(f"no weight {name!r} on {key}")

    def get_output(self) -> Tensor:
        return self._outputs[0] if self._outputs else self._default_output()

    def num_layers(self) -> int:
        return sum(1 for n in self.graph.nodes.values() if n.op_type != OpType.INPUT)
