"""Continuous batching: iteration-level scheduling of generation
requests (Orca, OSDI'22) over the block KV cache.

Unlike the request-level DynamicBatcher (serving/batcher.py), which
holds a batch's composition fixed for a whole device call, generation
is scheduled per *iteration*: every ``step()`` runs ONE decode across
the engine's fixed batch slots, and between steps the batch recomposes
freely —

* **join-mid-flight**: a queued request is admitted (FCFS) the moment a
  slot AND enough cache blocks are free; it prefils and decodes
  alongside sequences that are hundreds of tokens in;
* **free-on-finish**: a sequence hitting EOS / max-tokens / its
  deadline releases its blocks in the same step, so capacity returns
  immediately instead of at batch boundaries;
* **preempt-by-recompute**: if the cache cannot grow a running
  sequence, the youngest running sequence is evicted — blocks freed,
  prompt + generated-so-far re-queued at the FRONT — and later
  re-prefilled (vLLM's recompute preemption). Seeded sampling keys are
  indexed by generated-token count, so a preempted request's token
  stream continues exactly where it left off.

* **speculation-aware stepping**: a request submitted with a
  SpeculationConfig drafts up to k tokens per iteration (n-gram or
  draft-model drafter) and the step verifies every slot's window in ONE
  fixed-shape engine.verify call — up to k+1 tokens emitted per
  sequence per step, exactly (greedy output is token-for-token the
  non-speculative stream). The scheduler allocates blocks for the whole
  window up front, caps a window's k when the allocator is tight
  (before ever preempting), trims unused trailing blocks after partial
  acceptance, truncates emission at mid-window EOS / budget, and adapts
  each request's k against its acceptance EMA. Speculative and plain
  requests mix freely in one batch (a plain request is a zero-draft
  window whose sampling is bit-identical to the decode step).

Resilience mirrors PR 1's serving semantics: bounded queue
(QueueFullError), per-request deadlines (DeadlineExceededError before
OR during generation), retry-with-backoff for TransientDeviceError,
and a circuit breaker around device steps — all on an injectable clock
so chaos tests run on virtual time. Fault sites: ``generation.prefill``,
``generation.decode_step``, ``generation.verify``, and
``generation.journal_replay`` (runtime/faults.py).

* **self-healing** (recovery.py): every admitted stream is entered in a
  :class:`GenerationJournal`; batched device steps run under an
  :class:`EngineSupervisor` that absorbs one-off crashes (single step
  retry), quarantines poisoned requests (per-slot NaN blame vector from
  the jitted steps, or crash bisection with subset probes) so one bad
  request can no longer fail the whole batch, and recovers engine-level
  failures by ``engine.reset()`` + journal replay over the
  preempt-by-recompute path — byte-exact, because sampling keys index
  by generated-token count. A :class:`StepWatchdog` heartbeat around
  device calls detects stalled steps, trips the breaker (honest
  health), and drives the same restart. An exhausted restart budget
  fails *running* streams with a typed EngineFailedError; queued
  requests are held behind the breaker, never failed with the engine's
  internal error.

* **overlapped decode** (ISSUE 13, on by default; ``overlap=False``
  restores the sequential loop bit-for-bit): steady-state decode runs
  as a two-deep software pipeline — step N+1's fixed-shape jit is
  dispatched (sampled tokens carried device-resident from step N's
  output) while step N's device work completes, token readback is
  double-buffered, and host bookkeeping runs inside N+1's execute
  window. An in-flight *frontier* of at most one outstanding step
  drains deterministically on every non-steady event (admission,
  EOS/finish, preemption pressure, cancel/deadline, speculation,
  crash, watchdog trip, shutdown), so supervisor bisection, NaN blame,
  journal replay, and fleet failover observe exactly the sequential
  semantics — token streams are byte-identical with overlap on/off
  (tests/test_overlap.py). The speculative verify path stays
  sequential by design: drafting needs step N's committed tokens on
  the host, so there is no overlap window.

The scheduler is synchronous-by-design: ``step()`` does one iteration
and returns, so property tests drive it deterministically; ``start()``
wraps it in a background thread for serving.
"""
from __future__ import annotations

import contextlib
import dataclasses
import itertools
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import (
    NULL_JOURNEY,
    NULL_TRACE,
    CacheTelemetry,
    FlightRecorder,
    JourneyContext,
    JourneyRecorder,
    JourneyStats,
    RequestTrace,
    SLOMonitor,
    StepAnatomy,
    TraceRing,
    next_request_id,
)
from ..runtime import faults
from ..serving.overload import OverloadConfig, OverloadController, Priority
from ..serving.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    QueueFullError,
    RetryPolicy,
    ShuttingDownError,
)
from ..serving.stats import (
    ConstrainedStats,
    GoodputStats,
    RecoveryStats,
    ServingStats,
    SpeculationStats,
    TokenRate,
)
from .constrained.errors import MaskDeadEndError
from .engine import GenerationEngine, SamplingParams
from .recovery import (
    EngineFailedError,
    EngineSupervisor,
    GenerationJournal,
    PoisonedRequestError,
    RecoveryPolicy,
    StalledStepError,
    StepWatchdog,
    WatchdogPolicy,
)
from .speculative.drafter import SpeculationConfig, build_drafter

_END = object()  # token-stream sentinel


class GenerationHandle:
    """Caller's view of one request: a Future of the generated token
    list plus a per-token stream."""

    def __init__(self, request: "Request"):
        self._request = request
        self.future: Future = Future()
        self._tokens: "queue.Queue" = queue.Queue()
        # settle arbitration: the loop and watchdog threads race to
        # finish/fail a handle; the claim winner owns BOTH the future
        # and the trace, and closes the trace BEFORE the future settles
        # so a client woken by the future never reads a half-open trace
        self._settle_lock = threading.Lock()
        self._settled = False

    def _claim(self) -> bool:
        with self._settle_lock:
            if self._settled or self.future.done():
                return False
            self._settled = True
            return True

    # ----------------------------------------------------------- caller
    def done(self) -> bool:
        return self.future.done()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        return self.future.result(timeout=timeout)

    def cancel(self) -> None:
        """Ask the scheduler to drop this request at its next step."""
        self._request.cancelled = True

    @property
    def trace(self):
        """The request's RequestTrace (NULL_TRACE when observability is
        off) — transports read it to embed postmortems in error
        responses and annotate the transport kind."""
        return self._request.trace

    def trace_dict(self) -> dict:
        return self._request.trace.to_dict()

    def tokens(self, timeout: Optional[float] = None):
        """Iterate generated tokens as they are produced. Raises the
        request's failure if it errors mid-stream."""
        while True:
            item = self._tokens.get(timeout=timeout)
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    # -------------------------------------------------------- scheduler
    def _emit(self, token: int) -> None:
        self._tokens.put(token)

    def _finish(self, tokens: List[int]) -> None:
        # idempotent under races: the watchdog thread may reap a
        # deadline while the loop thread is deciding the same request's
        # fate — the loser of the claim must not propagate
        # InvalidStateError into (and kill) the loop
        if not self._claim():
            return
        # trace first: a client thread woken by the settling future may
        # immediately read trace_dict() for its response
        self._request._trace_done("completed", None)
        try:
            self.future.set_result(tokens)
        except Exception:
            return
        self._tokens.put(_END)

    def _fail(self, err: BaseException) -> bool:
        """Returns True only if THIS call failed the handle — losers of
        the loop/watchdog race must not double-count in stats."""
        if not self._claim():
            return False
        # the claim winner also closes the trace (BEFORE the future
        # settles), so every terminal path — loop, watchdog reap,
        # shutdown — lands exactly one finished trace in the ring and
        # error responses never embed a half-open trace
        self._request._trace_done(type(err).__name__, err)
        try:
            self.future.set_exception(err)
        except Exception:
            return False
        self._tokens.put(err)
        self._tokens.put(_END)
        return True


class Request:
    """One generation request. ``prompt`` may grow on preemption (the
    generated prefix is folded in for recompute); ``n_generated`` is the
    TOTAL generated count across preemptions, which also indexes the
    per-request sampling key stream. Ids come from the process-wide
    obs counter so a trace id names exactly one request across every
    serving path (sampling never mixes the id in — determinism is
    seed-only)."""

    def __init__(
        self,
        prompt: List[int],
        sampling: SamplingParams,
        deadline: Optional[float] = None,
        speculation: Optional[SpeculationConfig] = None,
        drafter=None,
        priority: str = Priority.STANDARD,
        grammar=None,
        response_format: Optional[Dict] = None,
    ):
        self.id = next_request_id()
        # overload control (serving/overload.py): the priority class
        # orders admission, preemption victims, and shed order; the
        # release hook returns this request's AdaptiveLimiter slot on
        # terminal settle (set at submit, fired exactly once by the
        # handle's settle-race winner)
        self.priority = priority
        self.priority_rank = Priority.rank(priority)
        self.overload_release: Optional[Callable[[], None]] = None
        # observability: the scheduler swaps in a live RequestTrace (+
        # destination ring) at submit when tracing is enabled
        self.trace = NULL_TRACE
        self.trace_ring = None
        # fleet-wide journey (ISSUE 20): the cross-replica trace context
        # travels ON the request, exactly like the trace — minted (or
        # joined from a remote traceparent) at submit, retargeted at the
        # adopting scheduler on failover/handoff, restored from the WAL
        # admission snapshot on warm restart
        self.journey = NULL_JOURNEY
        self.original_prompt = list(prompt)
        self.prompt = list(prompt)  # prompt + recomputed prefix
        self.sampling = sampling
        self.deadline = deadline  # absolute, scheduler clock
        self.submitted_at = 0.0  # stamped by the scheduler
        # effective budget, possibly clamped to the cache room the
        # scheduler can actually give this sequence
        self.max_new = sampling.max_new_tokens
        self.generated: List[int] = []  # tokens generated so far (total)
        self.cancelled = False
        self.preemptions = 0
        self.replays = 0  # journal-replay recoveries this stream rode out
        self.handle = GenerationHandle(self)
        # seed-only (no request-id mixing): the same seed + prompt +
        # params must reproduce the same tokens, run to run (with
        # temperature speculation: under the same window layout — see
        # speculative/sampling.py on realization-invariance). Folded as
        # 32 bits to match the decode/verify jits' in-jit derivation
        # (engine.derive_keys): prefill and decode MUST agree or a
        # preemption-recompute would fork the stream for seeds outside
        # [0, 2**32); in-range seeds are unchanged.
        self.base_key = jax.random.key(sampling.seed & 0xFFFFFFFF)
        # speculation state: live k adapts inside [1, config.k]; the
        # drafter is a pure function of the prefix, so preemption needs
        # no drafter checkpointing
        self.speculation = speculation if (speculation and speculation.enabled) else None
        self.drafter = drafter if self.speculation else None
        self.spec_k = speculation.k if self.speculation else 0
        self.acc_ema: Optional[float] = None
        self.spec_proposed = 0
        self.spec_accepted = 0
        # capacity observability: admission-wait blame (set while the
        # FCFS head is blocked on cache blocks) and the terminal
        # SLO/goodput sink (set by the scheduler when tracing is on)
        self.cache_wait_start: Optional[float] = None
        self.cache_wait_short = 0
        self.slo_sink = None
        # disaggregated serving: a KVHandoffPayload attached by
        # adopt(imported=...) — the decode-side admission imports these
        # blocks instead of recompute-prefilling; cleared on use (or on
        # rejection, which falls back to recompute)
        self.imported_kv = None
        # constrained decoding (ISSUE 18): the compiled TokenDFA shared
        # across requests under the same grammar, and the per-request
        # automaton cursor. mask_state is rebuilt at admission by
        # re-advancing over `generated` (the journal-replay discipline:
        # preempt-recompute, restart, and failover all reconstruct the
        # same state from the same tokens), so preemption/adopt just
        # drop it. mask_error is a deferred PoisonedRequestError the
        # step loop sweeps into a per-request quarantine — advance
        # failures deep in emit paths must fail ONE stream, not the
        # batch.
        self.grammar = grammar
        self.response_format = response_format
        self.mask_state = None
        self.mask_error: Optional[PoisonedRequestError] = None
        # durable serving (ISSUE 19): the stream's identity in the WAL
        # and on GET /v2/generate/resume/{id} — stable across process
        # restarts (a warm restart pins the journaled id onto the
        # re-admitted request, while self.id is process-local). Set by
        # the DurableJournal at first admission; None when the stream
        # is not durably journaled.
        self.durable_id: Optional[str] = None

    @property
    def n_generated(self) -> int:
        return len(self.generated)

    def _trace_done(self, outcome: str, err: Optional[BaseException]) -> None:
        """Terminal trace hook, called by the handle's settle-race
        winner (exactly once per request)."""
        # limiter slot back first (claim-protected, so exactly once),
        # and unconditionally — observability off must not leak slots
        release, self.overload_release = self.overload_release, None
        if release is not None:
            try:
                release()
            except Exception:
                pass  # limiter accounting must never poison a settle path
        if self.trace is not NULL_TRACE:
            self.trace.mark_finish(outcome, err)
            if self.trace_ring is not None:
                self.trace_ring.add(self.trace)
            if self.slo_sink is not None:
                try:
                    self.slo_sink(self)
                except Exception:
                    pass  # SLO accounting must never poison a settle path
        if self.journey is not NULL_JOURNEY:
            # terminal hop: the span carries the full RequestTrace
            # decomposition + event log, so the stitched journey holds
            # the per-replica story without a second lookup. Recorded
            # even when the trace is NULL (a warm-restored stream has a
            # journey but no trace) — the journey must still end.
            try:
                tr = {} if self.trace is NULL_TRACE else self.trace.to_dict()
                self.journey.hop(
                    "finish", outcome=outcome,
                    n_generated=len(self.generated),
                    queue_time_s=tr.get("queue_time_s"),
                    ttft_s=tr.get("ttft_s"), tpot_s=tr.get("tpot_s"),
                    total_s=tr.get("total_s"),
                    preemptions=tr.get("preemptions"),
                    replays=tr.get("replays"),
                    error=None if err is None else str(err),
                    trace_events=tr.get("events"),
                )
            except Exception:
                pass  # journeys must never poison a settle path

    def sample_key(self) -> jax.Array:
        """Key for the NEXT token: indexed by generated count, so a
        recomputed request continues its exact sampling stream. Used by
        the (admission-time) prefill only — the hot decode/verify steps
        derive the same keys IN-JIT from (seed, count) via
        engine.derive_keys, deleting the host key-assembly phase."""
        return jax.random.fold_in(self.base_key, self.n_generated)

    def update_speculation(self, proposed: int, accepted: int) -> None:
        """Fold one verification window into the adaptive-k state."""
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        cfg = self.speculation
        if cfg is None or proposed <= 0:
            return
        rate = accepted / proposed
        self.acc_ema = (
            rate
            if self.acc_ema is None
            else cfg.ema_alpha * rate + (1.0 - cfg.ema_alpha) * self.acc_ema
        )
        if not cfg.adaptive:
            return
        if self.acc_ema < cfg.low_acceptance:
            self.spec_k = max(1, self.spec_k - 1)
        elif self.acc_ema >= cfg.high_acceptance:
            self.spec_k = min(cfg.k, self.spec_k + 1)

    def finished(self) -> bool:
        if self.n_generated >= self.max_new:
            return True
        eos = self.sampling.eos_id
        return eos is not None and bool(self.generated) and self.generated[-1] == eos


class _Running:
    """Slot-resident state for an admitted request."""

    __slots__ = (
        "req", "slot", "blocks", "cached_len", "admitted_seq", "step_k",
        "shared_idx", "shared_entries",
    )

    def __init__(self, req: Request, slot: int, blocks: List[int], cached_len: int, admitted_seq: int,
                 shared_idx=None, shared_entries=None):
        self.req = req
        self.slot = slot
        self.blocks = blocks
        self.cached_len = cached_len  # cache positions written so far
        self.admitted_seq = admitted_seq  # admission order, for LIFO preemption
        self.step_k = 0  # drafts planned for THIS step (<= req.spec_k)
        # prefix caching (generation/prefix.py): table positions whose
        # blocks are index-owned (refcounted, immutable, freed by the
        # index — never by this sequence) and the held entries
        self.shared_idx = shared_idx if shared_idx is not None else set()
        self.shared_entries = shared_entries if shared_entries is not None else []


class _Frontier:
    """The overlap pipeline's in-flight frontier: AT MOST ONE
    outstanding decode step. Captures the dispatch-time slot states and
    the host-side argument arrays (reused — bumped by one — for the
    next dispatch, so steady state rebuilds nothing), plus the
    heartbeat seq the watchdog/stall bookkeeping is keyed on. ``seq0``
    is the scheduler's heartbeat seq just BEFORE this dispatch: a stall
    flagged on any later seq belongs to this frontier chain and voids
    its (late) result. Loop-thread only."""

    __slots__ = (
        "handle", "states", "positions", "active", "temps", "top_ks",
        "seeds", "counts", "tables", "sig", "hb_seq", "seq0",
    )

    def __init__(self, handle, states, positions, active, temps, top_ks,
                 seeds, counts, tables, sig, hb_seq, seq0):
        self.handle = handle
        self.states = states
        self.positions = positions
        self.active = active
        self.temps = temps
        self.top_ks = top_ks
        self.seeds = seeds
        self.counts = counts
        self.tables = tables
        self.sig = sig
        self.hb_seq = hb_seq
        self.seq0 = seq0


class ContinuousBatchingScheduler:
    def __init__(
        self,
        engine: GenerationEngine,
        *,
        max_queue: int = 256,
        clock: Callable[[], float] = time.monotonic,
        breaker: Optional[CircuitBreaker] = None,
        retry: Optional[RetryPolicy] = None,
        idle_wait_s: float = 0.002,
        speculation: Optional[SpeculationConfig] = None,
        draft_params=None,
        recovery: Optional[RecoveryPolicy] = None,
        watchdog: Optional[WatchdogPolicy] = None,
        observability: bool = True,
        journeys: Optional[bool] = None,
        trace_ring_size: int = 256,
        flight_capacity: int = 512,
        trace_progress_every: int = 8,
        slo_objectives=None,
        pressure_threshold: float = 0.10,
        fault_scope: Optional[str] = None,
        overlap: Optional[bool] = None,
        overload: Optional[OverloadConfig] = None,
    ):
        self.engine = engine
        # fleet integration (serving/fleet.py): fault_scope tags every
        # step's injection sites with this replica's id (so chaos plans
        # can target ONE replica); failover_sink, when set, receives
        # every live request instead of a terminal EngineFailedError
        # when the restart budget exhausts — the fleet journal-replays
        # them onto surviving replicas via adopt()
        self.fault_scope = fault_scope
        self.failover_sink: Optional[Callable] = None
        # disaggregated serving: when set (prefill-pool replicas only),
        # admission ends at the first token — the prompt's KV packs into
        # the wire format and the (request, payload) pair goes to the
        # sink for transfer to the decode pool instead of occupying a
        # decode slot here
        self.handoff_sink: Optional[Callable] = None
        # scheduler-wide default speculation policy (a request's own
        # config overrides it); draft_params backs 'draft_model' drafters
        self.speculation_default = speculation
        self.draft_params = draft_params
        self.max_queue = max_queue
        self.clock = clock
        self.breaker = breaker or CircuitBreaker(clock=clock)
        self.retry = retry or RetryPolicy()
        self.idle_wait_s = idle_wait_s
        self._queue: deque = deque()
        self._running: Dict[int, _Running] = {}  # slot -> state
        self._free_slots = list(range(engine.max_batch_slots - 1, -1, -1))
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._alive = False
        self._draining = False
        self._hard_stop = False
        self._stopped = False  # a stopped (started-then-stopped) scheduler rejects submits
        self._admitted_seq = itertools.count()
        # observability (surfaced on /v2/stats via GenerationModel)
        self.stats = ServingStats()
        self.token_rate = TokenRate(clock=time.monotonic)
        self.preemptions = 0
        self.stats.add_gauge("queue_depth", lambda: len(self._queue))
        self.stats.add_gauge("running", lambda: len(self._running))
        self.stats.add_gauge("tokens_generated", lambda: self.token_rate.total)
        self.stats.add_gauge("tokens_per_s", self.token_rate.rate)
        self.stats.add_gauge("preemptions", lambda: self.preemptions)
        self.stats.add_gauge(
            "cache_blocks_used",
            lambda: self.engine.allocator.num_total - self.engine.allocator.num_free,
        )
        self.stats.add_gauge("cache_blocks_total", lambda: self.engine.allocator.num_total)
        # mesh-native serving (ISSUE 15): mesh geometry + the per-shard
        # cache view — each device holds H/tp heads of every block, so
        # the per-shard byte load is total / tp_degree
        self.stats.add_gauge("mesh_devices", lambda: self.engine.mesh_devices)
        self.stats.add_gauge("tp_degree", lambda: self.engine.tp_degree)
        self.stats.add_gauge(
            "cache_shard_bytes",
            lambda: self.engine.cache_config.total_bytes
            // max(1, self.engine.tp_degree),
        )
        self.stats.add_gauge(
            "cache_shard_heads",
            lambda: self.engine.cache_config.num_heads
            // max(1, self.engine.tp_degree),
        )
        self.stats.add_gauge(
            "cache_occupancy",
            lambda: 1.0 - self.engine.allocator.num_free / max(1, self.engine.allocator.num_total),
        )
        self.stats.add_gauge("recompiles", lambda: sum(self.engine.recompiles().values()))
        self.stats.add_gauge(
            "device_time_s", lambda: sum(self.engine.device_time_s.values())
        )
        # per-request tracing + engine flight recorder (obs/): one
        # RequestTrace per submit, finished traces in a bounded ring
        # (GET /v2/debug/traces); one flight record per scheduler step
        # (GET /v2/debug/timeline, quarantine/restart postmortems).
        # observability=False turns both into no-ops (genbench's
        # tracing-overhead baseline).
        self.obs_enabled = observability
        self.trace_progress_every = trace_progress_every
        self.trace_ring = TraceRing(trace_ring_size)
        # fleet-wide journeys (ISSUE 20): one span ring per replica,
        # stitched across the fleet by JourneyIndex at query time. Rides
        # observability by default; ``journeys=False`` keeps tracing on
        # with journeys off (genbench's journey-overhead baseline). The
        # lane label starts as the fault scope (the replica id in fleet
        # mode) and the fleet renames it at spawn.
        self.journey_stats = JourneyStats()
        self.journey_stats.register_gauges(self.stats)
        journeys_on = observability and (journeys is None or bool(journeys))
        self.journeys: Optional[JourneyRecorder] = (
            JourneyRecorder(
                lane=fault_scope or "local", clock=self.clock,
                stats=self.journey_stats,
            )
            if journeys_on else None
        )
        # dual-clock stamps: records carry t (perf_counter, the
        # timeline's single rendering clock) AND t_sched (this
        # scheduler's possibly-virtual clock) for trace correlation
        self.flight = FlightRecorder(
            capacity=flight_capacity, enabled=observability, sched_clock=self.clock
        )
        self._step_phases: Dict[str, float] = {}
        self._step_info: Dict = {}
        self._step_recorded = False
        # step-anatomy profiler (obs/steptrace.py): first-class host
        # spans + the device execute span per iteration, feeding the
        # flexflow_serving_step_phase_seconds histograms, the
        # device-bubble/overlap-headroom gauges, and the on-demand
        # two-lane capture on GET /v2/debug/anatomy. _step_spans holds
        # THIS iteration's (phase, t0, t1) perf_counter stamps; loop
        # thread only.
        self.anatomy = StepAnatomy(enabled=observability)
        self.anatomy.register_gauges(self.stats)
        self._step_spans: List = []
        self.spec_stats = SpeculationStats()
        self.spec_stats.register_gauges(self.stats)
        # capacity & compute observability (obs/capacity.py, obs/slo.py):
        # block telemetry, MFU/goodput, retrace blame, SLO burn rates —
        # all surfaced as gauges here and on the /v2 debug endpoints
        self.capacity = CacheTelemetry(
            engine.allocator, clock=self.clock,
            pressure_threshold=pressure_threshold, enabled=observability,
            reclaimable=lambda: engine.prefix_cache.evictable_blocks,
        )
        self.capacity.register_gauges(self.stats, lambda: list(self._running.values()))
        # overload control (ISSUE 14, serving/overload.py): priority-
        # aware admission + AIMD concurrency limit (driven by the PR 5
        # queue-time/TTFT windows and the cache-pressure flag above) +
        # the graceful-degradation ladder. The roofline TTFT predictor
        # backs the infeasibility fast-fail: predicted TTFT for a
        # prompt behind `depth` queued requests is (depth + 1) prefills
        # on the PR 7 serving roofline — injectable for pinned tests.
        fm = engine.flops_model
        self.overload = OverloadController(
            clock=self.clock,
            slots=engine.max_batch_slots,
            max_queue=max_queue,
            queue_depth=lambda: len(self._queue),
            queue_p95=lambda: self.stats.window_p95("queue_time"),
            ttft_p95=lambda: self.stats.window_p95("ttft"),
            cache_pressure=lambda: self.capacity.under_pressure,
            ttft_predictor=lambda n, depth: (depth + 1) * fm.roofline_s(
                fm.prefill_flops(n), fm.prefill_bytes(n)
            ),
            stats=self.stats,
            on_transition=self._note_degrade,
            config=overload,
        )
        self.overload.register_gauges(self.stats)
        # per-priority queue accounting (gauge snapshot is racy-ok,
        # like every other scrape-side read of the live deque)
        for p in Priority.ORDER:
            self.stats.add_gauge(
                f"overload_queue_depth_{p}",
                lambda p=p: sum(
                    1 for r in list(self._queue) if r.priority == p
                ),
            )
        # prefix-cache telemetry (flexflow_serving_prefix_cache_*):
        # hit ratio, reuse volume, COW copies, host-tier swaps and
        # residency — counters ride as gauges like the cache_* family
        pc = engine.prefix_cache
        self.stats.add_gauge("prefix_cache_hit_ratio", pc.hit_ratio)
        self.stats.add_gauge(
            "prefix_cache_blocks_reused_total", lambda: pc.blocks_reused_total
        )
        self.stats.add_gauge(
            "prefix_cache_tokens_reused_total", lambda: pc.tokens_reused_total
        )
        self.stats.add_gauge(
            "prefix_cache_cow_copies_total", lambda: pc.cow_copies_total
        )
        self.stats.add_gauge(
            "prefix_cache_swaps_in_total", lambda: pc.swaps_in_total
        )
        self.stats.add_gauge(
            "prefix_cache_swaps_out_total", lambda: pc.swaps_out_total
        )
        self.stats.add_gauge("prefix_cache_host_bytes", lambda: pc.host_bytes)
        self.stats.add_gauge(
            "prefix_cache_resident_blocks", lambda: pc.resident_blocks
        )
        self.stats.add_gauge(
            "prefix_cache_offloaded_blocks", lambda: pc.offloaded_blocks
        )
        self.goodput = GoodputStats()
        self.goodput.register_gauges(self.stats)
        self.slo = SLOMonitor(slo_objectives, clock=self.clock)
        self.slo.register_gauges(self.stats)
        self.stats.add_gauge("mfu", self.engine.mfu)
        self.stats.add_gauge(
            "model_tflops_total", lambda: self.engine.total_flops() / 1e12
        )
        self.stats.add_gauge(
            "achieved_tflops",
            lambda: self.engine.total_flops()
            / max(1e-9, self.engine.total_device_time_s()) / 1e12,
        )
        self.stats.add_gauge("retraces_blamed", self.engine.programs.total_retraces)
        # steady-state retrace blame rides the flight ring next to the
        # step that caused it ("decode retraced: batch 8 -> 9")
        self.engine.programs.on_retrace = self._note_retrace
        # cost-model truth (obs/truth.py): predicted-vs-measured step
        # times as perf_* gauges, drift alarms onto the flight ring,
        # full pairs on GET /v2/debug/predictions
        self.stats.add_gauge(
            "perf_prediction_pairs", lambda: self.engine.ledger.pairs_total
        )
        self.stats.add_gauge(
            "perf_prediction_error_p50",
            lambda: self.engine.ledger.error_summary()["abs_err_p50"],
        )
        self.stats.add_gauge(
            "perf_prediction_error_max",
            lambda: self.engine.ledger.error_summary()["abs_err_max"],
        )
        self.stats.add_gauge(
            "perf_drift_alarms", lambda: self.engine.ledger.alarms_total
        )
        self.engine.ledger.on_alarm = self._note_drift
        # overlapped decode (ISSUE 13): steady-state decode runs as a
        # two-deep software pipeline — step N+1 dispatched (tokens
        # carried device-resident from step N's output) while step N's
        # device work completes, its readback double-buffered, host
        # bookkeeping hidden inside N+1's execute window. Any non-steady
        # event (admission, finish/EOS, preempt, expiry, speculation,
        # crash, watchdog trip, shutdown) first DRAINS the in-flight
        # frontier deterministically, so recovery/replay/failover all
        # observe exactly the sequential semantics. _pipe (the at-most-
        # one-deep frontier) and its companions are loop-thread-only,
        # like _running; the heartbeat hand-off to the watchdog thread
        # stays the documented GIL-atomic tuple swap.
        self.overlap = True if overlap is None else bool(overlap)
        self._pipe: Optional[_Frontier] = None
        # plain counters (read by tests/genbench, not /metrics gauges):
        # dispatches that went through the pipeline, frontier drains by
        # reason, and in-flight steps discarded (recomputed exactly by
        # the next sequential step)
        self.pipe_dispatches = 0
        self.pipe_drains: Dict[str, int] = {}
        self.pipe_discards = 0
        # self-healing (recovery.py): journal + supervisor + watchdog.
        # _heartbeat is (seq, started_at) while a device call is in
        # flight — the watchdog's stall signal
        self.recovery_stats = RecoveryStats()
        self.recovery_stats.register_gauges(self.stats)
        # constrained decoding (ISSUE 18): grammar-cache + mask-step
        # telemetry (flexflow_serving_constrained_* on /metrics). The
        # serving layer's GrammarCache shares this object so per-model
        # compile hits/misses land next to the scheduler's masked-step
        # and dead-end counters.
        self.constrained_stats = ConstrainedStats()
        self.constrained_stats.register_gauges(self.stats)
        self.journal = GenerationJournal()
        self.supervisor = EngineSupervisor(self, recovery)
        self.watchdog = StepWatchdog(self, watchdog)
        self._heartbeat = None
        self._hb_seq = 0
        # the request popped for admission but not yet slot-resident:
        # visible to the watchdog's deadline reaper, which otherwise
        # could not see it while its prefill is wedged. _admitting_blocks
        # mirrors its allocation so cache_report can show a provisional
        # residency row while the prefill (possibly a cold compile) runs
        self._admitting: Optional[Request] = None
        self._admitting_blocks: Optional[List[int]] = None

    # ------------------------------------------------------------- submit
    def submit(
        self,
        prompt: Sequence[int],
        sampling: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
        speculation: Optional[SpeculationConfig] = None,
        transport: Optional[str] = None,
        priority: Optional[str] = None,
        grammar=None,
        response_format: Optional[Dict] = None,
        journey: Optional[JourneyContext] = None,
    ) -> GenerationHandle:
        """Enqueue one request (priority-ordered, FCFS within a class).
        Typed rejections mirror the batcher: OverloadedError (a
        QueueFullError subclass, carrying reason / priority /
        retry_after_s) on backpressure, limiter throttling, or
        degradation shedding; InfeasibleError when the roofline-
        predicted TTFT already exceeds the deadline; CircuitOpenError
        while the breaker holds traffic; ShuttingDownError while
        draining; DeadlineExceededError for an already-expired budget.
        A full queue sheds the youngest queued request of the LOWEST
        class that is strictly below the newcomer's (never a mid-stream
        resume) before rejecting the newcomer. ``speculation`` turns on
        (exact) speculative decoding for this request; None falls back
        to the scheduler-wide default. ``transport`` annotates the
        request's trace ("http"/"grpc"). ``priority`` is one of
        Priority.ORDER (default standard). ``grammar`` is a compiled
        constrained-decoding TokenDFA (see generation/constrained/);
        ``response_format`` is the wire spec it came from, kept for
        stream validation and replay provenance."""
        if self._draining:
            raise ShuttingDownError("generation scheduler draining")
        if self._stopped:
            raise ShuttingDownError("generation scheduler stopped")
        sampling = sampling or SamplingParams()
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.engine.buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds max bucket {self.engine.buckets[-1]}"
            )
        room = self.engine.max_seq_len - len(prompt)
        if room < 1:
            raise ValueError(f"prompt fills max_seq_len {self.engine.max_seq_len}")
        if (
            self.engine.cache_config.blocks_for(len(prompt) + 1)
            > self.engine.allocator.num_total
        ):
            raise ValueError("prompt exceeds total cache capacity; can never be admitted")
        if grammar is not None and grammar.vocab_size != self.engine.cfg.vocab_size:
            raise ValueError(
                f"grammar compiled against vocab {grammar.vocab_size}, "
                f"engine vocab is {self.engine.cfg.vocab_size}"
            )
        if deadline_s is not None and deadline_s <= 0:
            self.stats.incr("expired")
            raise DeadlineExceededError("deadline already expired at submit")
        priority = Priority.parse(priority)
        rank = Priority.rank(priority)
        ctl = self.overload
        # chaos hook: force admission-path failures (typically a typed
        # OverloadedError) so tests drive the limiter/shed paths
        # deterministically without generating real pressure
        faults.inject(faults.SERVING_ADMISSION, (priority, len(self._queue)))
        if ctl.degraded_reject(priority):
            raise ctl.overload_error(
                f"degraded: shedding {priority} traffic "
                f"(ladder level {ctl.ladder.level})",
                "degraded", priority,
            )
        if deadline_s is not None:
            predicted = ctl.infeasible(len(prompt), deadline_s)
            if predicted is not None:
                raise ctl.infeasible_error(priority, predicted, deadline_s)
        shed: List = []  # (victim, error) pairs, settled OUTSIDE the lock
        with self._lock:
            # breaker FIRST — before any shed planning, so a submit the
            # breaker is about to refuse can never destroy queued work.
            # ready(), NOT allow(): submit only enqueues — the device
            # call happens at admission, so the half-open probe slot
            # must be claimed by _admit. A submit that claimed it would
            # leave the probe's outcome forever unrecorded and stall
            # held requests for another recovery window.
            if not self.breaker.ready():
                self.stats.incr("rejected")
                raise CircuitOpenError("generation circuit open")
            deadline = None if deadline_s is None else self.clock() + deadline_s
            spec = speculation if speculation is not None else self.speculation_default
            drafter = None
            if spec is not None and spec.enabled:
                # clamp to the engine's compiled verify window so per-
                # request k NEVER changes the jit shape
                if spec.k > self.engine.max_spec_tokens:
                    spec = dataclasses.replace(spec, k=self.engine.max_spec_tokens)
                drafter = build_drafter(
                    spec, draft_params=self.draft_params,
                    max_seq_len=self.engine.max_seq_len,
                )
            req = Request(
                list(prompt), sampling, deadline=deadline,
                speculation=spec, drafter=drafter, priority=priority,
                grammar=grammar, response_format=response_format,
            )
            req.submitted_at = self.clock()
            if self.obs_enabled:
                req.trace = RequestTrace(
                    req.id, clock=self.clock,
                    progress_every=self.trace_progress_every,
                )
                req.trace_ring = self.trace_ring
                req.slo_sink = self._slo_record
                req.trace.mark_accept(
                    prompt_len=len(prompt),
                    deadline_s=deadline_s,
                    speculative=bool(spec is not None and spec.enabled),
                )
                if transport is not None:
                    req.trace.mark_transport(transport)
                if self.journeys is not None:
                    # a context handed in from ingress (HTTP/gRPC/fleet)
                    # keeps its id and parents onto the ingress span;
                    # otherwise the journey roots here
                    ctx = journey if journey is not None else self.journeys.mint()
                    ctx.recorder = self.journeys
                    req.journey = ctx
                    req.trace.journey_id = ctx.journey_id
                    ctx.hop(
                        "submit", request_id=req.id,
                        prompt_len=len(prompt), priority=priority,
                        transport=transport,
                    )
            # the sequence can never outgrow max_seq_len (its last token
            # would need a cache position past the block table) NOR the
            # TOTAL cache: a sequence needing more blocks than exist
            # would preempt-self forever at the head of the FCFS queue
            cache_room = (
                self.engine.allocator.num_total * self.engine.cache_config.block_size
                - len(prompt)
            )
            req.max_new = min(sampling.max_new_tokens, room, cache_room)
            # degrade level 3+: clamp NEW admissions' budgets per class
            # (running streams keep the budget they were admitted with)
            cap = ctl.max_new_cap(priority)
            if cap is not None:
                req.max_new = min(req.max_new, max(1, cap))
            # overload gates, planned BEFORE any victim is touched: the
            # full shed set (one for queue space when full, at most one
            # more when queued lower-priority work holds the limiter
            # slot — no priority inversion) is feasibility-checked
            # first, so a newcomer the gates will refuse anyway never
            # destroys queued work. Victims' limiter slots release here
            # (under the lock, so the acquire below cannot lose them);
            # their handles settle AFTER the lock drops.
            need = 1 if len(self._queue) >= self.max_queue else 0
            freed = need
            if not ctl.limiter.can_admit(priority, freed=freed):
                freed += 1  # one extra shed, for the limiter slot itself
                if not ctl.limiter.can_admit(priority, freed=freed):
                    raise ctl.overload_error(
                        "admission throttled by the adaptive concurrency "
                        f"limit ({ctl.limiter.limit:.0f})",
                        "limiter", priority,
                    )
            if freed:
                victims = self._shed_victims_locked(rank, freed)
                if len(victims) < freed:
                    if need and not victims:
                        raise ctl.overload_error(
                            f"generation queue full ({self.max_queue})",
                            "queue_full", priority,
                        )
                    raise ctl.overload_error(
                        "admission throttled by the adaptive concurrency "
                        f"limit ({ctl.limiter.limit:.0f})",
                        "limiter", priority,
                    )
                reason = "queue_full" if need else "limiter"
                detail = (
                    f"queue full at {self.max_queue}" if need
                    else f"adaptive limit {ctl.limiter.limit:.0f}"
                )
                for victim in victims:
                    self._queue.remove(victim)
                    release, victim.overload_release = (
                        victim.overload_release, None
                    )
                    if release is not None:
                        try:
                            release()
                        except Exception:
                            pass
                    shed.append((victim, ctl.overload_error(
                        f"shed for a higher-priority admission ({detail})",
                        reason, victim.priority, shed=True,
                    )))
            if not ctl.limiter.try_acquire(priority):
                # unreachable by construction (can_admit held under this
                # lock and inflight only shrinks concurrently); typed
                # anyway rather than trusting the invariant with a hang
                raise ctl.overload_error(
                    "admission throttled by the adaptive concurrency "
                    f"limit ({ctl.limiter.limit:.0f})",
                    "limiter", priority,
                )
            req.overload_release = ctl.limiter.release
            self._queue_insert_locked(req)
        # settle shed victims OUTSIDE the lock: Future.set_exception
        # runs client done-callbacks synchronously, and a callback that
        # re-enters the scheduler must not deadlock on _lock
        for victim, err in shed:
            victim.handle._fail(err)
        self.stats.incr("admitted")
        self._wake.set()
        return req.handle

    # ------------------------------------------------------------ control
    def start(self) -> None:
        if self._alive:
            return
        self._alive = True
        self._draining = False
        self._hard_stop = False
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self.watchdog.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful by default: finish queued + running requests, then
        exit. ``drain=False`` fails outstanding work immediately."""
        if self._thread is None:
            # never-started (manual-step) scheduler: honor the drain
            # contract inline — queued futures must not hang forever
            self._draining = True
            if drain:
                while self.has_work() and self.step():
                    pass
            self._abort_all(ShuttingDownError("scheduler stopped"))
            self._draining = False
            self._stopped = True
            return
        self._draining = True
        self._alive = False
        if not drain:
            self._hard_stop = True  # loop exits after the current step
        self._wake.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive() and self._heartbeat is None:
            # alive but NOT inside a device call: the drain is starved,
            # not wedged — e.g. an OPEN breaker holding queued requests
            # it cannot admit. Break the loop and fail the leftovers
            # typed below instead of leaking threads + hanging clients.
            self._hard_stop = True
            self._wake.set()
            self._thread.join(timeout=5.0)
        wedged = self._thread.is_alive()
        self._thread = None
        if wedged:
            # a wedged step keeps ownership of the slot/allocator state;
            # touching it here would race the live thread. The watchdog
            # stays alive on purpose: it is the only thing left that can
            # fail deadline-carrying handles stuck behind the zombie step
            return
        if drain:
            # the loop exited; anything still outstanding completes here
            # (watchdog still running: a step wedging during THIS drain
            # is the exact failure class it exists to catch)
            while self.has_work() and self.step():
                pass
        if self.has_work():
            # leftovers that cannot make progress (held behind an open
            # breaker, or drain=False): fail them typed, never hang them.
            # Runs only AFTER the loop exited: _abort_all mutates
            # _running/allocator state the stepping thread owns.
            self._abort_all(ShuttingDownError("scheduler stopped"))
        self.watchdog.stop()
        self._draining = False
        self._stopped = True

    def _abort_all(self, err: BaseException) -> None:
        """Shutdown-only teardown (``err`` is always a typed
        ShuttingDownError). Engine failures never come through here:
        the supervisor journal-replays running streams and HOLDS queued
        requests, so a queued-but-never-admitted request can no longer
        be failed with some other request's engine-internal error."""
        self._discard_frontier()  # shutdown: in-flight results are moot
        with self._lock:
            queued, self._queue = list(self._queue), deque()
        for req in queued:
            if req.handle._fail(err):
                self.stats.incr("failed")
        for state in list(self._running.values()):
            self._release(state)
            if state.req.handle._fail(err):
                self.stats.incr("failed")

    def _fail_running_engine_dead(self, err: EngineFailedError) -> None:
        """Restart budget exhausted: every slot-resident stream is truly
        lost to THIS engine — fail it with the typed EngineFailedError
        (never the raw device traceback). The engine was reset, so
        slot/allocator bookkeeping restarts from empty rather than
        freeing stale block ids into the fresh free list.

        With a ``failover_sink`` installed (fleet mode), the streams are
        not lost at all: every live request — slot-resident, replay-
        requeued mid-stream, and fresh queued — leaves this scheduler
        entirely (journal drained, slots cleared, queue emptied) and is
        handed to the sink, which journal-replays it onto a surviving
        replica (adopt()). The handoff is safe against double emission
        because the requests fully exit this scheduler's bookkeeping
        before the sink runs."""
        self.journal.drain()
        states = sorted(self._running.values(), key=lambda s: s.admitted_seq)
        self._reset_slots()
        self.engine.reset()
        for state in states:
            state.blocks = []
            state.shared_idx = set()
            state.shared_entries = []
        # streams that already completed their budget/EOS at a pipeline
        # consume but were still awaiting release when the engine died:
        # they hold every token — complete them, never fail or migrate
        done_states = [
            s for s in states
            if not s.req.handle.done() and s.req.finished()
        ]
        for s in done_states:
            s.req.handle._finish(list(s.req.generated))
            self.stats.incr("completed")
        states = [s for s in states if s not in done_states]
        sink = self.failover_sink
        if sink is not None:
            with self._lock:
                queued, self._queue = list(self._queue), deque()
            live = [s.req for s in states if not s.req.handle.done()]
            live += [r for r in queued if not r.handle.done()]
            try:
                sink(live, err)
                return
            except Exception:
                # the fleet must never make a dying engine worse: put
                # the taken queue back (ahead of anything submitted
                # meanwhile) and fall through to the single-engine
                # terminal semantics
                with self._lock:
                    for req in reversed(queued):
                        self._queue.appendleft(req)
        for state in states:
            if state.req.handle._fail(err):
                self.stats.incr("failed")
        # replay-requeued MID-STREAM requests (n_generated > 0) are as
        # lost as the slot-resident ones — their clients already hold
        # tokens, so holding them for a possible future probe would
        # hang them instead. Fresh queued requests stay held: they
        # streamed nothing and remain safe to resubmit or admit later.
        # One lock hold for the whole partition: the queue must never
        # look momentarily empty to a concurrent submit, or max_queue
        # backpressure overshoots while the kept requests re-enter.
        with self._lock:
            keep: deque = deque()
            for req in self._queue:
                if req.n_generated > 0:
                    if req.handle._fail(err):
                        self.stats.incr("failed")
                else:
                    keep.append(req)
            self._queue = keep

    def _rebuild_from_journal(self) -> None:
        """Journal-replay after an engine teardown: every live stream is
        requeued at the FRONT (it was admitted before anything waiting)
        with its generated tokens folded into the prompt — the
        preempt-by-recompute path then resumes each token stream
        exactly. Must run after ``engine.reset()``: old block ids must
        not be freed into the fresh allocator."""
        entries = self.journal.drain()
        self._reset_slots()
        replayed = 0
        requeue = []
        for entry in entries:
            req = entry.req
            if req.handle.done():  # reaped (deadline) while the engine was down
                continue
            if req.finished():
                # completed its budget/EOS before the teardown (a
                # pipeline consume can finish a stream whose release
                # was still pending when the restart hit): it already
                # holds every token — complete it, never replay it
                req.handle._finish(list(req.generated))
                self.stats.incr("completed")
                continue
            req.prompt = req.original_prompt + list(req.generated)
            # constrained streams rebuild their automaton cursor at
            # re-admission by re-advancing over `generated` — the
            # journal IS the mask state
            req.mask_state = None
            req.replays += 1
            req.trace.note_replay()
            req.journey.hop(
                "replay", n_generated=req.n_generated,
                reason="engine_restart",
            )
            replayed += req.n_generated
            requeue.append(req)
        with self._lock:
            for req in reversed(requeue):
                self._queue.appendleft(req)
        if replayed:
            self.recovery_stats.incr("replayed_tokens", replayed)
        self._wake.set()

    def steal_queue(self) -> List[Request]:
        """Fleet rescue: atomically take every QUEUED (never slot-
        resident this life, or held behind the breaker) request off this
        scheduler, for adoption elsewhere. Safe against a live loop
        thread — the queue is only popped under the same lock. Slot-
        resident streams are NOT stealable (the loop thread owns them);
        they finish, fail over via the supervisor, or expire."""
        with self._lock:
            stolen, self._queue = list(self._queue), deque()
        return [r for r in stolen if not r.handle.done()]

    def adopt(self, req: Request, *, front: bool = True,
              imported=None) -> None:
        """Cross-replica journal-replay admission (fleet failover): take
        ownership of a Request journaled on a dead sibling scheduler.
        The replay state IS the request object — original prompt, every
        emitted token, the per-token-count seeded sampling keys and
        speculation config — so the recompute-prefill path resumes the
        stream byte-exactly on THIS engine (fleet replicas are built by
        one factory, hence geometrically identical). Bypasses the
        max_queue bound and the breaker on purpose: a migrated stream
        was already admitted once and must not be dropped for
        backpressure it cleared on its original replica. ``front``
        requeues ahead of fresh work (mid-stream requests were admitted
        before anything now waiting).

        ``imported`` (disaggregated serving) attaches a CRC-verified
        :class:`KVHandoffPayload`: admission imports the prefilled
        blocks instead of recompute-prefilling, and any import failure
        falls back to the recompute path — the stream is byte-exact
        either way, so a handoff can degrade but never corrupt."""
        req.imported_kv = imported
        req.prompt = req.original_prompt + list(req.generated)
        req.mask_state = None  # rebuilt from `generated` at admission
        # heterogeneous-adopter guards (unreachable for fleet-built
        # replicas, which share one factory): mirror submit()'s
        # can-never-be-admitted checks, or the adopted stream wedges
        # this queue's FCFS head forever
        room = self.engine.max_seq_len - len(req.prompt)
        cache_room = (
            self.engine.allocator.num_total * self.engine.cache_config.block_size
            - len(req.prompt)
        )
        if (
            len(req.prompt) > self.engine.buckets[-1]
            or room < 1
            or self.engine.cache_config.blocks_for(len(req.prompt) + 1)
            > self.engine.allocator.num_total
        ):
            if req.handle._fail(ValueError(
                f"adopted stream length {len(req.prompt)} can never be "
                f"admitted on this engine (max bucket "
                f"{self.engine.buckets[-1]}, max_seq_len "
                f"{self.engine.max_seq_len}, cache blocks "
                f"{self.engine.allocator.num_total})"
            )):
                self.stats.incr("failed")
            return
        # re-clamp the budget against THIS engine's geometry (total
        # generated = already-emitted + what still fits here)
        req.max_new = min(
            req.max_new, req.n_generated + room, req.n_generated + cache_room
        )
        if req.n_generated > 0 and imported is None:
            # a recompute adoption replays the stream; an imported
            # handoff is the disaggregated steady state and counts only
            # if the import is later rejected (see _admit_imported)
            req.replays += 1
            req.trace.note_replay()
            self.recovery_stats.incr("replayed_tokens", req.n_generated)
        # retarget terminal observability at the adopting scheduler so
        # the finished trace and SLO/goodput accounting land where the
        # stream actually completed
        if req.trace_ring is not None:
            req.trace_ring = self.trace_ring
        if req.slo_sink is not None:
            req.slo_sink = self._slo_record
        if req.journey is not NULL_JOURNEY:
            # retarget the journey at the adopting replica's span ring:
            # from here on, hops land in THIS lane (or nowhere, if this
            # scheduler runs with journeys off — the context stays
            # intact so a later adopter can pick it back up)
            req.journey.recorder = self.journeys
            req.journey.hop(
                "adopt", replica=self.fault_scope,
                imported=imported is not None, front=front,
                n_generated=req.n_generated,
            )
        # retarget overload accounting too: release the dead replica's
        # limiter slot and count the stream against THIS limiter —
        # forced past the limit (a migrated stream was already admitted
        # once and must never be dropped for headroom it cleared
        # elsewhere), so would_admit/pressure see the true load
        release, req.overload_release = req.overload_release, None
        if release is not None:
            try:
                release()
            except Exception:
                pass
        self.overload.limiter.acquire_forced()
        req.overload_release = self.overload.limiter.release
        with self._lock:
            if front:
                self._queue.appendleft(req)
            else:
                self._queue.append(req)
        self._wake.set()

    def _reset_slots(self) -> None:
        """Post-``engine.reset()`` slot bookkeeping: every slot is empty
        and every outstanding block table invalid wholesale (the
        allocator free list was restored, so per-block frees — which
        would double-free — must never follow this)."""
        self._running.clear()
        self._free_slots = list(range(self.engine.max_batch_slots - 1, -1, -1))

    def _quarantine(self, state: _Running, err: BaseException) -> None:
        """Fail ONE poisoned request and keep the batch: blocks freed,
        slot returned, everyone else untouched. The flight recorder's
        trailing window rides the error out as the postmortem."""
        req = state.req
        req.trace.event(
            "quarantine",
            step=getattr(err, "step", None),
            reason=getattr(err, "reason", type(err).__name__),
        )
        if getattr(err, "flight_snapshot", None) is None:
            try:
                err.flight_snapshot = self.flight.incident(
                    "quarantine", request_id=req.id,
                    error=repr(err)[:200],
                )
            except Exception:
                pass  # exceptions with __slots__ cannot carry the dump
        self._release(state)
        if req.handle._fail(err):
            self.stats.incr("failed")
            self.recovery_stats.incr("quarantined")

    def _sweep_mask_errors(self) -> None:
        """Quarantine running slots whose constrained stream parked a
        grammar error during token bookkeeping. _advance_mask never
        raises mid-emit — a dead-ended automaton must not unwind the
        scatter loop and take the batch's other slots with it — so the
        error waits one iteration here, where quarantine is safe: the
        slot is released, the typed error reaches the one caller, and
        everyone else keeps streaming."""
        for state in list(self._running.values()):
            err = state.req.mask_error
            if err is not None:
                state.req.mask_error = None
                self._quarantine(state, err)

    def ready(self) -> bool:
        return not self._draining and self.breaker.ready()

    def has_work(self) -> bool:
        return bool(self._queue) or bool(self._running)

    # ------------------------------------------- capacity / SLO reporting
    def _note_retrace(self, name: str, blame: str) -> None:
        """Program-registry retrace hook: the blame string lands on the
        flight ring in true order with the step that retraced."""
        self.flight.record_event("retrace", program=name, blame=blame)

    def _note_drift(self, alarm: Dict) -> None:
        """Truth-ledger drift hook: the calibration-staleness alarm
        ("decode: predicted 1.8ms, measured p50 3.1ms, error +72%, ...")
        lands on the flight ring next to the steps that proved it."""
        self.flight.record_event(
            "drift", program=alarm["key"], blame=alarm["blame"]
        )

    def _slo_record(self, req: Request) -> None:
        """Terminal SLO/goodput sink (exactly once per request, via the
        handle's settle-race winner). Deadline-goodput counts a token as
        good only when its request COMPLETED in-deadline; the SLO
        windows see every outcome."""
        tr = req.trace
        in_deadline = req.deadline is None or (
            tr.t_finish is not None and tr.t_finish <= req.deadline
        )
        self.goodput.record(
            req.n_generated, good=(tr.outcome == "completed" and in_deadline)
        )
        self.slo.observe(tr.outcome or "unknown", ttft_s=tr.ttft_s, tpot_s=tr.tpot_s)

    def cache_report(self) -> Dict:
        """The ``GET /v2/debug/cache`` payload: allocator state +
        per-request block residency (obs/capacity.py). Read order
        matters for concurrent scrapes: the free count FIRST (so a
        request finishing mid-scrape leaves the residency table at
        worst undercounting ``used``, never claiming freed blocks),
        then the running snapshot, then the in-flight admission — with
        id-dedup in report(), a request can never be counted twice,
        and the undercount window shrinks from the whole prefill to
        the register-then-clear gap."""
        free = self.engine.allocator.num_free
        running = list(self._running.values())
        adm_req, adm_blocks = self._admitting, self._admitting_blocks
        return self.capacity.report(
            running, queue_depth=len(self._queue),
            admitting=(adm_req, adm_blocks)
            if adm_req is not None and adm_blocks else None,
            free=free,
            prefix=self.engine.prefix_cache.snapshot(),
        )

    def _loop(self) -> None:
        while (self._alive or (self._draining and self.has_work())) and not self._hard_stop:
            if not self.step():
                self._wake.wait(timeout=self.idle_wait_s)
                self._wake.clear()

    # ---------------------------------------------------------- internals
    def _release(self, state: _Running) -> None:
        self.journal.discard(state.req)
        # private blocks go back to the allocator; shared (index-owned)
        # blocks only drop this sequence's refcount — their content
        # stays cached for the next matching prompt
        self.engine.allocator.free(
            [b for i, b in enumerate(state.blocks) if i not in state.shared_idx]
        )
        self.engine.prefix_cache.release(state.shared_entries)
        state.blocks = []
        state.shared_idx = set()
        state.shared_entries = []
        del self._running[state.slot]
        self._free_slots.append(state.slot)

    def _finish(self, state: _Running) -> None:
        self._release(state)
        req = state.req
        self.stats.latency.record(max(0.0, self.clock() - req.submitted_at))
        tpot = req.trace.tpot_s
        if tpot is not None:
            self.stats.observe("tpot", tpot, exemplar=req.journey.journey_id)
        req.handle._finish(list(req.generated))
        self.stats.incr("completed")

    def _expire(self) -> None:
        now = self.clock()
        with self._lock:
            keep: deque = deque()
            for req in self._queue:
                if req.handle.done():
                    pass  # reaped by the watchdog during a stall; just drop
                elif req.cancelled:
                    if req.handle._fail(ShuttingDownError("request cancelled")):
                        self.stats.incr("cancelled")
                elif req.deadline is not None and now >= req.deadline:
                    if req.handle._fail(DeadlineExceededError("deadline expired while queued")):
                        self.stats.incr("expired")
                else:
                    keep.append(req)
            self._queue = keep
        for state in list(self._running.values()):
            req = state.req
            if req.handle.done():
                # failed externally (watchdog deadline reap): resource
                # cleanup belongs to this thread, the counting happened
                # where the handle was failed
                self._release(state)
            elif req.cancelled:
                self._release(state)
                if req.handle._fail(ShuttingDownError("request cancelled")):
                    self.stats.incr("cancelled")
            elif req.deadline is not None and now >= req.deadline:
                self._release(state)
                if req.handle._fail(DeadlineExceededError("deadline expired mid-generation")):
                    self.stats.incr("expired")

    @contextlib.contextmanager
    def _stamped(self):
        """Heartbeat stamp around any section that can wedge on the
        device — the watchdog's only stall signal."""
        self._hb_seq += 1
        self._heartbeat = (self._hb_seq, self.clock())
        try:
            yield
        finally:
            self._heartbeat = None

    def _device(self, fn):
        """Run one device step under retry + breaker accounting, with a
        heartbeat stamped around the call so the watchdog can see a step
        that neither returns nor raises."""
        with self._stamped():
            try:
                out = self.retry.run(fn)
            except Exception:
                self.breaker.record_failure()
                raise
        self.breaker.record_success()
        return out

    def _probe_call(self, fn):
        """Device call for a blame-assignment probe: heartbeat only, no
        retry/breaker (an expected crash while bisecting is not device
        health signal — but a STALL during a probe must still be
        visible to the watchdog)."""
        with self._stamped():
            return fn()

    def _queue_insert_locked(self, req: Request) -> None:
        """Priority-ordered enqueue: ahead of the first FRESH queued
        request of a strictly lower class, FIFO within a class. Resumed
        work (preempted / journal-replayed, requeued at the front by
        appendleft) keeps absolute precedence — a new interactive
        request must not starve a mid-stream resume whose client
        already holds tokens."""
        q = self._queue
        for i, cand in enumerate(q):
            if cand.n_generated > 0 or cand.preemptions > 0 or cand.replays > 0:
                continue
            if cand.priority_rank > req.priority_rank:
                q.insert(i, req)
                return
        q.append(req)

    def _shed_victims_locked(self, rank: int, n: int) -> List[Request]:
        """Up to ``n`` shed victims for a newcomer of ``rank``: fresh
        queued requests of classes strictly below the newcomer's (never
        a mid-stream resume — its client already holds tokens), lowest
        class first, youngest first within a class."""
        cands = [
            (cand.priority_rank, idx, cand)
            for idx, cand in enumerate(self._queue)
            if cand.priority_rank > rank
            and cand.n_generated == 0 and cand.preemptions == 0
            and cand.replays == 0 and not cand.handle.done()
        ]
        cands.sort(key=lambda t: (t[0], t[1]), reverse=True)
        return [cand for _, _, cand in cands[:n]]

    def _shed_queued_best_effort(self) -> None:
        """Degrade level 4: every queued fresh best-effort request
        fails typed (reason "degraded"); resumed best-effort streams
        keep their place — shedding them would cut off clients
        mid-stream."""
        with self._lock:
            victims = [
                r for r in self._queue
                if r.priority_rank == Priority.RANK[Priority.BEST_EFFORT]
                and r.n_generated == 0 and r.preemptions == 0
                and r.replays == 0 and not r.handle.done()
            ]
            for r in victims:
                self._queue.remove(r)
        for r in victims:
            r.handle._fail(self.overload.overload_error(
                "degraded: best-effort shed at ladder level "
                f"{self.overload.ladder.level}",
                "degraded", r.priority, shed=True,
            ))

    def _note_degrade(self, old: int, new: int, pressure: float) -> None:
        """Ladder-transition hook: every level change is a flight-ring
        event next to the steps that caused it."""
        self.flight.record_event(
            "degrade", level=new, prev=old, pressure=round(pressure, 3)
        )

    def _overload_tick(self) -> None:
        """One overload-control iteration (limiter AIMD + ladder), plus
        the ladder's level-4 action: shed queued best-effort work."""
        self.overload.tick()
        if self.overload.ladder.shed_best_effort():
            self._shed_queued_best_effort()

    def _preempt_youngest(self, exclude: Optional[_Running] = None) -> bool:
        """Evict a running sequence for recompute under cache pressure:
        the victim is the youngest member of the LOWEST priority class
        present (vLLM's LIFO recompute victim, priority-ordered): free
        its blocks, fold its generated tokens into the prompt, and
        requeue it at the FRONT. ``exclude`` is the growing sequence:
        it is never the victim here — and neither is anything that
        OUTRANKS it (growing a best-effort stream must not evict an
        interactive one; returning False makes the caller self-preempt
        the grower instead)."""
        victims = [s for s in self._running.values() if s is not exclude]
        if exclude is not None:
            victims = [
                s for s in victims
                if s.req.priority_rank >= exclude.req.priority_rank
            ]
        if not victims:
            return False
        victim = max(victims, key=lambda s: (s.req.priority_rank, s.admitted_seq))
        self.capacity.note_preempt(len(victim.blocks))
        # stash the victim's computed KV in the radix index before the
        # release: its re-admission (and any prefix-sharing request)
        # re-matches the blocks — under continued pressure they offload
        # to the host tier and swap back in instead of recomputing
        self.engine.stash_prefix(victim)
        self._release(victim)
        req = victim.req
        req.prompt = req.original_prompt + list(req.generated)
        req.mask_state = None  # rebuilt from `generated` at re-admission
        req.preemptions += 1
        self.preemptions += 1
        req.trace.note_preempt()
        with self._lock:
            self._queue.appendleft(req)
        return True

    def _admit(self) -> bool:
        """FCFS, cache-capacity-aware admission. Returns True if a
        request was admitted (prefilled)."""
        with self._lock:
            if not self._queue or not self._free_slots:
                return False
            # an OPEN breaker holds admission: queued requests wait out a
            # device outage (expiring at their own deadlines) instead of
            # being burned one per step against a dead engine; after
            # recovery_s the next admission is the half-open probe whose
            # success resumes service
            if not self.breaker.allow():
                return False
            req = self._queue[0]
        if req.grammar is not None and req.mask_state is None:
            # constrained stream: rebuild the automaton cursor by
            # re-advancing over every emitted token. First admission
            # starts at the grammar's start state; preempt-recompute,
            # engine restart, and cross-replica adoption all arrive
            # here with mask_state dropped and `generated` intact, so
            # the journal IS the mask state (byte-exact replay). A
            # refused token (replay divergence or an injected
            # generation.mask_advance fault) fails the ONE request
            # typed — the queue and batch are untouched.
            try:
                req.mask_state = req.grammar.state_after(
                    req.generated, req.sampling.eos_id
                )
            except Exception as e:
                with self._lock:
                    if self._queue and self._queue[0] is req:
                        self._queue.popleft()
                self.constrained_stats.incr("dead_end_failures")
                err = PoisonedRequestError(
                    f"request {req.id} could not rebuild its grammar "
                    f"state: {e}",
                    request_id=req.id, step="mask", reason="mask_advance",
                )
                req.trace.event("quarantine", step="mask", reason="mask_advance")
                err.flight_snapshot = self.flight.incident(
                    "quarantine", request_id=req.id, step="mask",
                    reason="mask_advance",
                )
                if req.handle._fail(err):
                    self.stats.incr("failed")
                    self.recovery_stats.incr("quarantined")
                return True
        if req.imported_kv is not None:
            # disaggregated decode pool: the prompt's KV arrived over
            # the handoff wire — import it instead of prefilling
            return self._admit_imported(req)
        # prefix match + block acquisition run OUTSIDE the submit lock:
        # the reclaim path does per-block device reads (host-tier
        # swap-outs) that must neither block concurrent submits nor —
        # via the heartbeat stamp — hide a wedged device from the
        # watchdog. The allocator and prefix index carry their own
        # locks; only the queue/slot mutation below needs _lock.
        # Radix planning is a first-class anatomy phase (prefix_plan):
        # PR 11 made it a real admission cost the waterfall must not
        # hide inside "admit".
        t_p0 = time.perf_counter()
        plan = self.engine.prefix_plan(req.prompt)
        t_p1 = time.perf_counter()
        self._span("prefix_plan", t_p0, t_p1)
        need = (
            self.engine.cache_config.blocks_for(len(req.prompt) + 1)
            - plan.n_resident
        )
        blocks = self.engine.allocator.allocate(need)
        if blocks is None:
            # unreferenced cached prefixes are the reclaim of last
            # resort BEFORE making the head wait (or preempt): LRU
            # entries offload to host and their device blocks free
            with self._stamped():
                reclaimed = self.engine.reclaim_cached(
                    need - self.engine.allocator.num_free
                )
            if reclaimed:
                blocks = self.engine.allocator.allocate(need)
        if blocks is None:
            # admission-rejection blame: remember when the FCFS head
            # first stalled on blocks and how many it is short — the
            # eventual admit stamps "queued Nms waiting for K
            # block(s)" on the request's trace
            if self.obs_enabled and req.cache_wait_start is None:
                req.cache_wait_start = self.clock()
            req.cache_wait_short = need - self.engine.allocator.num_free
            return False
        with self._lock:
            if not self._queue or self._queue[0] is not req or not self._free_slots:
                # the head changed while blocks were gathered (fleet
                # steal_queue / adopt mutate the queue from other
                # threads): hand the blocks back, retry next iteration
                self.engine.allocator.free(blocks)
                return False
            self._queue.popleft()
            slot = self._free_slots.pop()
        if req.cache_wait_start is not None:
            wait_s = max(0.0, self.clock() - req.cache_wait_start)
            blame = self.capacity.note_admission_wait(wait_s, req.cache_wait_short)
            req.trace.event(
                "cache_wait", wait_s=wait_s,
                blocks_short=req.cache_wait_short, blame=blame,
            )
            req.cache_wait_start = None
        self._span("admit", t_p1, time.perf_counter())
        # assemble the block table from the prefix plan: swap-ins + the
        # COW boundary copy are device work, so the watchdog's stall
        # heartbeat covers them like any other step
        t_q0 = time.perf_counter()
        with self._stamped():
            prep = self.engine.prepare_prefix(req.prompt, plan, blocks)
        t_q1 = time.perf_counter()
        self._span("prefix_plan", t_q0, t_q1)
        if prep is None:
            # a mid-assembly swap-in fallback could not replace the
            # lost shared blocks: everything was handed back — requeue
            # the head and retry next iteration
            self._free_slots.append(slot)
            with self._lock:
                self._queue.appendleft(req)
            return False
        table, shared_idx, entries, prefix_len = prep
        # blocks first, then the request: cache_report treats a set
        # _admitting as implying its blocks are readable (private
        # blocks only — shared ones are the prefix index's to report)
        self._admitting_blocks = [
            b for i, b in enumerate(table) if i not in shared_idx
        ]
        self._admitting = req
        t_dev = time.perf_counter()
        self._span("admit", t_q1, t_dev)
        try:
            pf_mask = None
            if req.mask_state is not None:
                # the prefill samples this stream's next token in-jit:
                # mask it exactly like a decode step would
                pf_mask = req.mask_state.mask_row(req.sampling.eos_id)
                self.constrained_stats.incr("masked_steps")
            token = self._device(
                lambda: self.engine.prefill_one(
                    req.prompt, table, req.sampling, req.sample_key(),
                    prefix_len=prefix_len, mask=pf_mask,
                )
            )
        except Exception as e:
            self._admitting = None
            self._admitting_blocks = None
            self.engine.release_admission(table, shared_idx, entries)
            self._free_slots.append(slot)
            if self.supervisor.failed:
                # half-open probe against a still-dead engine: a HELD
                # request must not eat the raw device error for probing.
                # Back to the front; the probe's recorded failure just
                # re-opened the breaker, so admission waits out another
                # recovery window before the next attempt.
                with self._lock:
                    self._queue.appendleft(req)
                return False
            if req.n_generated > 0:
                # a replayed/preempted stream whose consumer already
                # holds tokens: a raw prefill error must not cut it off
                # mid-stream. Requeue it and treat the failure as
                # engine-level — budgeted restart + backoff (give-up
                # fails running streams typed and holds the queue).
                with self._lock:
                    self._queue.appendleft(req)
                self.supervisor._restart_and_replay(e, "prefill")
                return True
            if req.handle._fail(e):
                self.stats.incr("failed")
            return True  # did work (and must not spin on the same head)
        t_dev_end = time.perf_counter()
        dev_s = t_dev_end - t_dev
        # the prefill's dispatch/block/execute/readback spans join the
        # iteration's anatomy timeline with their real offsets
        execute_s = self._engine_spans()
        if not bool(self.engine.last_finite[0]):
            # poisoned prompt: the prefill's logits went non-finite, and
            # a single-sequence step needs no bisection to assign blame
            self._admitting = None
            self._admitting_blocks = None
            self.engine.release_admission(table, shared_idx, entries)
            self._free_slots.append(slot)
            err = PoisonedRequestError(
                f"request {req.id} produced non-finite logits at prefill",
                request_id=req.id, step="prefill", reason="nan_logits",
            )
            req.trace.event("quarantine", step="prefill", reason="nan_logits")
            err.flight_snapshot = self.flight.incident(
                "quarantine", request_id=req.id, step="prefill",
                reason="nan_logits",
            )
            if req.handle._fail(err):
                self.stats.incr("failed")
                self.recovery_stats.incr("quarantined")
            return True
        # the prompt's freshly written full blocks join the radix index
        # AFTER the finiteness gate — poisoned K/V must never become
        # shared content another request could reuse (reuse telemetry
        # also counts here, so failed admissions never inflate it)
        self.engine.register_prefix(
            req.prompt, table, shared_idx, entries, prefix_len=prefix_len
        )
        state = _Running(
            req, slot, table, cached_len=len(req.prompt),
            admitted_seq=next(self._admitted_seq),
            shared_idx=shared_idx, shared_entries=entries,
        )
        self._running[slot] = state
        # clear only AFTER slot registration: cache_report reads
        # _running first and dedupes by request id, so the blocks are
        # visible (as a provisional or real row, never both) for the
        # whole admission — residency keeps summing to used under
        # concurrent scrapes
        self._admitting = None
        self._admitting_blocks = None
        if self.supervisor.failed:  # a dead engine just served a prefill
            self.supervisor.note_engine_recovered()
        self.journal.record(req, state.admitted_seq)
        if req.handle.done():  # watchdog reaped it while the prefill ran
            self._release(state)
            return True
        was_first = req.n_generated == 0
        now = self.clock()
        req.trace.mark_admit(
            slot=slot, prompt_len=len(req.prompt),
            preemptions=req.preemptions, replays=req.replays,
        )
        req.journey.hop(
            "admit", slot=slot, prompt_len=len(req.prompt),
            replica=self.fault_scope, preemptions=req.preemptions,
            replays=req.replays,
        )
        if self.obs_enabled and was_first and req.preemptions == 0 and req.replays == 0:
            # first-life admission only: a recompute re-admission is a
            # scheduling event, not client-visible queueing
            self.stats.observe(
                "queue_time", max(0.0, now - req.submitted_at),
                exemplar=req.journey.journey_id,
            )
        self._emit_token(state, token)
        req.trace.note_tokens(1, "prefill")
        req.journey.hop(
            "prefill", prompt_len=len(req.prompt),
            prefix_reused=prefix_len, replica=self.fault_scope,
        )
        if self.obs_enabled and was_first:
            # gated like tpot (trace-derived in _finish) so disabling
            # observability drops all three SLO windows together, not
            # a confusing two of three
            self.stats.observe(
                "ttft", max(0.0, now - req.submitted_at),
                exemplar=req.journey.journey_id,
            )
        self.flight.record_step(
            "prefill",
            phases={"prefix_plan": (t_p1 - t_p0) + (t_q1 - t_q0),
                    "device": dev_s},
            execute_s=execute_s, request_id=req.id,
            prompt_len=len(req.prompt), occupancy=len(self._running),
            queue_depth=len(self._queue),
            blocks_free=self.engine.allocator.num_free,
            prefix_reused=prefix_len,
        )
        self.token_rate.record(1)
        if req.finished():
            self._finish(state)
        elif self.handoff_sink is not None:
            # disaggregated prefill pool: this replica's job ends at the
            # first token. Pack the prompt's KV into the CRC-stamped
            # wire format while the blocks are still resident, hand the
            # slot back, and ship (request, payload) to the handoff
            # supervisor — the stream continues on the decode pool.
            with self._stamped():
                payload = self.engine.pack_kv_blocks(
                    state.blocks, state.cached_len
                )
            self._release(state)
            req.trace.event(
                "kv_handoff_pack", n_blocks=len(payload.blocks),
                payload_bytes=payload.nbytes,
            )
            req.journey.hop(
                "kv_handoff_pack", n_blocks=len(payload.blocks),
                payload_bytes=payload.nbytes, replica=self.fault_scope,
            )
            sink = self.handoff_sink
            try:
                sink(req, payload)
            except Exception as e:
                # the sink must never kill the loop; a sink crash fails
                # the stream typed instead of losing it silently
                if req.handle._fail(e):
                    self.stats.incr("failed")
        self._span("admit", t_dev_end, time.perf_counter())
        return True

    def _admit_imported(self, req: Request) -> bool:
        """Disaggregated decode-pool admission: commit a handed-off
        prompt's KV blocks into this engine's cache (CRC-verified per
        block, resharded onto this engine's head partitioning by the
        jitted block writer) and seat the stream directly in a decode
        slot — no prefill device call. Any failure — injected fault,
        CRC mismatch, geometry mismatch — rejects the import and falls
        back to the recompute-prefill path, which replays the stream
        byte-exactly from the request object."""
        payload = req.imported_kv
        t0 = time.perf_counter()
        need = self.engine.cache_config.blocks_for(payload.n_positions + 1)
        blocks = self.engine.allocator.allocate(need)
        if blocks is None:
            with self._stamped():
                reclaimed = self.engine.reclaim_cached(
                    need - self.engine.allocator.num_free
                )
            if reclaimed:
                blocks = self.engine.allocator.allocate(need)
        if blocks is None:
            if self.obs_enabled and req.cache_wait_start is None:
                req.cache_wait_start = self.clock()
            req.cache_wait_short = need - self.engine.allocator.num_free
            return False
        with self._lock:
            if not self._queue or self._queue[0] is not req or not self._free_slots:
                self.engine.allocator.free(blocks)
                return False
            self._queue.popleft()
            slot = self._free_slots.pop()
        try:
            faults.inject(
                faults.GENERATION_KV_IMPORT, (req.id, len(payload.blocks))
            )
            if payload.block_size != self.engine.cache_config.block_size:
                raise ValueError(
                    f"handoff block size {payload.block_size} != this "
                    f"engine's {self.engine.cache_config.block_size}"
                )
            if len(payload.blocks) < self.engine.cache_config.blocks_for(
                payload.n_positions
            ):
                raise ValueError("handoff payload is missing blocks")
            n_import = self.engine.cache_config.blocks_for(payload.n_positions)
            wire = payload.blocks[:n_import]
            for pb in wire:
                if not pb.verify():
                    raise ValueError(
                        "imported KV block failed CRC verification"
                    )
            # every block CRC-verified BEFORE any device write, then one
            # batched program commits the whole payload — a decode-pool
            # replica pays one dispatch per adopted stream between steps
            with self._stamped():
                self.engine.import_kv_blocks(blocks[:n_import], wire)
        except Exception as e:
            # reject the import: hand everything back and requeue for
            # the recompute path (this is the replay the clean-handoff
            # adopt() deliberately did not count)
            req.imported_kv = None
            self.engine.allocator.free(blocks)
            with self._lock:
                self._free_slots.append(slot)
                self._queue.appendleft(req)
            self.recovery_stats.incr("kv_imports_rejected")
            if req.n_generated > 0:
                req.replays += 1
                req.trace.note_replay()
                self.recovery_stats.incr("replayed_tokens", req.n_generated)
            req.trace.event(
                "kv_import_rejected", reason=type(e).__name__,
                n_blocks=len(payload.blocks),
            )
            return True
        req.imported_kv = None
        self.recovery_stats.incr("kv_imports")
        state = _Running(
            req, slot, blocks, cached_len=payload.n_positions,
            admitted_seq=next(self._admitted_seq),
        )
        self._running[slot] = state
        self.journal.record(req, state.admitted_seq)
        if req.handle.done():  # reaped while blocks were in flight
            self._release(state)
            return True
        req.trace.mark_admit(
            slot=slot, prompt_len=len(req.prompt),
            preemptions=req.preemptions, replays=req.replays,
        )
        req.trace.event(
            "kv_import", n_blocks=len(payload.blocks),
            n_positions=payload.n_positions, payload_bytes=payload.nbytes,
        )
        req.journey.hop(
            "admit", slot=slot, prompt_len=len(req.prompt),
            replica=self.fault_scope, imported=True,
            n_blocks=len(payload.blocks),
        )
        self.flight.record_step(
            "kv_import", phases={"admit": time.perf_counter() - t0},
            request_id=req.id, prompt_len=len(req.prompt),
            occupancy=len(self._running), queue_depth=len(self._queue),
            blocks_free=self.engine.allocator.num_free,
        )
        self._span("admit", t0, time.perf_counter())
        return True

    def _emit_token(self, state: _Running, token: int) -> None:
        state.req.generated.append(int(token))
        state.req.handle._emit(int(token))
        # durable serving: the journal mirrors the token delta into its
        # WAL buffer (a no-op on the base journal) — host bookkeeping
        # that the overlap pipeline hides under device execution, like
        # the mask advance below; the write+fsync happens once per step
        # in journal.flush_step()
        self.journal.note_token(state.req, int(token))
        if state.req.mask_state is not None:
            self._advance_mask(state.req, int(token))

    def _advance_mask(self, req: Request, token: int) -> None:
        """Advance a constrained request's automaton over one emitted
        token — host bookkeeping that the overlap pipeline hides under
        device execution. NEVER raises: emit paths run deep inside
        admission/scatter flows where an exception would take down the
        batch, so a refused advance (injected generation.mask_advance
        fault or replay divergence) parks a typed error on the request
        for the step loop's quarantine sweep (_sweep_mask_errors) —
        blast radius of ONE stream. A cleanly exhausted grammar
        (accepting, no live continuation) instead clamps the budget so
        the stream completes this step."""
        ms = req.mask_state
        try:
            ms.advance(token, req.sampling.eos_id)
        except Exception as e:
            reason = (
                "mask_dead_end" if isinstance(e, MaskDeadEndError)
                else "mask_advance"
            )
            self.constrained_stats.incr("dead_end_failures")
            req.mask_error = PoisonedRequestError(
                f"request {req.id} grammar refused emitted token "
                f"{token}: {e}",
                request_id=req.id, step="mask", reason=reason,
            )
            return
        if ms.exhausted() and not ms.done:
            # the grammar has exactly one continuation left (EOS, when
            # the request has one): end the stream deterministically
            # instead of decoding against an everything-banned row
            req.max_new = req.n_generated

    def _plan_speculation(self) -> None:
        """Decide each running sequence's draft count for THIS step:
        its adaptive k, capped by the remaining token budget (never
        draft past max_new), the sequence-length ceiling, and — in
        _grow — cache pressure."""
        for state in self._running.values():
            req = state.req
            if req.drafter is None:
                state.step_k = 0
                continue
            budget = req.max_new - req.n_generated  # >= 1 while running
            pos_room = (self.engine.max_seq_len - 1) - state.cached_len
            state.step_k = max(0, min(req.spec_k, budget - 1, pos_room))
            # degrade ladder: level 1 caps the window, level 2 disables
            # drafting outright — exact either way (PR 3's acceptance
            # rule: any k, including 0, emits the same greedy stream)
            cap = self.overload.spec_cap()
            if cap is not None:
                state.step_k = min(state.step_k, cap)

    def _grow(self) -> None:
        """Ensure every running sequence has cache blocks for its next
        window — up to step_k + 1 new positions. Under pressure, first
        shrink the window (cap speculation), then preempt-by-recompute."""
        for state in list(self._running.values()):
            if self._running.get(state.slot) is not state:
                continue  # preempted earlier in this sweep
            while True:
                need = self.engine.cache_config.blocks_for(
                    state.cached_len + state.step_k + 1
                )
                if len(state.blocks) >= need:
                    break
                got = self.engine.allocator.allocate(1)
                if got is None:
                    # evict an unreferenced cached prefix (offloading it
                    # to the host tier) before shrinking anyone's window
                    # or preempting a live sequence; the swap-out device
                    # read rides the heartbeat for the watchdog
                    with self._stamped():
                        reclaimed = self.engine.reclaim_cached(1)
                    if reclaimed:
                        got = self.engine.allocator.allocate(1)
                if got is not None:
                    state.blocks.extend(got)
                    continue
                if state.step_k > 0:
                    # cap on cache pressure: give up drafts before
                    # evicting anyone
                    state.step_k -= 1
                    continue
                if not self._preempt_youngest(exclude=state):
                    # nothing left to evict but this sequence itself:
                    # recompute it later when capacity returns
                    self._preempt_self(state)
                    break

    def _preempt_self(self, state: _Running) -> None:
        self.capacity.note_preempt(len(state.blocks))
        self.engine.stash_prefix(state)  # see _preempt_youngest
        self._release(state)
        req = state.req
        req.prompt = req.original_prompt + list(req.generated)
        req.mask_state = None  # rebuilt from `generated` at re-admission
        req.preemptions += 1
        self.preemptions += 1
        req.trace.note_preempt()
        with self._lock:
            self._queue.appendleft(req)

    def _collect_slots(self, order):
        """Slot-indexed arrays every batched device step needs: the
        seed token (last emitted, not yet cached), its cache position,
        block tables, the live mask, and per-slot sampling params —
        shared by the decode and verify assemblies so the two paths
        cannot drift. ``seeds``/``counts`` feed the engine's in-jit
        sampling-key derivation (ISSUE 13): byte-identical keys to the
        old host fold_in, with zero host key assembly on the hot path."""
        b = self.engine.max_batch_slots
        last = np.zeros((b,), np.int32)
        start = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.engine.max_blocks_per_seq), np.int32)
        active = np.zeros((b,), bool)
        temps = np.zeros((b,), np.float32)
        top_ks = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        counts = np.zeros((b,), np.int32)
        for state in order:
            i = state.slot
            req = state.req
            last[i] = req.generated[-1] if req.generated else req.prompt[-1]
            start[i] = state.cached_len  # next cache position
            tables[i, : len(state.blocks)] = state.blocks
            active[i] = True
            temps[i] = req.sampling.temperature
            top_ks[i] = req.sampling.top_k
            seeds[i] = req.sampling.seed & 0xFFFFFFFF
            counts[i] = req.n_generated
        return last, start, tables, active, temps, top_ks, seeds, counts

    def _decode_mask(self, order):
        """[B, V] grammar-mask rows for one decode step, or None when
        no live slot is constrained — the engine then stages its one
        cached zeros array: no per-step upload, no new program, the
        common case pays an any() over the batch."""
        if not any(s.req.mask_state is not None for s in order):
            return None
        mask = np.zeros(
            (self.engine.max_batch_slots, self.engine.cfg.vocab_size),
            np.float32,
        )
        n = 0
        for state in order:
            ms = state.req.mask_state
            if ms is not None:
                mask[state.slot] = ms.mask_row(state.req.sampling.eos_id)
                n += 1
        self.constrained_stats.incr("masked_steps", n)
        return mask

    def _quarantine_nan(self, kind: str, order) -> bool:
        """Act on the engine's per-slot NaN blame vector after a step
        that returned normally. Partial blame pins the poison on the
        flagged request(s): quarantine them, keep everyone else (their
        tokens from this step are valid and the step is about to scatter
        them). Whole-batch blame is not data-dependent — restart and
        journal-replay instead (returns True: skip the scatter)."""
        ok = self.engine.last_finite
        live = [s for s in order if self._running.get(s.slot) is s]
        blamed = [s for s in live if not bool(ok[s.slot])]
        if not blamed:
            return False
        # the failing step must be ON the flight ring before any
        # quarantine/restart incident freezes its snapshot
        self._flight_step()
        self.flight.record_event(
            "nan_blame", step=kind,
            request_ids=[s.req.id for s in blamed], live=len(live),
        )
        if len(blamed) == len(live) and len(live) > 1:
            self.supervisor.handle_engine_nan(kind)
            return True
        for state in blamed:
            self._quarantine(
                state,
                PoisonedRequestError(
                    f"request {state.req.id} produced non-finite logits at {kind} step",
                    request_id=state.req.id, step=kind, reason="nan_logits",
                ),
            )
        return False

    def _decode_step_fns(self, order):
        """The sequential decode step and its bisection probe over
        ``order``, built from ONE slot collection — shared by the
        sequential iteration and the pipeline-failure re-run so the two
        can never drift. (The old host "sample" phase — per-request
        fold_in + stack — is gone: sampling keys derive in-jit from
        (seed, count).)"""
        b = self.engine.max_batch_slots
        (tokens, positions, tables, active, temps, top_ks, seeds,
         counts) = self._collect_slots(order)
        mask = self._decode_mask(order)

        def step():
            return self.engine.decode(
                tokens, positions, tables, active, temps, top_ks, seeds,
                counts, mask,
            )

        def probe(subset):
            # blame-assignment probe: same step with only ``subset``
            # active; outputs discarded, cache writes idempotent (the
            # SAME mask as the real step, so bisection re-runs are
            # deterministic for constrained slots too)
            act = np.zeros((b,), bool)
            for s in subset:
                act[s.slot] = True
            self._probe_call(
                lambda: self.engine.decode(
                    tokens, positions, tables, act, temps, top_ks, seeds,
                    counts, mask,
                )
            )

        return step, probe

    def _decode_once(self) -> bool:
        if not self._running:
            return False
        t_c0 = time.perf_counter()
        order = sorted(self._running.values(), key=lambda s: s.slot)
        step, probe = self._decode_step_fns(order)
        t_c1 = time.perf_counter()
        self._span("schedule", t_c0, t_c1)
        ph, info = self._step_phases, self._step_info
        info["kind"] = "decode"
        t_dev = time.perf_counter()
        out = self.supervisor.run_step("decode", step, order, probe)
        ph["device"] = time.perf_counter() - t_dev
        if out is None:
            info["handled_failure"] = True
            return True  # failure handled: quarantined or journal-replayed
        info["execute_s"] = self._engine_spans()
        if self._quarantine_nan("decode", order):
            info["handled_failure"] = True
            return True
        t_book = time.perf_counter()
        n_live, _ = self._scatter_decode(order, out)
        self._span("bookkeep", t_book, time.perf_counter())
        info["emitted"] = n_live
        self.token_rate.record(n_live)
        return True

    def _scatter_decode(self, order, out, defer_finish: bool = False):
        """Scatter one decode step's sampled tokens back onto the slot
        states (shared by the sequential step, the pipeline consume,
        and the pipeline-failure sequential re-run). Returns
        (n_emitted, finished_states). ``defer_finish`` is the pipeline
        case: a finished slot's blocks must not be released while a
        successor step is still in flight over them — the caller drains
        the frontier first, then finishes. A slot that ALREADY finished
        at a previous consume is skipped outright (its token in a
        drained in-flight step is one a sequential scheduler would
        never have decoded)."""
        n_live = 0
        finish = []
        for state in order:
            if self._running.get(state.slot) is not state:
                continue  # preempted/expired between collect and scatter
            if state.req.handle.done():
                continue  # watchdog-reaped mid-step; _expire releases it
            if state.req.finished():
                continue  # finished at a previous pipeline consume
            state.cached_len += 1
            self._emit_token(state, int(out[state.slot]))
            state.req.trace.note_tokens(1, "decode")
            n_live += 1
            if state.req.finished():
                finish.append(state)
        if not defer_finish:
            for state in finish:
                self._finish(state)
        return n_live, finish

    # ----------------------------------------------------- overlap pipeline
    def _nonsteady(self, now: float) -> bool:
        """True when THIS iteration must run the sequential path (after
        a deterministic frontier drain): any event whose handling
        mutates slot/block state the in-flight step depends on, or
        whose semantics are defined sequentially — admission, finish,
        cancel/deadline, speculation, shutdown, a declared-dead
        engine."""
        if self._draining or self._hard_stop or self.supervisor.failed:
            return True
        if self._queue:
            with self._lock:
                queued = list(self._queue)
            for req in queued:
                if req.handle.done() or req.cancelled or (
                    req.deadline is not None and now >= req.deadline
                ):
                    return True  # queue expiry needs the sequential sweep
            if self._free_slots and self.breaker.ready():
                return True  # an admission could actually place
        for s in self._running.values():
            req = s.req
            if (
                req.handle.done()
                or req.cancelled
                or req.finished()
                or (req.deadline is not None and now >= req.deadline)
                or req.drafter is not None
                # constrained slots are non-steady by construction: the
                # pipeline dispatches step N+1 with step N's token still
                # device-resident, and the host cannot advance the
                # automaton (= build N+1's mask row) over a token it has
                # not seen. Sequential stepping keeps constrained
                # streams byte-identical overlap on/off — the existing
                # drafter clause rides the same reasoning.
                or req.grammar is not None
            ):
                return True
        return False

    def _discard_frontier(self) -> None:
        """Drop the in-flight step WITHOUT bookkeeping: its sampled
        tokens are never emitted, so the next sequential step recomputes
        them byte-identically (the step's K/V writes are idempotent
        rewrites of the same positions from the same inputs). Used when
        the in-flight result is tainted (NaN blame, stall, failure) or
        moot (shutdown, engine reset). Swallows the step's own error —
        the caller decides how the failure is handled."""
        f, self._pipe = self._pipe, None
        if f is None:
            return
        try:
            jax.block_until_ready((f.handle.out, f.handle.ok))
        except Exception:
            # restore the pre-step cache refs so a sequential re-run
            # reads intact inputs — but only while this step's outputs
            # are still current: a predecessor's consume failure may
            # already have rolled the whole chain back to OLDER intact
            # refs, and restoring forward would resurrect errored
            # arrays. (Non-donating engines; a donating engine only
            # reaches here on the reset + replay path.)
            h = f.handle
            if h.prev_k is not None and self.engine.cache.k is h.ck:
                self.engine.cache.update(h.prev_k, h.prev_v)
        self.pipe_discards += 1
        self._heartbeat = None

    def _drain_frontier(self, reason: str) -> None:
        """Deterministically empty the pipeline before a non-steady
        event: consume the in-flight step with FULL bookkeeping (tokens
        emitted, finishes resolved), so the scheduler state afterwards
        is exactly what a sequential scheduler would hold at the same
        point in every stream. Never raises — device failures take the
        pipeline-failure path (sequential supervisor semantics)."""
        f, self._pipe = self._pipe, None
        if f is None:
            return
        self.pipe_drains[reason] = self.pipe_drains.get(reason, 0) + 1
        try:
            self._consume_and_finish(f)
        except Exception as e:
            self._pipeline_failure(e, f.seq0)

    def _consume_and_finish(self, f: "_Frontier"):
        """Consume one in-flight decode step: blocked (double-buffered)
        readback, watchdog/stall arbitration, NaN blame, token scatter
        — then, if any stream finished, drain the successor frontier
        before releasing its blocks. Returns tokens emitted, or None
        when a failure was fully handled here (restart or whole-batch
        blame). Device errors propagate to the caller's
        pipeline-failure handling."""
        faults.inject(faults.GENERATION_ASYNC_READBACK, ("decode", len(f.states)))
        t_b0 = time.perf_counter()
        out = self.engine.consume_decode(f.handle)
        # completion stamp (satellite: dispatch AND completion): the
        # successor — if any — only starts device work now, so its
        # heartbeat age and execute span are measured from here; a
        # one-deep pipeline at long execute times is therefore never
        # misread as a wedged loop, while a consume that never returns
        # ages its own dispatch stamp until the watchdog trips
        nf = self._pipe
        if nf is not None:
            self._heartbeat = (nf.hb_seq, self.clock())
            nf.handle.t_started = time.perf_counter()
        else:
            self._heartbeat = None
        ph = self._step_phases
        ph["device"] = ph.get("device", 0.0) + (time.perf_counter() - t_b0)
        self._step_info["execute_s"] = (
            self._step_info.get("execute_s", 0.0) + self._engine_spans()
        )
        if self.supervisor._consume_stall(f.seq0):
            # the watchdog tripped while this chain was in flight: the
            # late result is stale — discard everything and replay
            # (exactly run_step's post-success stall arbitration). The
            # restart-inflated iteration stays out of the hot anatomy
            # window, like every handled failure (the PR 12 rule).
            self._step_info["handled_failure"] = True
            self._discard_frontier()
            self.supervisor._restart_and_replay(
                StalledStepError("decode step exceeded the watchdog stall timeout"),
                "decode",
            )
            return None
        ok = self.engine.last_finite
        live = [s for s in f.states if self._running.get(s.slot) is s]
        if any(not bool(ok[s.slot]) for s in live):
            # the successor was dispatched from this step's (poisoned)
            # token carry: discard it wholesale, then apply the standard
            # blame rules — partial blame quarantines and keeps the
            # survivors' tokens from THIS step, whole-batch restarts
            self._discard_frontier()
            if self._quarantine_nan("decode", f.states):
                self._step_info["handled_failure"] = True
                return None
        t_book = time.perf_counter()
        n_live, finish = self._scatter_decode(f.states, out, defer_finish=True)
        self.token_rate.record(n_live)
        if finish:
            # finish/EOS is a non-steady event: the successor step may
            # still be writing into the finishing streams' blocks —
            # drain it (bookkept; its tokens for finished slots are
            # skipped by the scatter) before any release
            if self._pipe is not None:
                self._drain_frontier("finish")
            for st in finish:
                if self._running.get(st.slot) is st and not st.req.handle.done():
                    self._finish(st)
        self._span("bookkeep", t_book, time.perf_counter())
        return n_live

    def _dispatch_pipeline(self, live, prev: Optional["_Frontier"]) -> "_Frontier":
        """Dispatch the next decode step without blocking. With an
        unconsumed predecessor, the token array is its device-resident
        output (no host round trip at all) and the argument arrays are
        the predecessor's, bumped in place — steady state rebuilds
        nothing and re-uploads nothing but three [B] scalars-per-slot
        vectors."""
        b = self.engine.max_batch_slots
        sig = tuple((s.slot, s.req.id, len(s.blocks)) for s in live)
        covered = {s.slot for s in prev.states} if prev is not None else set()
        if prev is not None and prev.sig == sig:
            positions, active = prev.positions, prev.active
            temps, top_ks = prev.temps, prev.top_ks
            seeds, counts, tables = prev.seeds, prev.counts, prev.tables
            for s in live:  # same composition: everyone advances by one
                positions[s.slot] += 1
                counts[s.slot] += 1
        else:
            positions = np.zeros((b,), np.int32)
            active = np.zeros((b,), bool)
            temps = np.zeros((b,), np.float32)
            top_ks = np.zeros((b,), np.int32)
            seeds = np.zeros((b,), np.uint32)
            counts = np.zeros((b,), np.int32)
            tables = np.zeros((b, self.engine.max_blocks_per_seq), np.int32)
            for s in live:
                i = s.slot
                pend = 1 if i in covered else 0
                positions[i] = s.cached_len + pend
                counts[i] = s.req.n_generated + pend
                active[i] = True
                temps[i] = s.req.sampling.temperature
                top_ks[i] = s.req.sampling.top_k
                seeds[i] = s.req.sampling.seed & 0xFFFFFFFF
                tables[i, : len(s.blocks)] = s.blocks
        tokens_host = None
        tokens_dev = prev.handle.out if prev is not None else None
        if prev is None:
            tokens_host = np.zeros((b,), np.int32)
            for s in live:
                req = s.req
                tokens_host[s.slot] = (
                    req.generated[-1] if req.generated else req.prompt[-1]
                )
        hb_prev = self._heartbeat
        seq0 = prev.seq0 if prev is not None else self._hb_seq
        self._hb_seq += 1
        seq = self._hb_seq
        self._heartbeat = (seq, self.clock())  # dispatch stamp
        try:
            handle = self.engine.decode_async(
                tokens_host, positions, tables, active, temps, top_ks,
                seeds, counts, tokens_dev=tokens_dev,
            )
        except Exception:
            self._heartbeat = hb_prev  # the step never went in flight
            self._hb_seq = seq  # seq stays burned; stall flags on it are void
            raise
        self._step_spans.append(("dispatch", handle.t0, handle.t_disp))
        ph = self._step_phases
        ph["dispatch"] = ph.get("dispatch", 0.0) + (handle.t_disp - handle.t0)
        return _Frontier(
            handle, list(live), positions, active, temps, top_ks, seeds,
            counts, tables, sig, seq, seq0,
        )

    def _pipeline_failure(self, e: BaseException, since_seq: int) -> None:
        """A pipelined dispatch or consume failed. Discard what is in
        flight (restoring pre-step cache refs when possible), then give
        the failed step the EXACT sequential treatment from the point
        after its first failure (supervisor.resume_step): retryable
        errors re-run invisibly, hard errors pay the breaker-accounted
        retry -> bisect -> restart ladder. A donating engine skips
        straight to reset + journal replay — its failed step consumed
        its own input buffers."""
        self.flight.record_event("pipeline_failure", error=repr(e)[:200])
        self._discard_frontier()
        self._step_info["handled_failure"] = True
        if self.engine.donate:
            self.supervisor._restart_and_replay(e, "decode")
            return
        order = [
            s for s in sorted(self._running.values(), key=lambda s: s.slot)
            if not s.req.handle.done() and not s.req.finished()
        ]
        if not order:
            return
        step, probe = self._decode_step_fns(order)
        out = self.supervisor.resume_step("decode", e, step, order, probe, since_seq)
        if out is None:
            return
        self._step_info["execute_s"] = self._engine_spans()
        if self._quarantine_nan("decode", order):
            return
        n_live, _ = self._scatter_decode(order, out)
        self.token_rate.record(n_live)
        self._step_info["handled_failure"] = False
        self._step_info["emitted"] = n_live

    def _try_pipeline(self) -> Optional[bool]:
        """One overlapped-decode iteration. Returns None when the
        iteration must run sequentially instead (the frontier is
        guaranteed drained by then); True when pipelined work happened.
        Steady state: dispatch step N+1 (token carry from step N's
        device output), then consume step N — its bookkeeping runs
        inside N+1's execute window instead of on the critical path."""
        now = self.clock()
        if self._nonsteady(now):
            # drain, then fall through to the sequential body in the
            # SAME iteration: the non-steady event (an admission, an
            # expiry, a verify step) must not wait an extra step —
            # join-mid-flight latency and TTFT keep their sequential
            # semantics. The drained consume's tokens/spans ride this
            # iteration's record.
            self._drain_frontier("nonsteady")
            return None
        order = sorted(self._running.values(), key=lambda s: s.slot)
        if not order:
            if self._pipe is not None:  # defensive: should be unreachable
                self._drain_frontier("idle")
            return None
        info = self._step_info
        t_s0 = time.perf_counter()
        f = self._pipe
        covered = {s.slot for s in f.states} if f is not None else set()
        # slots live at the NEXT dispatch: budget-predicted finishes are
        # excluded (sequential would have freed them before this step);
        # EOS cannot be predicted and is handled at consume
        live = []
        for s in order:
            pend = 1 if s.slot in covered else 0
            if s.req.n_generated + pend >= s.req.max_new:
                continue
            live.append(s)
        if not live:
            if f is None:
                return None
            # stream tail: nothing left to dispatch — consume only
            info["kind"] = "decode"
            self._pipe = None
            self._span("schedule", t_s0, time.perf_counter())
            try:
                n = self._consume_and_finish(f)
            except Exception as e:
                self._pipeline_failure(e, f.seq0)
                return True
            if n is not None:
                info["emitted"] = n
            return True
        # grow block tables for the dispatch positions (plain allocation
        # only: reclaim/preempt pressure is handled sequentially)
        for s in live:
            pend = 1 if s.slot in covered else 0
            need = self.engine.cache_config.blocks_for(s.cached_len + pend + 1)
            short = False
            while len(s.blocks) < need:
                got = self.engine.allocator.allocate(1)
                if got is None:
                    short = True
                    break
                s.blocks.extend(got)
            if short:
                self._span("schedule", t_s0, time.perf_counter())
                if f is not None:
                    info["kind"] = "decode"
                    self._drain_frontier("pressure")
                    return True
                return None
        self._span("schedule", t_s0, time.perf_counter())
        info["kind"] = "decode"
        try:
            new_f = self._dispatch_pipeline(live, f)
        except Exception as e:
            # dispatch failed host-side; the in-flight predecessor is
            # healthy — consume it first, then give the failed step the
            # sequential recovery treatment
            if f is not None:
                self._pipe = None
                try:
                    self._consume_and_finish(f)
                except Exception as e2:
                    self._pipeline_failure(e2, f.seq0)
                    return True
            # the predecessor (if any) consumed cleanly and cleared its
            # own stall flags; only trips from here on concern the re-run
            self._pipeline_failure(e, self._hb_seq)
            return True
        self._pipe = new_f
        self.pipe_dispatches += 1
        if f is None:
            info["emitted"] = 0  # warm-up: tokens arrive next iteration
            return True
        try:
            n = self._consume_and_finish(f)
        except Exception as e:
            self._pipeline_failure(e, f.seq0)
            return True
        if n is not None:
            info["emitted"] = n
        return True

    def _trim_blocks(self, state: _Running) -> None:
        """Return trailing blocks a partially-accepted window no longer
        covers (their positions hold rejected-draft garbage the next
        window would rewrite anyway). Keeps allocator accounting exact
        when acceptance stops short of a block boundary. cached_len + 1,
        not cached_len: the next step always writes position cached_len,
        so trimming its block would hand it to a queued request at
        _admit and force an avoidable preemption one step later."""
        keep = max(1, self.engine.cache_config.blocks_for(state.cached_len + 1))
        if len(state.blocks) > keep:
            extra = state.blocks[keep:]
            del state.blocks[keep:]
            self.engine.allocator.free(extra)
            self.capacity.note_trim(len(extra))

    def _verify_mask(self, order, window, n_draft) -> Optional[np.ndarray]:
        """(batch, window, vocab) additive grammar bias for ONE verify
        step, or None when nothing running is constrained (the engine
        stages its cached all-zeros array — no new program, no upload).

        Position j of the window samples the token that FOLLOWS the
        first j window tokens, so row 0 is the current automaton
        state's mask and row j+1 is the mask at the state reached by
        consuming draft tokens 0..j — exactly the states a masked
        sequential decode would pass through if it accepted that
        prefix. Masking draft scoring and target sampling with the
        same rows is what keeps speculative acceptance byte-identical
        to the unspeculated constrained stream."""
        if not any(s.req.mask_state is not None for s in order):
            return None
        mask = np.zeros(
            (self.engine.max_batch_slots, self.engine.spec_window,
             self.engine.cfg.vocab_size),
            np.float32,
        )
        n = 0
        for state in order:
            ms = state.req.mask_state
            if ms is None:
                continue
            i = state.slot
            eos = state.req.sampling.eos_id
            mask[i, 0] = ms.mask_row(eos)
            draft = [int(t) for t in window[i, 1 : 1 + max(0, int(n_draft[i]))]]
            for j, st in enumerate(ms.states_along(draft, eos)):
                mask[i, j + 1] = ms.dfa.mask_row(st, eos)
            n += 1
        self.constrained_stats.incr("masked_steps", n)
        return mask

    def _verify_once(self) -> bool:
        """One speculative verification step across all running slots:
        draft (host), verify the batch × (k+1) window (ONE fixed-shape
        device call), then emit each slot's accepted run — truncated at
        mid-window EOS and the request's budget."""
        if not self._running:
            return False
        b = self.engine.max_batch_slots
        w = self.engine.spec_window
        ph, info = self._step_phases, self._step_info
        info["kind"] = "verify"
        t_c0 = time.perf_counter()
        order = sorted(self._running.values(), key=lambda s: s.slot)
        (last, start, tables, _active, temps, top_ks, seeds,
         counts) = self._collect_slots(order)
        t_draft = time.perf_counter()
        self._span("schedule", t_c0, t_draft)
        window = np.zeros((b, w), np.int32)
        window[:, 0] = last
        n_draft = np.full((b,), -1, np.int32)  # -1 = inactive slot
        for state in order:
            i = state.slot
            req = state.req
            draft: List[int] = []
            if state.step_k > 0 and req.drafter is not None:
                try:
                    # original_prompt, NOT prompt: after a preemption the
                    # recompute prompt already folds in generated tokens
                    draft = list(
                        req.drafter.propose(
                            req.original_prompt + req.generated, state.step_k
                        )
                    )[: state.step_k]
                except Exception:
                    # a dying drafter must not kill the scheduler loop:
                    # verification is exact with ANY draft, so a failed
                    # proposal degrades to a plain (zero-draft) step
                    self.stats.incr("drafter_errors")
            if req.mask_state is not None and draft:
                # grammar-banned draft tokens would be rejected by the
                # masked target anyway; trimming to the longest legal
                # prefix just stops them wasting verify positions
                draft = req.mask_state.filter_draft(draft, req.sampling.eos_id)
            window[i, 1 : 1 + len(draft)] = draft
            n_draft[i] = len(draft)
        t_d1 = time.perf_counter()
        self._span("draft", t_draft, t_d1)
        # the per-window key matrix derives in-jit from (seed, count) —
        # the old host "sample" phase (vmapped fold_in + stack per
        # request) no longer exists
        info["drafted"] = int(np.maximum(n_draft, 0).sum())
        wmask = self._verify_mask(order, window, n_draft)

        def step():
            return self.engine.verify(
                window, start, n_draft, tables, temps, top_ks, seeds, counts,
                mask=wmask,
            )

        def probe(subset):
            nd = np.full((b,), -1, np.int32)  # everyone else inactive
            for s in subset:
                nd[s.slot] = n_draft[s.slot]
            self._probe_call(
                lambda: self.engine.verify(
                    window, start, nd, tables, temps, top_ks, seeds, counts,
                    mask=wmask,
                )
            )

        t_dev = time.perf_counter()
        result = self.supervisor.run_step("verify", step, order, probe)
        ph["device"] = time.perf_counter() - t_dev
        if result is None:
            info["handled_failure"] = True
            return True  # failure handled: quarantined or journal-replayed
        info["execute_s"] = self._engine_spans()
        out, n_emitted = result
        if self._quarantine_nan("verify", order):
            info["handled_failure"] = True
            return True
        t_book = time.perf_counter()
        n_accepted = 0
        n_live_tokens = 0
        for state in order:
            if self._running.get(state.slot) is not state:
                continue  # preempted/expired between collect and scatter
            if state.req.handle.done():
                continue  # watchdog-reaped mid-step; _expire releases it
            req = state.req
            i = state.slot
            m = int(n_emitted[i])
            toks = [int(t) for t in out[i, :m]]
            # budget truncation: never emit past max_new
            toks = toks[: req.max_new - req.n_generated]
            # mid-window EOS: keep through the FIRST eos, drop the rest
            eos = req.sampling.eos_id
            if eos is not None and eos in toks:
                toks = toks[: toks.index(eos) + 1]
            accepted = max(0, m - 1)  # drafts the target agreed with
            n_accepted += accepted
            req.update_speculation(proposed=int(max(0, n_draft[i])), accepted=accepted)
            req.trace.note_speculation(int(max(0, n_draft[i])), accepted)
            emitted = 0
            for t in toks:
                self._emit_token(state, t)
                emitted += 1
                if req.mask_state is not None and (
                    req.mask_error is not None or req.finished()
                ):
                    # constrained stream ended mid-window — a parked
                    # advance error or the exhaustion clamp. The rest of
                    # the accepted run was sampled at states past the
                    # grammar's end: drop it, never surface or cache it.
                    break
            self.spec_stats.record_window(
                proposed=int(max(0, n_draft[i])), accepted=accepted, emitted=emitted
            )
            req.trace.note_tokens(emitted, "verify")
            state.cached_len += emitted
            self._trim_blocks(state)
            n_live_tokens += emitted
            if req.finished():
                self._finish(state)
        self._span("bookkeep", t_book, time.perf_counter())
        info["accepted"] = n_accepted
        info["emitted"] = n_live_tokens
        self.token_rate.record(n_live_tokens)
        return True

    def _span(self, name: str, t0: float, t1: float) -> None:
        """Record one host span of THIS iteration: real perf_counter
        stamps for the anatomy profiler, duration accumulated into the
        flight record's phase dict. Loop thread only."""
        self._step_spans.append((name, t0, t1))
        ph = self._step_phases
        ph[name] = ph.get(name, 0.0) + (t1 - t0)

    def _engine_spans(self) -> float:
        """Adopt the engine's last step's dispatch/block/execute/
        readback spans into this iteration's anatomy span list (NOT
        into the flight phases — those keep the conflated "device"
        total so the ring's series stays continuous). Returns the
        device-execute seconds for the flight record's new
        ``execute_s`` field."""
        spans = self.engine.last_step_spans
        self._step_spans.extend(spans)
        return sum(s1 - s0 for name, s0, s1 in spans if name == "execute")

    def _flight_step(self) -> None:
        """Write THIS iteration's step record (idempotent per step):
        normally at the end of step(), but flushed early when NaN blame
        is about to freeze an incident snapshot — the failing step must
        be on the ring its own postmortem is cut from."""
        if self._step_recorded or not self.flight.enabled:
            return
        self._step_recorded = True
        info = dict(self._step_info)
        self.flight.record_step(
            info.pop("kind", "admit"),
            phases=dict(self._step_phases),
            occupancy=len(self._running),
            queue_depth=len(self._queue),
            blocks_free=self.engine.allocator.num_free,
            **info,
        )

    # ---------------------------------------------------------------- step
    def step(self) -> bool:
        """One scheduling iteration: expire, admit (join-mid-flight),
        plan speculation, grow/preempt, then decode — or verify, when
        any running request speculates. Returns True if any work
        happened. Each working iteration writes one flight-recorder
        step record with its phase decomposition (admission prefills
        record their own entries inside _admit). With a fault_scope
        (fleet replica), the whole iteration — including the supervisor
        recovery path — runs inside that injection scope so chaos plans
        can target this replica alone."""
        if self.fault_scope is None:
            return self._step_impl()
        with faults.scope(self.fault_scope):
            return self._step_impl()

    def _step_impl(self) -> bool:
        self._step_phases = {}
        info = self._step_info = {}
        self._step_spans = []
        self._step_recorded = False
        t0 = time.perf_counter()
        if self.overlap:
            # overlapped decode: steady-state iterations pipeline
            # dispatch/consume; any non-steady event drains the
            # frontier and falls through to the sequential body below
            r = self._try_pipeline()
            if r is not None:
                if r:
                    self._flight_step()
                    self.anatomy.observe_step(
                        info.get("kind", "decode"), self._step_spans, t0,
                        time.perf_counter(),
                        tokens=int(info.get("emitted", 0)),
                        hot=not info.get("handled_failure", False),
                    )
                # durable group commit rides the pipeline's execute
                # window like the other host bookkeeping (no-op on the
                # base journal)
                self.journal.flush_step()
                self.capacity.tick()
                self._overload_tick()
                return r
        self._expire()
        self._sweep_mask_errors()
        t1 = time.perf_counter()
        self._span("schedule", t0, t1)
        admitted = 0
        # admit as many as fit THIS iteration — they decode together
        # below. Admission spans (admit / prefix_plan / the prefill's
        # dispatch-execute-readback) are recorded inside _admit.
        while self._admit():
            admitted += 1
        t2 = time.perf_counter()
        self._plan_speculation()
        self._grow()
        t3 = time.perf_counter()
        self._span("schedule", t2, t3)
        if admitted:
            info["admitted"] = admitted
        speculating = any(s.step_k > 0 for s in self._running.values())
        stepped = self._verify_once() if speculating else self._decode_once()
        did = stepped or admitted > 0
        if did:
            self._flight_step()
            # one anatomy observation per working iteration: host spans
            # + the device execute lane, under the iteration's step kind
            # (admission work inside a decode iteration charges the
            # decode critical path — which is exactly where it sits).
            # Handled-failure iterations stay out of the hot window:
            # they have no execute span and a retry-inflated wall that
            # would skew the bubble/headroom math for a whole window.
            self.anatomy.observe_step(
                info.get("kind", "admit"), self._step_spans, t0,
                time.perf_counter(),
                tokens=int(info.get("emitted", 0)) + admitted,
                hot=not info.get("handled_failure", False),
            )
        # durable group commit: one write+fsync for every journal
        # record this iteration buffered (admits, token deltas, ends) —
        # off the device dispatch path, a no-op on the base journal
        self.journal.flush_step()
        # integrate time-at-pressure AFTER the step's allocations, so
        # the pressure flag reflects the state the next interval runs in
        # (injectable clock: virtual-clock tests integrate exactly);
        # the overload control plane ticks on the fresh pressure flag
        self.capacity.tick()
        self._overload_tick()
        return did
