"""Continuous batching: iteration-level scheduling of generation
requests (Orca, OSDI'22) over the block KV cache.

Unlike the request-level DynamicBatcher (serving/batcher.py), which
holds a batch's composition fixed for a whole device call, generation
is scheduled per *iteration*: every ``step()`` runs ONE decode across
the engine's fixed batch slots, and between steps the batch recomposes
freely —

* **join-mid-flight**: a queued request is admitted (FCFS) the moment a
  slot AND enough cache blocks are free; it prefils and decodes
  alongside sequences that are hundreds of tokens in;
* **free-on-finish**: a sequence hitting EOS / max-tokens / its
  deadline releases its blocks in the same step, so capacity returns
  immediately instead of at batch boundaries;
* **preempt-by-recompute**: if the cache cannot grow a running
  sequence, the youngest running sequence is evicted — blocks freed,
  prompt + generated-so-far re-queued at the FRONT — and later
  re-prefilled (vLLM's recompute preemption). Seeded sampling keys are
  indexed by generated-token count, so a preempted request's token
  stream continues exactly where it left off.

Resilience mirrors PR 1's serving semantics: bounded queue
(QueueFullError), per-request deadlines (DeadlineExceededError before
OR during generation), retry-with-backoff for TransientDeviceError,
and a circuit breaker around device steps — all on an injectable clock
so chaos tests run on virtual time. Fault sites: ``generation.prefill``
and ``generation.decode_step`` (runtime/faults.py).

The scheduler is synchronous-by-design: ``step()`` does one iteration
and returns, so property tests drive it deterministically; ``start()``
wraps it in a background thread for serving.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..runtime import faults
from ..serving.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    QueueFullError,
    RetryPolicy,
    ShuttingDownError,
)
from ..serving.stats import ServingStats, TokenRate
from .engine import GenerationEngine, SamplingParams

_END = object()  # token-stream sentinel


class GenerationHandle:
    """Caller's view of one request: a Future of the generated token
    list plus a per-token stream."""

    def __init__(self, request: "Request"):
        self._request = request
        self.future: Future = Future()
        self._tokens: "queue.Queue" = queue.Queue()

    # ----------------------------------------------------------- caller
    def done(self) -> bool:
        return self.future.done()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        return self.future.result(timeout=timeout)

    def cancel(self) -> None:
        """Ask the scheduler to drop this request at its next step."""
        self._request.cancelled = True

    def tokens(self, timeout: Optional[float] = None):
        """Iterate generated tokens as they are produced. Raises the
        request's failure if it errors mid-stream."""
        while True:
            item = self._tokens.get(timeout=timeout)
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    # -------------------------------------------------------- scheduler
    def _emit(self, token: int) -> None:
        self._tokens.put(token)

    def _finish(self, tokens: List[int]) -> None:
        self._tokens.put(_END)
        if not self.future.done():
            self.future.set_result(tokens)

    def _fail(self, err: BaseException) -> None:
        self._tokens.put(err)
        self._tokens.put(_END)
        if not self.future.done():
            self.future.set_exception(err)


class Request:
    """One generation request. ``prompt`` may grow on preemption (the
    generated prefix is folded in for recompute); ``n_generated`` is the
    TOTAL generated count across preemptions, which also indexes the
    per-request sampling key stream."""

    _ids = itertools.count()

    def __init__(
        self,
        prompt: List[int],
        sampling: SamplingParams,
        deadline: Optional[float] = None,
    ):
        self.id = next(Request._ids)
        self.original_prompt = list(prompt)
        self.prompt = list(prompt)  # prompt + recomputed prefix
        self.sampling = sampling
        self.deadline = deadline  # absolute, scheduler clock
        self.submitted_at = 0.0  # stamped by the scheduler
        # effective budget, possibly clamped to the cache room the
        # scheduler can actually give this sequence
        self.max_new = sampling.max_new_tokens
        self.generated: List[int] = []  # tokens generated so far (total)
        self.cancelled = False
        self.preemptions = 0
        self.handle = GenerationHandle(self)
        # seed-only (no request-id mixing): the same seed + prompt +
        # params must reproduce the same tokens, run to run
        self.base_key = jax.random.key(sampling.seed)

    @property
    def n_generated(self) -> int:
        return len(self.generated)

    def sample_key(self) -> jax.Array:
        """Key for the NEXT token: indexed by generated count, so a
        recomputed request continues its exact sampling stream."""
        return jax.random.fold_in(self.base_key, self.n_generated)

    def finished(self) -> bool:
        if self.n_generated >= self.max_new:
            return True
        eos = self.sampling.eos_id
        return eos is not None and bool(self.generated) and self.generated[-1] == eos


class _Running:
    """Slot-resident state for an admitted request."""

    __slots__ = ("req", "slot", "blocks", "cached_len", "admitted_seq")

    def __init__(self, req: Request, slot: int, blocks: List[int], cached_len: int, admitted_seq: int):
        self.req = req
        self.slot = slot
        self.blocks = blocks
        self.cached_len = cached_len  # cache positions written so far
        self.admitted_seq = admitted_seq  # admission order, for LIFO preemption


class ContinuousBatchingScheduler:
    def __init__(
        self,
        engine: GenerationEngine,
        *,
        max_queue: int = 256,
        clock: Callable[[], float] = time.monotonic,
        breaker: Optional[CircuitBreaker] = None,
        retry: Optional[RetryPolicy] = None,
        idle_wait_s: float = 0.002,
    ):
        self.engine = engine
        self.max_queue = max_queue
        self.clock = clock
        self.breaker = breaker or CircuitBreaker(clock=clock)
        self.retry = retry or RetryPolicy()
        self.idle_wait_s = idle_wait_s
        self._queue: deque = deque()
        self._running: Dict[int, _Running] = {}  # slot -> state
        self._free_slots = list(range(engine.max_batch_slots - 1, -1, -1))
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._alive = False
        self._draining = False
        self._hard_stop = False
        self._stopped = False  # a stopped (started-then-stopped) scheduler rejects submits
        self._admitted_seq = itertools.count()
        # observability (surfaced on /v2/stats via GenerationModel)
        self.stats = ServingStats()
        self.token_rate = TokenRate(clock=time.monotonic)
        self.preemptions = 0
        self.stats.add_gauge("queue_depth", lambda: len(self._queue))
        self.stats.add_gauge("running", lambda: len(self._running))
        self.stats.add_gauge("tokens_generated", lambda: self.token_rate.total)
        self.stats.add_gauge("tokens_per_s", self.token_rate.rate)
        self.stats.add_gauge("preemptions", lambda: self.preemptions)
        self.stats.add_gauge(
            "cache_blocks_used",
            lambda: self.engine.allocator.num_total - self.engine.allocator.num_free,
        )
        self.stats.add_gauge("cache_blocks_total", lambda: self.engine.allocator.num_total)
        self.stats.add_gauge(
            "cache_occupancy",
            lambda: 1.0 - self.engine.allocator.num_free / max(1, self.engine.allocator.num_total),
        )
        self.stats.add_gauge("recompiles", lambda: sum(self.engine.recompiles().values()))

    # ------------------------------------------------------------- submit
    def submit(
        self,
        prompt: Sequence[int],
        sampling: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> GenerationHandle:
        """Enqueue one request (FCFS). Typed rejections mirror the
        batcher: QueueFullError on backpressure, CircuitOpenError while
        the breaker holds traffic, ShuttingDownError while draining,
        DeadlineExceededError for an already-expired budget."""
        if self._draining:
            raise ShuttingDownError("generation scheduler draining")
        if self._stopped:
            raise ShuttingDownError("generation scheduler stopped")
        sampling = sampling or SamplingParams()
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.engine.buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds max bucket {self.engine.buckets[-1]}"
            )
        room = self.engine.max_seq_len - len(prompt)
        if room < 1:
            raise ValueError(f"prompt fills max_seq_len {self.engine.max_seq_len}")
        if (
            self.engine.cache_config.blocks_for(len(prompt) + 1)
            > self.engine.allocator.num_total
        ):
            raise ValueError("prompt exceeds total cache capacity; can never be admitted")
        if deadline_s is not None and deadline_s <= 0:
            self.stats.incr("expired")
            raise DeadlineExceededError("deadline already expired at submit")
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self.stats.incr("rejected")
                raise QueueFullError(f"generation queue full ({self.max_queue})")
            if not self.breaker.allow():
                self.stats.incr("rejected")
                raise CircuitOpenError("generation circuit open")
            deadline = None if deadline_s is None else self.clock() + deadline_s
            req = Request(list(prompt), sampling, deadline=deadline)
            req.submitted_at = self.clock()
            # the sequence can never outgrow max_seq_len (its last token
            # would need a cache position past the block table) NOR the
            # TOTAL cache: a sequence needing more blocks than exist
            # would preempt-self forever at the head of the FCFS queue
            cache_room = (
                self.engine.allocator.num_total * self.engine.cache_config.block_size
                - len(prompt)
            )
            req.max_new = min(sampling.max_new_tokens, room, cache_room)
            self._queue.append(req)
        self.stats.incr("admitted")
        self._wake.set()
        return req.handle

    # ------------------------------------------------------------ control
    def start(self) -> None:
        if self._alive:
            return
        self._alive = True
        self._draining = False
        self._hard_stop = False
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful by default: finish queued + running requests, then
        exit. ``drain=False`` fails outstanding work immediately."""
        if self._thread is None:
            # never-started (manual-step) scheduler: honor the drain
            # contract inline — queued futures must not hang forever
            self._draining = True
            if drain:
                while self.has_work() and self.step():
                    pass
            self._abort_all(ShuttingDownError("scheduler stopped"))
            self._draining = False
            self._stopped = True
            return
        self._draining = True
        self._alive = False
        if not drain:
            self._hard_stop = True  # loop exits after the current step
        self._wake.set()
        self._thread.join(timeout=timeout)
        wedged = self._thread.is_alive()
        self._thread = None
        if wedged:
            # a wedged step keeps ownership of the slot/allocator state;
            # touching it here would race the live thread
            return
        if drain:
            # the loop exited; anything still outstanding completes here
            while self.has_work() and self.step():
                pass
        else:
            # abort only AFTER the loop exited: _abort_all mutates
            # _running/allocator state the stepping thread owns
            self._abort_all(ShuttingDownError("scheduler stopped"))
        self._draining = False
        self._stopped = True

    def _abort_all(self, err: BaseException) -> None:
        with self._lock:
            queued, self._queue = list(self._queue), deque()
        for req in queued:
            req.handle._fail(err)
            self.stats.incr("failed")
        for state in list(self._running.values()):
            self._release(state)
            state.req.handle._fail(err)
            self.stats.incr("failed")

    def ready(self) -> bool:
        return not self._draining and self.breaker.ready()

    def has_work(self) -> bool:
        return bool(self._queue) or bool(self._running)

    def _loop(self) -> None:
        while (self._alive or (self._draining and self.has_work())) and not self._hard_stop:
            if not self.step():
                self._wake.wait(timeout=self.idle_wait_s)
                self._wake.clear()

    # ---------------------------------------------------------- internals
    def _release(self, state: _Running) -> None:
        self.engine.allocator.free(state.blocks)
        state.blocks = []
        del self._running[state.slot]
        self._free_slots.append(state.slot)

    def _finish(self, state: _Running) -> None:
        self._release(state)
        req = state.req
        self.stats.latency.record(max(0.0, self.clock() - req.submitted_at))
        req.handle._finish(list(req.generated))
        self.stats.incr("completed")

    def _expire(self) -> None:
        now = self.clock()
        with self._lock:
            keep: deque = deque()
            for req in self._queue:
                if req.cancelled:
                    req.handle._fail(ShuttingDownError("request cancelled"))
                    self.stats.incr("cancelled")
                elif req.deadline is not None and now >= req.deadline:
                    req.handle._fail(DeadlineExceededError("deadline expired while queued"))
                    self.stats.incr("expired")
                else:
                    keep.append(req)
            self._queue = keep
        for state in list(self._running.values()):
            req = state.req
            if req.cancelled:
                self._release(state)
                req.handle._fail(ShuttingDownError("request cancelled"))
                self.stats.incr("cancelled")
            elif req.deadline is not None and now >= req.deadline:
                self._release(state)
                req.handle._fail(DeadlineExceededError("deadline expired mid-generation"))
                self.stats.incr("expired")

    def _device(self, fn):
        """Run one device step under retry + breaker accounting."""
        try:
            out = self.retry.run(fn)
        except Exception:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return out

    def _preempt_youngest(self, exclude: Optional[_Running] = None) -> bool:
        """Evict the most recently admitted running sequence (vLLM's
        LIFO recompute victim): free its blocks, fold its generated
        tokens into the prompt, and requeue it at the FRONT."""
        victims = [s for s in self._running.values() if s is not exclude]
        if not victims:
            return False
        victim = max(victims, key=lambda s: s.admitted_seq)
        self._release(victim)
        req = victim.req
        req.prompt = req.original_prompt + list(req.generated)
        req.preemptions += 1
        self.preemptions += 1
        with self._lock:
            self._queue.appendleft(req)
        return True

    def _admit(self) -> bool:
        """FCFS, cache-capacity-aware admission. Returns True if a
        request was admitted (prefilled)."""
        with self._lock:
            if not self._queue or not self._free_slots:
                return False
            req = self._queue[0]
            need = self.engine.cache_config.blocks_for(len(req.prompt) + 1)
            blocks = self.engine.allocator.allocate(need)
            if blocks is None:
                return False
            self._queue.popleft()
            slot = self._free_slots.pop()
        try:
            token = self._device(
                lambda: self.engine.prefill_one(
                    req.prompt, blocks, req.sampling, req.sample_key()
                )
            )
        except Exception as e:
            self.engine.allocator.free(blocks)
            self._free_slots.append(slot)
            req.handle._fail(e)
            self.stats.incr("failed")
            return True  # did work (and must not spin on the same head)
        state = _Running(req, slot, blocks, cached_len=len(req.prompt), admitted_seq=next(self._admitted_seq))
        self._running[slot] = state
        self._emit_token(state, token)
        self.token_rate.record(1)
        if req.finished():
            self._finish(state)
        return True

    def _emit_token(self, state: _Running, token: int) -> None:
        state.req.generated.append(int(token))
        state.req.handle._emit(int(token))

    def _grow(self) -> None:
        """Ensure every running sequence has a cache slot for its next
        token; preempt-by-recompute on exhaustion."""
        for state in list(self._running.values()):
            if self._running.get(state.slot) is not state:
                continue  # preempted earlier in this sweep
            need = self.engine.cache_config.blocks_for(state.cached_len + 1)
            while len(state.blocks) < need:
                got = self.engine.allocator.allocate(1)
                if got is not None:
                    state.blocks.extend(got)
                    continue
                if not self._preempt_youngest(exclude=state):
                    # nothing left to evict but this sequence itself:
                    # recompute it later when capacity returns
                    self._preempt_self(state)
                    break

    def _preempt_self(self, state: _Running) -> None:
        self._release(state)
        req = state.req
        req.prompt = req.original_prompt + list(req.generated)
        req.preemptions += 1
        self.preemptions += 1
        with self._lock:
            self._queue.appendleft(req)

    def _decode_once(self) -> bool:
        if not self._running:
            return False
        b = self.engine.max_batch_slots
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.engine.max_blocks_per_seq), np.int32)
        active = np.zeros((b,), bool)
        temps = np.zeros((b,), np.float32)
        top_ks = np.zeros((b,), np.int32)
        keys = []
        order = sorted(self._running.values(), key=lambda s: s.slot)
        for state in order:
            i = state.slot
            req = state.req
            tokens[i] = req.generated[-1] if req.generated else req.prompt[-1]
            positions[i] = state.cached_len  # next cache position
            tables[i, : len(state.blocks)] = state.blocks
            active[i] = True
            temps[i] = req.sampling.temperature
            top_ks[i] = req.sampling.top_k
        key_by_slot = {s.slot: s.req.sample_key() for s in order}
        dummy = jax.random.key(0)
        keys = jax.numpy.stack([key_by_slot.get(i, dummy) for i in range(b)])
        try:
            out = self._device(
                lambda: self.engine.decode(
                    tokens, positions, tables, active, temps, top_ks, keys
                )
            )
        except Exception as e:
            # a decode failure is batch-wide: fail every running request
            # (leaf attribution like the batcher's bisection needs
            # per-sequence device calls, which defeats batching here)
            for state in list(self._running.values()):
                self._release(state)
                state.req.handle._fail(e)
                self.stats.incr("failed")
            return True
        n_live = 0
        for state in order:
            if self._running.get(state.slot) is not state:
                continue  # preempted/expired between collect and scatter
            state.cached_len += 1
            self._emit_token(state, int(out[state.slot]))
            n_live += 1
            if state.req.finished():
                self._finish(state)
        self.token_rate.record(n_live)
        return True

    # ---------------------------------------------------------------- step
    def step(self) -> bool:
        """One scheduling iteration: expire, admit (join-mid-flight),
        grow/preempt, decode. Returns True if any work happened."""
        self._expire()
        did = False
        # admit as many as fit THIS iteration — they decode together below
        while self._admit():
            did = True
        self._grow()
        if self._decode_once():
            did = True
        return did
