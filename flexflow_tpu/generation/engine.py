"""Generation engine: prefill/decode split over the block KV cache.

XLA has no dynamic shapes, so naive generation recompiles on every
prompt length and batch size. The engine compiles a FIXED family of
programs instead:

* **prefill** — one jitted program per *prompt-length bucket* (prompt
  padded up to the bucket; per-sequence length masking keeps logits
  identical to the unpadded forward). A handful of buckets covers every
  prompt, and a bucket compiles at most once.
* **decode** — ONE jitted program, period: always ``max_batch_slots``
  sequences (inactive slots masked to scratch block 0), always the same
  block-table width. Steady-state decode NEVER recompiles, whatever
  joins or leaves the batch — the property tools/genbench.py asserts.

``trace_counts`` counts actual retraces (the Python body only runs at
trace time), so tests and the bench can assert the compile behavior
instead of trusting it.

Sampling (greedy / temperature / top-k) runs inside the jitted steps —
per-slot parameters are arrays, so mixed sampling configs share one
program.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerConfig
from ..runtime import faults
from .cache import BlockAllocator, CacheConfig, KVCache, slot_mapping
from .decoder import DecoderParams, decode_step, prefill, verify_step

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature <= 0`` means greedy (argmax); ``top_k <= 0`` disables
    the top-k filter. ``seed`` makes the request's sampling stream
    deterministic — preemption-by-recompute replays the same stream.
    """

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    seed: int = 0


def default_buckets(max_seq_len: int, start: int = 16) -> Tuple[int, ...]:
    """Doubling prompt-length buckets: start, 2*start, ... up to (and
    including) max_seq_len."""
    buckets: List[int] = []
    b = min(start, max_seq_len)
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq_len)
    return tuple(buckets)


def topk_scaled_logits(logits, temps, top_ks):
    """Temperature-scaled, top-k-masked logits — THE sampling transform
    for both the decode step and speculative verification (speculative/
    sampling.py imports this one; two copies drifting apart would break
    the zero-draft-verify ≡ decode bit-exactness contract).

    logits [..., V]; temps/top_ks shaped logits.shape[:-1] (callers
    broadcast). temp <= 0 rows are scaled by 1 (greedy callers argmax
    the RAW logits); top_k <= 0 disables the top-k filter.
    """
    v = logits.shape[-1]
    safe_t = jnp.where(temps <= 0.0, 1.0, temps)
    scaled = logits / safe_t[..., None]
    k = jnp.where(top_ks <= 0, v, jnp.clip(top_ks, 1, v)).astype(jnp.int32)
    sorted_desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    thresh = jnp.take_along_axis(sorted_desc, k[..., None] - 1, axis=-1)
    return jnp.where(scaled >= thresh, scaled, NEG_INF)


def _sample(logits, temps, top_ks, keys):
    """Vectorized sampling: greedy where temp<=0, else temperature +
    optional top-k. logits [B, V]; temps/top_ks [B]; keys [B] PRNG."""
    v = logits.shape[-1]
    greedy = temps <= 0.0
    masked = topk_scaled_logits(logits, temps, top_ks)
    gumbel = jax.vmap(lambda key: jax.random.gumbel(key, (v,)))(keys)
    sampled = jnp.argmax(masked + gumbel, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)


class GenerationEngine:
    """Owns the cache, the allocator, and the jitted step family. The
    continuous-batching scheduler drives it; ``generate`` is a
    convenience wrapper that spins up a private scheduler."""

    def __init__(
        self,
        params: DecoderParams,
        cfg: TransformerConfig,
        cache_config: Optional[CacheConfig] = None,
        *,
        cache_budget_bytes: Optional[int] = None,
        max_batch_slots: int = 4,
        prompt_buckets: Optional[Sequence[int]] = None,
        max_seq_len: Optional[int] = None,
        block_size: int = 16,
        max_spec_tokens: int = 4,
    ):
        self.params = params
        self.cfg = cfg
        self.max_seq_len = max_seq_len or cfg.seq_length
        self.max_batch_slots = max_batch_slots
        if cache_config is None:
            if cache_budget_bytes is not None:
                cache_config = CacheConfig.from_budget(
                    cache_budget_bytes,
                    num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    head_dim=cfg.hidden_size // cfg.num_heads,
                    block_size=block_size,
                )
            else:
                # enough for every slot to reach max_seq_len, plus scratch
                per_seq = -(-self.max_seq_len // block_size)
                cache_config = CacheConfig(
                    num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    head_dim=cfg.hidden_size // cfg.num_heads,
                    num_blocks=1 + per_seq * max_batch_slots,
                    block_size=block_size,
                )
        self.cache_config = cache_config
        self.cache = KVCache.create(cache_config)
        self.allocator = BlockAllocator(cache_config)
        self.max_blocks_per_seq = cache_config.blocks_for(self.max_seq_len)
        self.buckets = tuple(sorted(prompt_buckets or default_buckets(self.max_seq_len)))
        if self.buckets[-1] > self.max_seq_len:
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds max_seq_len {self.max_seq_len}"
            )
        if self.buckets[-1] < self.max_seq_len:
            # preemption-by-recompute re-prefills prompt + generated,
            # which can reach max_seq_len - 1: there must be a bucket
            # that holds it
            self.buckets = self.buckets + (self.max_seq_len,)
        if max_spec_tokens < 1:
            raise ValueError("max_spec_tokens must be >= 1")
        # speculative verification window: 1 committed token + up to
        # max_spec_tokens drafts, ONE fixed jit shape whatever per-
        # request adaptive k does
        self.max_spec_tokens = max_spec_tokens
        self.spec_window = max_spec_tokens + 1
        self.backend = jax.default_backend()
        # retrace counters: the Python body runs only when XLA traces, so
        # these count compiles, not calls (genbench's recompile guard)
        self.trace_counts: Dict[str, int] = {}
        # host-call counters: engine steps actually issued (genbench's
        # tokens-per-engine-step accounting)
        self.step_counts: Dict[str, int] = {"prefill": 0, "decode": 0, "verify": 0}
        self._prefill_jit = jax.jit(self._prefill_impl)
        self._decode_jit = jax.jit(self._decode_impl)
        self._verify_jit = jax.jit(self._verify_impl)

    # ------------------------------------------------------------ geometry
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket {self.buckets[-1]}"
        )

    # ------------------------------------------------------- jitted bodies
    def _prefill_impl(self, params, tokens, length, cache_k, cache_v, block_table, temp, top_k, key):
        s = tokens.shape[1]
        self.trace_counts[f"prefill[{s}]"] = self.trace_counts.get(f"prefill[{s}]", 0) + 1
        nb, bs = cache_k.shape[1], cache_k.shape[2]
        logits, ks, vs = prefill(params, tokens, jnp.full((1,), length, jnp.int32))
        positions = jnp.arange(s, dtype=jnp.int32)
        slots = slot_mapping(block_table, positions, bs)
        slots = jnp.where(positions < length, slots, 0)  # padding -> scratch

        def write(cache, layer_kv):
            flat = cache.reshape(nb * bs, *cache.shape[2:])
            return flat.at[slots].set(layer_kv.astype(flat.dtype)).reshape(cache.shape)

        cache_k = jax.vmap(write)(cache_k, ks[:, 0])
        cache_v = jax.vmap(write)(cache_v, vs[:, 0])
        last = logits[0, length - 1]
        token = _sample(last[None], temp[None], top_k[None], key[None])[0]
        return token, cache_k, cache_v

    def _decode_impl(
        self, params, tokens, positions, cache_k, cache_v, block_tables, context_lens, temps, top_ks, keys
    ):
        self.trace_counts["decode"] = self.trace_counts.get("decode", 0) + 1
        logits, cache_k, cache_v = decode_step(
            params, tokens, positions, cache_k, cache_v, block_tables,
            context_lens, backend=self.backend,
        )
        return _sample(logits, temps, top_ks, keys), cache_k, cache_v

    def _verify_impl(
        self, params, tokens, start, n_draft, cache_k, cache_v, block_tables, temps, top_ks, keys
    ):
        """Speculative verification: score a [B, W] window (committed
        token + drafts) in one forward and accept/emit in-jit.
        ``n_draft[b]`` counts the slot's real drafts (0..W-1); -1 marks
        an inactive slot (everything masked to scratch, 0 emitted)."""
        from .speculative.sampling import speculative_accept

        self.trace_counts["verify"] = self.trace_counts.get("verify", 0) + 1
        w = tokens.shape[1]
        offs = jnp.arange(w, dtype=jnp.int32)[None, :]
        # window token j sits at cache position start + j; slots past the
        # drafts (and whole inactive rows) are padding -> position -1
        positions = jnp.where(offs <= n_draft[:, None], start[:, None] + offs, -1)
        logits, cache_k, cache_v = verify_step(
            params, tokens, positions, cache_k, cache_v, block_tables,
            backend=self.backend,
        )
        out, n_emitted = speculative_accept(
            logits, tokens[:, 1:], jnp.maximum(n_draft, 0), temps, top_ks, keys
        )
        return out, jnp.where(n_draft >= 0, n_emitted, 0), cache_k, cache_v

    # ----------------------------------------------------------- host API
    def prefill_one(
        self,
        prompt: Sequence[int],
        block_table: Sequence[int],
        sampling: SamplingParams,
        key: jax.Array,
    ) -> int:
        """Prefill one sequence into its allocated blocks and sample its
        first generated token. ``block_table`` is the sequence's block
        ids (padded internally to the engine's fixed table width)."""
        faults.inject("generation.prefill", prompt)
        self.step_counts["prefill"] += 1
        n = len(prompt)
        bucket = self.bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[: len(block_table)] = block_table
        token, ck, cv = self._prefill_jit(
            self.params,
            jnp.asarray(tokens),
            jnp.int32(n),
            self.cache.k,
            self.cache.v,
            jnp.asarray(table),
            jnp.float32(sampling.temperature),
            jnp.int32(sampling.top_k),
            key,
        )
        self.cache.update(ck, cv)
        return int(token)

    def decode(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        block_tables: np.ndarray,
        active: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        keys: jax.Array,
    ) -> np.ndarray:
        """One decode step across all ``max_batch_slots`` slots. Arrays
        are slot-indexed; inactive slots (active[i] False) write to
        scratch and return garbage tokens the scheduler ignores."""
        faults.inject("generation.decode_step", tokens)
        self.step_counts["decode"] += 1
        context_lens = np.where(active, positions + 1, 0).astype(np.int32)
        safe_pos = np.where(active, positions, 0).astype(np.int32)
        out, ck, cv = self._decode_jit(
            self.params,
            jnp.asarray(np.where(active, tokens, 0).astype(np.int32)),
            jnp.asarray(safe_pos),
            self.cache.k,
            self.cache.v,
            jnp.asarray(block_tables.astype(np.int32)),
            jnp.asarray(context_lens),
            jnp.asarray(temps.astype(np.float32)),
            jnp.asarray(top_ks.astype(np.int32)),
            keys,
        )
        self.cache.update(ck, cv)
        return np.asarray(out)

    def verify(
        self,
        window_tokens: np.ndarray,
        start: np.ndarray,
        n_draft: np.ndarray,
        block_tables: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        keys: jax.Array,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One speculative verification step across all slots.

        ``window_tokens`` [B, spec_window]: per slot, the last committed
        token followed by its drafts (then padding); ``start`` [B]: the
        committed token's cache position (the slot's ``cached_len``);
        ``n_draft`` [B]: real drafts per slot, -1 for inactive slots;
        ``keys`` [B, spec_window]: per-emitted-count sampling keys.
        Returns (out_tokens [B, spec_window], n_emitted [B]) — the
        scheduler keeps ``out_tokens[i, :n_emitted[i]]`` (further
        truncated by EOS / budget). ONE fixed-shape jit: per-request
        adaptive k only changes ``n_draft`` values, never the shape.
        """
        faults.inject("generation.verify", window_tokens)
        self.step_counts["verify"] += 1
        out, n_emitted, ck, cv = self._verify_jit(
            self.params,
            jnp.asarray(window_tokens.astype(np.int32)),
            jnp.asarray(start.astype(np.int32)),
            jnp.asarray(n_draft.astype(np.int32)),
            self.cache.k,
            self.cache.v,
            jnp.asarray(block_tables.astype(np.int32)),
            jnp.asarray(temps.astype(np.float32)),
            jnp.asarray(top_ks.astype(np.int32)),
            keys,
        )
        self.cache.update(ck, cv)
        return np.asarray(out), np.asarray(n_emitted)

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        sampling: Optional[SamplingParams] = None,
        speculation=None,
        **scheduler_kwargs,
    ) -> List[List[int]]:
        """Convenience: run ``prompts`` through a private continuous-
        batching scheduler to completion; returns generated tokens per
        prompt (prompt excluded). ``speculation``: a SpeculationConfig
        to decode speculatively (exact — greedy output is identical)."""
        from .scheduler import ContinuousBatchingScheduler

        sampling = sampling or SamplingParams()
        sched = ContinuousBatchingScheduler(self, **scheduler_kwargs)
        handles = [sched.submit(list(p), sampling, speculation=speculation) for p in prompts]
        while any(not h.done() for h in handles):
            if not sched.step():
                break
        return [h.result(timeout=0) for h in handles]

    def recompiles(self) -> Dict[str, int]:
        """Retraces beyond the first compile, per program."""
        return {k: v - 1 for k, v in self.trace_counts.items() if v > 1}
