"""Generation engine: prefill/decode split over the block KV cache.

XLA has no dynamic shapes, so naive generation recompiles on every
prompt length and batch size. The engine compiles a FIXED family of
programs instead:

* **prefill** — one jitted program per *prompt-length bucket* (prompt
  padded up to the bucket; per-sequence length masking keeps logits
  identical to the unpadded forward). A handful of buckets covers every
  prompt, and a bucket compiles at most once.
* **decode** — ONE jitted program, period: always ``max_batch_slots``
  sequences (inactive slots masked to scratch block 0), always the same
  block-table width. Steady-state decode NEVER recompiles, whatever
  joins or leaves the batch — the property tools/genbench.py asserts.

``trace_counts`` counts actual retraces (the Python body only runs at
trace time), so tests and the bench can assert the compile behavior
instead of trusting it.

Sampling (greedy / temperature / top-k) runs inside the jitted steps —
per-slot parameters are arrays, so mixed sampling configs share one
program.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerConfig
from ..obs.capacity import ProgramRegistry, ServingFlops
from ..obs.truth import PredictionLedger
from ..runtime import faults
from .cache import BlockAllocator, CacheConfig, KVCache, slot_mapping
from .decoder import DecoderParams, decode_step, prefill, verify_step

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature <= 0`` means greedy (argmax); ``top_k <= 0`` disables
    the top-k filter. ``seed`` makes the request's sampling stream
    deterministic — preemption-by-recompute replays the same stream.
    """

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    seed: int = 0


def default_buckets(max_seq_len: int, start: int = 16) -> Tuple[int, ...]:
    """Doubling prompt-length buckets: start, 2*start, ... up to (and
    including) max_seq_len."""
    buckets: List[int] = []
    b = min(start, max_seq_len)
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq_len)
    return tuple(buckets)


def topk_scaled_logits(logits, temps, top_ks):
    """Temperature-scaled, top-k-masked logits — THE sampling transform
    for both the decode step and speculative verification (speculative/
    sampling.py imports this one; two copies drifting apart would break
    the zero-draft-verify ≡ decode bit-exactness contract).

    logits [..., V]; temps/top_ks shaped logits.shape[:-1] (callers
    broadcast). temp <= 0 rows are scaled by 1 (greedy callers argmax
    the RAW logits); top_k <= 0 disables the top-k filter.
    """
    v = logits.shape[-1]
    safe_t = jnp.where(temps <= 0.0, 1.0, temps)
    scaled = logits / safe_t[..., None]
    k = jnp.where(top_ks <= 0, v, jnp.clip(top_ks, 1, v)).astype(jnp.int32)
    sorted_desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    thresh = jnp.take_along_axis(sorted_desc, k[..., None] - 1, axis=-1)
    return jnp.where(scaled >= thresh, scaled, NEG_INF)


def _sample(logits, temps, top_ks, keys):
    """Vectorized sampling: greedy where temp<=0, else temperature +
    optional top-k. logits [B, V]; temps/top_ks [B]; keys [B] PRNG."""
    v = logits.shape[-1]
    greedy = temps <= 0.0
    masked = topk_scaled_logits(logits, temps, top_ks)
    gumbel = jax.vmap(lambda key: jax.random.gumbel(key, (v,)))(keys)
    sampled = jnp.argmax(masked + gumbel, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)


class GenerationEngine:
    """Owns the cache, the allocator, and the jitted step family. The
    continuous-batching scheduler drives it; ``generate`` is a
    convenience wrapper that spins up a private scheduler."""

    def __init__(
        self,
        params: DecoderParams,
        cfg: TransformerConfig,
        cache_config: Optional[CacheConfig] = None,
        *,
        cache_budget_bytes: Optional[int] = None,
        max_batch_slots: int = 4,
        prompt_buckets: Optional[Sequence[int]] = None,
        max_seq_len: Optional[int] = None,
        block_size: int = 16,
        max_spec_tokens: int = 4,
    ):
        self.params = params
        self.cfg = cfg
        self.max_seq_len = max_seq_len or cfg.seq_length
        self.max_batch_slots = max_batch_slots
        if cache_config is None:
            if cache_budget_bytes is not None:
                cache_config = CacheConfig.from_budget(
                    cache_budget_bytes,
                    num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    head_dim=cfg.hidden_size // cfg.num_heads,
                    block_size=block_size,
                )
            else:
                # enough for every slot to reach max_seq_len, plus scratch
                per_seq = -(-self.max_seq_len // block_size)
                cache_config = CacheConfig(
                    num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    head_dim=cfg.hidden_size // cfg.num_heads,
                    num_blocks=1 + per_seq * max_batch_slots,
                    block_size=block_size,
                )
        self.cache_config = cache_config
        self.cache = KVCache.create(cache_config)
        self.allocator = BlockAllocator(cache_config)
        self.max_blocks_per_seq = cache_config.blocks_for(self.max_seq_len)
        self.buckets = tuple(sorted(prompt_buckets or default_buckets(self.max_seq_len)))
        if self.buckets[-1] > self.max_seq_len:
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds max_seq_len {self.max_seq_len}"
            )
        if self.buckets[-1] < self.max_seq_len:
            # preemption-by-recompute re-prefills prompt + generated,
            # which can reach max_seq_len - 1: there must be a bucket
            # that holds it
            self.buckets = self.buckets + (self.max_seq_len,)
        if max_spec_tokens < 1:
            raise ValueError("max_spec_tokens must be >= 1")
        # speculative verification window: 1 committed token + up to
        # max_spec_tokens drafts, ONE fixed jit shape whatever per-
        # request adaptive k does
        self.max_spec_tokens = max_spec_tokens
        self.spec_window = max_spec_tokens + 1
        self.backend = jax.default_backend()
        # retrace counters: the Python body runs only when XLA traces, so
        # these count compiles, not calls (genbench's recompile guard)
        self.trace_counts: Dict[str, int] = {}
        # host-call counters: engine steps actually issued (genbench's
        # tokens-per-engine-step accounting)
        self.step_counts: Dict[str, int] = {"prefill": 0, "decode": 0, "verify": 0}
        # cumulative wall seconds inside each step kind's host API call
        # (dispatch + device + result sync) — the device_time_s gauge
        self.device_time_s: Dict[str, float] = {"prefill": 0.0, "decode": 0.0, "verify": 0.0}
        # serving FLOPs accounting (obs/capacity.py): model-shaped FLOPs
        # per step kind — true prompt lengths and live context only, so
        # MFU = flops / device_time_s / chip peak is padding-honest.
        # Recovery replay / bisection probes accrue in BOTH terms (they
        # are real device work); goodput_ratio is the client-useful view.
        # The chip comes from the detected device kind (the calibration
        # preset table), so MFU and the truth ledger's roofline
        # predictions use real peaks instead of the generic default.
        from ..search.calibration import chip_spec_for, detected_device_kind

        kind = detected_device_kind(self.backend)
        self.flops_model = ServingFlops.from_config(
            cfg, dtype=cache_config.dtype, chip=chip_spec_for(kind)
        )
        # drift alarms only where the roofline means something: on the
        # CPU backend the prediction models a chip that is not there
        # (dispatch overhead dominates, peaks are uncalibrated), so the
        # pairs still record — an operator can read the error — but a
        # permanently-wrong prediction must not spam the flight ring
        self._roofline_alarm = jax.default_backend() != "cpu"
        self.flops_by_kind: Dict[str, float] = {"prefill": 0.0, "decode": 0.0, "verify": 0.0}
        # jit program registry: every traced program's static signature,
        # trace count, and compile wall time; retraces carry blame
        # strings (GET /v2/debug/programs)
        self.programs = ProgramRegistry()
        # cost-model truth ledger (obs/truth.py): every steady-state
        # step pairs its roofline-predicted time (same derate constants
        # as the search cost model) with measured wall seconds; EWMA
        # drift alarms land on the flight ring and
        # GET /v2/debug/predictions serves the pairs. Compile calls are
        # excluded — their wall time is compile cost, stamped into the
        # program registry instead.
        self.ledger = PredictionLedger()
        # per-slot finiteness of the last step's logits (the supervisor's
        # NaN blame vector: a cheap in-jit isfinite reduce, so a poisoned
        # request is pinned to its slot without extra device calls);
        # scalar-shaped [1] after prefill_one
        self.last_finite = np.ones((max_batch_slots,), bool)
        # crash-recovery restarts (generation/recovery.py supervisor)
        self.resets = 0
        # the fault plan's NaN-poison carrier: outside chaos runs inject
        # returns this very object, so the steady-state decode path pays
        # one identity check instead of a fresh alloc + device transfer
        self._zero_bias = np.zeros((max_batch_slots,), np.float32)
        self._zero_bias_dev = jnp.zeros((max_batch_slots,), jnp.float32)
        self._prefill_jit = jax.jit(self._prefill_impl)
        self._decode_jit = jax.jit(self._decode_impl)
        self._verify_jit = jax.jit(self._verify_impl)

    # ------------------------------------------------------------ geometry
    def reset(self) -> None:
        """Tear down device-side generation state after a crash or a
        stalled step: rezero the KV cache and restore the allocator's
        free list. The compiled program family and trace counters
        survive (params are unchanged), so recovery costs no
        recompilation — the scheduler journal-replays every live stream
        into the fresh cache."""
        self.cache.reset()
        self.allocator.reset()
        self.last_finite = np.ones((self.max_batch_slots,), bool)
        self.resets += 1

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket {self.buckets[-1]}"
        )

    # ------------------------------------------------------- jitted bodies
    def _prefill_impl(self, params, tokens, length, cache_k, cache_v, block_table, temp, top_k, key):
        s = tokens.shape[1]
        self.trace_counts[f"prefill[{s}]"] = self.trace_counts.get(f"prefill[{s}]", 0) + 1
        self.programs.note_trace(f"prefill[{s}]", {
            "params": params, "tokens": tokens, "length": length,
            "cache_k": cache_k, "block_table": block_table,
            "temp": temp, "top_k": top_k, "key": key,
        })
        nb, bs = cache_k.shape[1], cache_k.shape[2]
        logits, ks, vs = prefill(params, tokens, jnp.full((1,), length, jnp.int32))
        positions = jnp.arange(s, dtype=jnp.int32)
        slots = slot_mapping(block_table, positions, bs)
        slots = jnp.where(positions < length, slots, 0)  # padding -> scratch

        def write(cache, layer_kv):
            flat = cache.reshape(nb * bs, *cache.shape[2:])
            return flat.at[slots].set(layer_kv.astype(flat.dtype)).reshape(cache.shape)

        cache_k = jax.vmap(write)(cache_k, ks[:, 0])
        cache_v = jax.vmap(write)(cache_v, vs[:, 0])
        last = logits[0, length - 1]
        ok = jnp.all(jnp.isfinite(last))  # blame: poisoned prompt
        token = _sample(last[None], temp[None], top_k[None], key[None])[0]
        return token, ok, cache_k, cache_v

    def _decode_impl(
        self, params, tokens, positions, cache_k, cache_v, block_tables, context_lens, temps, top_ks, bias, keys
    ):
        self.trace_counts["decode"] = self.trace_counts.get("decode", 0) + 1
        self.programs.note_trace("decode", {
            "params": params, "tokens": tokens, "positions": positions,
            "cache_k": cache_k, "block_tables": block_tables,
            "context_lens": context_lens, "temps": temps, "top_ks": top_ks,
            "bias": bias, "keys": keys,
        })
        logits, cache_k, cache_v = decode_step(
            params, tokens, positions, cache_k, cache_v, block_tables,
            context_lens, backend=self.backend,
        )
        # bias is the fault plan's per-slot NaN poison (zeros outside
        # chaos runs); applying it before the finiteness reduce makes the
        # injected poison indistinguishable from model-produced NaN/inf
        logits = logits + bias[:, None]
        ok = jnp.all(jnp.isfinite(logits), axis=-1)
        return _sample(logits, temps, top_ks, keys), ok, cache_k, cache_v

    def _verify_impl(
        self, params, tokens, start, n_draft, cache_k, cache_v, block_tables, temps, top_ks, bias, keys
    ):
        """Speculative verification: score a [B, W] window (committed
        token + drafts) in one forward and accept/emit in-jit.
        ``n_draft[b]`` counts the slot's real drafts (0..W-1); -1 marks
        an inactive slot (everything masked to scratch, 0 emitted)."""
        from .speculative.sampling import speculative_accept

        self.trace_counts["verify"] = self.trace_counts.get("verify", 0) + 1
        self.programs.note_trace("verify", {
            "params": params, "tokens": tokens, "start": start,
            "n_draft": n_draft, "cache_k": cache_k,
            "block_tables": block_tables, "temps": temps, "top_ks": top_ks,
            "bias": bias, "keys": keys,
        })
        w = tokens.shape[1]
        offs = jnp.arange(w, dtype=jnp.int32)[None, :]
        # window token j sits at cache position start + j; slots past the
        # drafts (and whole inactive rows) are padding -> position -1
        positions = jnp.where(offs <= n_draft[:, None], start[:, None] + offs, -1)
        logits, cache_k, cache_v = verify_step(
            params, tokens, positions, cache_k, cache_v, block_tables,
            backend=self.backend,
        )
        logits = logits + bias[:, None, None]
        # blame vector: finiteness over each slot's REAL window positions
        # only — padded positions (and whole inactive rows) attend to
        # nothing and may hold garbage that must not indict the request
        valid = offs <= jnp.maximum(n_draft, 0)[:, None]
        ok = jnp.all(
            jnp.where(valid[:, :, None], jnp.isfinite(logits), True), axis=(1, 2)
        )
        out, n_emitted = speculative_accept(
            logits, tokens[:, 1:], jnp.maximum(n_draft, 0), temps, top_ks, keys
        )
        return out, jnp.where(n_draft >= 0, n_emitted, 0), ok, cache_k, cache_v

    # ----------------------------------------------------------- host API
    def prefill_one(
        self,
        prompt: Sequence[int],
        block_table: Sequence[int],
        sampling: SamplingParams,
        key: jax.Array,
    ) -> int:
        """Prefill one sequence into its allocated blocks and sample its
        first generated token. ``block_table`` is the sequence's block
        ids (padded internally to the engine's fixed table width)."""
        faults.inject(faults.GENERATION_PREFILL, prompt)
        self.step_counts["prefill"] += 1
        t0 = time.perf_counter()
        n = len(prompt)
        bucket = self.bucket_for(n)
        traces_before = self.trace_counts.get(f"prefill[{bucket}]", 0)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[: len(block_table)] = block_table
        token, ok, ck, cv = self._prefill_jit(
            self.params,
            jnp.asarray(tokens),
            jnp.int32(n),
            self.cache.k,
            self.cache.v,
            jnp.asarray(table),
            jnp.float32(sampling.temperature),
            jnp.int32(sampling.top_k),
            key,
        )
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok).reshape(1)
        out = int(token)  # forces the result sync before the clock stops
        elapsed = time.perf_counter() - t0
        # FLOPs accrue only on SUCCESS, next to the time they pair with:
        # a step that raises (and is retried by the supervisor) must not
        # count its FLOPs without its time, or MFU inflates under faults
        flops = self.flops_model.prefill_flops(n)
        self.flops_by_kind["prefill"] += flops
        self.device_time_s["prefill"] += elapsed
        if self.trace_counts.get(f"prefill[{bucket}]", 0) > traces_before:
            # this call traced (first compile or a retrace): its wall
            # time is the program's compile cost, registry-stamped
            self.programs.set_compile_time(f"prefill[{bucket}]", elapsed)
        else:
            # ledger prediction covers EXECUTED work — the program
            # computes the full padded bucket, so predicting from the
            # true prompt length would alarm on every short prompt in a
            # wide bucket. MFU above stays useful-work-only.
            self.ledger.observe(
                f"prefill[{bucket}]",
                self.flops_model.roofline_s(
                    self.flops_model.prefill_flops(bucket),
                    self.flops_model.prefill_bytes(bucket),
                ),
                elapsed,
                label=f"prefill[{bucket}] ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
        return out

    def decode(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        block_tables: np.ndarray,
        active: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        keys: jax.Array,
    ) -> np.ndarray:
        """One decode step across all ``max_batch_slots`` slots. Arrays
        are slot-indexed; inactive slots (active[i] False) write to
        scratch and return garbage tokens the scheduler ignores. After
        the call ``last_finite[i]`` says whether slot i's logits were
        finite — the supervisor's per-slot NaN blame vector."""
        masked = np.where(active, tokens, 0).astype(np.int32)
        masked, bias = faults.inject(faults.GENERATION_DECODE_STEP, (masked, self._zero_bias))
        self.step_counts["decode"] += 1
        t0 = time.perf_counter()
        traces_before = self.trace_counts.get("decode", 0)
        context_lens = np.where(active, positions + 1, 0).astype(np.int32)
        safe_pos = np.where(active, positions, 0).astype(np.int32)
        # scratch-mask inactive slots' tables too: an inactive slot with
        # a REAL table (a bisection probe deactivating a live slot)
        # would otherwise write its position-0 K/V into that slot's
        # first real block and silently corrupt the surviving stream
        tables = np.where(active[:, None], block_tables, 0).astype(np.int32)
        out, ok, ck, cv = self._decode_jit(
            self.params,
            jnp.asarray(masked),
            jnp.asarray(safe_pos),
            self.cache.k,
            self.cache.v,
            jnp.asarray(tables),
            jnp.asarray(context_lens),
            jnp.asarray(temps.astype(np.float32)),
            jnp.asarray(top_ks.astype(np.int32)),
            self._bias_arg(bias),
            keys,
        )
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok)
        result = np.asarray(out)  # result sync included in the timing
        elapsed = time.perf_counter() - t0
        # success-only, paired with the time below (see prefill())
        n_active, ctx_sum = int(active.sum()), int(context_lens.sum())
        flops = self.flops_model.decode_flops(n_active, ctx_sum)
        self.flops_by_kind["decode"] += flops
        self.device_time_s["decode"] += elapsed
        if self.trace_counts.get("decode", 0) > traces_before:
            self.programs.set_compile_time("decode", elapsed)
        else:
            # EXECUTED work: the fixed-shape program runs every batch
            # slot's projections/FFN (inactive rows masked to scratch,
            # but computed); only attention context is truly live-only
            b = self.max_batch_slots
            self.ledger.observe(
                "decode",
                self.flops_model.roofline_s(
                    self.flops_model.decode_flops(b, ctx_sum),
                    self.flops_model.decode_bytes(b, ctx_sum),
                ),
                elapsed,
                label=f"decode ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
        return result

    def _bias_arg(self, bias) -> jax.Array:
        """Device-side logit bias: the cached zeros unless a fault plan
        actually poisoned this call."""
        if bias is self._zero_bias:
            return self._zero_bias_dev
        return jnp.asarray(np.asarray(bias, np.float32))

    def verify(
        self,
        window_tokens: np.ndarray,
        start: np.ndarray,
        n_draft: np.ndarray,
        block_tables: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        keys: jax.Array,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One speculative verification step across all slots.

        ``window_tokens`` [B, spec_window]: per slot, the last committed
        token followed by its drafts (then padding); ``start`` [B]: the
        committed token's cache position (the slot's ``cached_len``);
        ``n_draft`` [B]: real drafts per slot, -1 for inactive slots;
        ``keys`` [B, spec_window]: per-emitted-count sampling keys.
        Returns (out_tokens [B, spec_window], n_emitted [B]) — the
        scheduler keeps ``out_tokens[i, :n_emitted[i]]`` (further
        truncated by EOS / budget). ONE fixed-shape jit: per-request
        adaptive k only changes ``n_draft`` values, never the shape.
        """
        window = window_tokens.astype(np.int32)
        window, bias = faults.inject(faults.GENERATION_VERIFY, (window, self._zero_bias))
        self.step_counts["verify"] += 1
        # useful verify work: per live slot, n_draft+1 window tokens;
        # window token j at position start+j attends to start+j+1 live
        # context positions -> (nd+1)(start+1) + nd(nd+1)/2. Computed
        # BEFORE the clock starts: device_time_s is wall seconds inside
        # the step's host API call only, same as prefill/decode
        nd = np.maximum(n_draft, 0).astype(np.int64)
        live = n_draft >= 0
        w_tok = np.where(live, nd + 1, 0)
        ctx = np.where(live, w_tok * (start.astype(np.int64) + 1) + nd * (nd + 1) // 2, 0)
        t0 = time.perf_counter()
        traces_before = self.trace_counts.get("verify", 0)
        out, n_emitted, ok, ck, cv = self._verify_jit(
            self.params,
            jnp.asarray(window),
            jnp.asarray(start.astype(np.int32)),
            jnp.asarray(n_draft.astype(np.int32)),
            self.cache.k,
            self.cache.v,
            jnp.asarray(block_tables.astype(np.int32)),
            jnp.asarray(temps.astype(np.float32)),
            jnp.asarray(top_ks.astype(np.int32)),
            self._bias_arg(bias),
            keys,
        )
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok)
        result = (np.asarray(out), np.asarray(n_emitted))
        elapsed = time.perf_counter() - t0
        # success-only, paired with the time below (see prefill())
        n_tok, ctx_sum = int(w_tok.sum()), int(ctx.sum())
        flops = self.flops_model.verify_flops(n_tok, ctx_sum)
        self.flops_by_kind["verify"] += flops
        self.device_time_s["verify"] += elapsed
        if self.trace_counts.get("verify", 0) > traces_before:
            self.programs.set_compile_time("verify", elapsed)
        else:
            # EXECUTED work: all B x W window positions compute (see
            # decode) — padding only skips attention context
            bw = self.max_batch_slots * self.spec_window
            self.ledger.observe(
                "verify",
                self.flops_model.roofline_s(
                    self.flops_model.verify_flops(bw, ctx_sum),
                    self.flops_model.verify_bytes(bw, ctx_sum),
                ),
                elapsed,
                label=f"verify ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
        return result

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        sampling: Optional[SamplingParams] = None,
        speculation=None,
        **scheduler_kwargs,
    ) -> List[List[int]]:
        """Convenience: run ``prompts`` through a private continuous-
        batching scheduler to completion; returns generated tokens per
        prompt (prompt excluded). ``speculation``: a SpeculationConfig
        to decode speculatively (exact — greedy output is identical)."""
        from .scheduler import ContinuousBatchingScheduler

        sampling = sampling or SamplingParams()
        sched = ContinuousBatchingScheduler(self, **scheduler_kwargs)
        handles = [sched.submit(list(p), sampling, speculation=speculation) for p in prompts]
        while any(not h.done() for h in handles):
            if not sched.step():
                break
        return [h.result(timeout=0) for h in handles]

    def recompiles(self) -> Dict[str, int]:
        """Retraces beyond the first compile, per program."""
        return {k: v - 1 for k, v in self.trace_counts.items() if v > 1}

    def total_flops(self) -> float:
        """Cumulative useful model FLOPs across all step kinds."""
        return sum(self.flops_by_kind.values())

    def total_device_time_s(self) -> float:
        return sum(self.device_time_s.values())

    def mfu(self) -> float:
        """Serving model-FLOPs utilization: useful FLOPs over device
        seconds against the chip's peak for the cache dtype. 0 before
        any step ran."""
        t = self.total_device_time_s()
        if t <= 0:
            return 0.0
        return self.total_flops() / t / self.flops_model.peak_flops
