"""Generation engine: prefill/decode split over the block KV cache.

XLA has no dynamic shapes, so naive generation recompiles on every
prompt length and batch size. The engine compiles a FIXED family of
programs instead:

* **prefill** — one jitted program per *prompt-length bucket* (prompt
  padded up to the bucket; per-sequence length masking keeps logits
  identical to the unpadded forward). A handful of buckets covers every
  prompt, and a bucket compiles at most once.
* **decode** — ONE jitted program, period: always ``max_batch_slots``
  sequences (inactive slots masked to scratch block 0), always the same
  block-table width. Steady-state decode NEVER recompiles, whatever
  joins or leaves the batch — the property tools/genbench.py asserts.

``trace_counts`` counts actual retraces (the Python body only runs at
trace time), so tests and the bench can assert the compile behavior
instead of trusting it.

Sampling (greedy / temperature / top-k) runs inside the jitted steps —
per-slot parameters are arrays, so mixed sampling configs share one
program — and since ISSUE 13 the per-slot PRNG keys ALSO derive in-jit
from (seed, generated-token count), bit-identical to the old host
fold_in, so the hot loop assembles no keys at all.

ISSUE 13's overlap support: :meth:`GenerationEngine.decode_async` /
:meth:`GenerationEngine.consume_decode` split one decode step into a
non-blocking dispatch (token array carried device-resident from the
previous step, async host copy of the results started at
dispatch-return) and a later consume — the scheduler's two-deep
pipeline. Slot-constant args stage device-resident (``_stage``), and
``donate_cache`` aliases the decode/verify jits' KV-cache inputs to
their outputs (in-place update; auto on accelerators).

ISSUE 15 made the engine MESH-NATIVE: pass ``tp_degree`` /
``mesh_devices`` / ``mesh`` and the decoder weights + KV cache shard
along the head axis over a ``"model"`` mesh axis
(generation/sharding.py), every jit is built with explicit
out-shardings, every non-sharded input commits replicated through one
staging path (call-stable input shardings — the retrace contract), and
the serving TP degree is chosen by the Unity-style search + cost model
(search/serving_strategy.py). No mesh arguments -> the legacy
single-device paths, untouched; a 1-device mesh is bit-for-bit the
legacy engine.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerConfig
from ..obs.capacity import ProgramRegistry, ServingFlops
from ..obs.truth import PredictionLedger
from ..runtime import faults
from .cache import BlockAllocator, CacheConfig, KVCache, slot_mapping
from .decoder import DecoderParams, decode_step, prefill, verify_step
from .prefix import KVHandoffPayload, PackedBlock, PrefixCache, PrefixEntry
from .sharding import ServingLayout

NEG_INF = -1e30


@dataclasses.dataclass
class PrefixPlan:
    """Admission-time reuse decision for one prompt (engine.prefix_plan):
    the cached entries to share, the boundary entry to COW-copy when the
    prompt is fully covered (its last position must still be recomputed
    for logits, and that write lands inside the last matched block), the
    token count reuse covers, and how many shared entries are already
    device-resident (the rest swap in from the host tier)."""

    entries: List[PrefixEntry]
    cow: Optional[PrefixEntry]
    reuse_tokens: int
    n_resident: int


EMPTY_PREFIX_PLAN = PrefixPlan([], None, 0, 0)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature <= 0`` means greedy (argmax); ``top_k <= 0`` disables
    the top-k filter. ``seed`` makes the request's sampling stream
    deterministic — preemption-by-recompute replays the same stream.
    Seeds are folded as 32-bit values everywhere (the decode/verify
    jits derive keys in-jit from a uint32 seed): values outside
    [0, 2**32) truncate, consistently across prefill/decode/replay.
    """

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    seed: int = 0


def default_buckets(max_seq_len: int, start: int = 16) -> Tuple[int, ...]:
    """Doubling prompt-length buckets: start, 2*start, ... up to (and
    including) max_seq_len."""
    buckets: List[int] = []
    b = min(start, max_seq_len)
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq_len)
    return tuple(buckets)


def topk_scaled_logits(logits, temps, top_ks):
    """Temperature-scaled, top-k-masked logits — THE sampling transform
    for both the decode step and speculative verification (speculative/
    sampling.py imports this one; two copies drifting apart would break
    the zero-draft-verify ≡ decode bit-exactness contract).

    logits [..., V]; temps/top_ks shaped logits.shape[:-1] (callers
    broadcast). temp <= 0 rows are scaled by 1 (greedy callers argmax
    the RAW logits); top_k <= 0 disables the top-k filter.
    """
    v = logits.shape[-1]
    safe_t = jnp.where(temps <= 0.0, 1.0, temps)
    scaled = logits / safe_t[..., None]
    k = jnp.where(top_ks <= 0, v, jnp.clip(top_ks, 1, v)).astype(jnp.int32)
    sorted_desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    thresh = jnp.take_along_axis(sorted_desc, k[..., None] - 1, axis=-1)
    return jnp.where(scaled >= thresh, scaled, NEG_INF)


def _sample(logits, temps, top_ks, keys):
    """Vectorized sampling: greedy where temp<=0, else temperature +
    optional top-k. logits [B, V]; temps/top_ks [B]; keys [B] PRNG."""
    v = logits.shape[-1]
    greedy = temps <= 0.0
    masked = topk_scaled_logits(logits, temps, top_ks)
    gumbel = jax.vmap(lambda key: jax.random.gumbel(key, (v,)))(keys)
    sampled = jnp.argmax(masked + gumbel, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)


def derive_keys(seeds, counts):
    """Per-slot sampling keys derived IN-JIT from (seed, generated-token
    count): ``fold_in(key(seed), count)`` — bit-identical to the host
    derivation the scheduler used before ISSUE 13 (``Request.base_key``
    + ``fold_in`` by count), so seeded streams are unchanged while the
    host ``sample`` phase (per-request fold_in + stack, a real host
    dispatch per step) disappears from the critical path. Seeds are
    folded as 32-bit values; seeds >= 2**32 truncate."""
    return jax.vmap(lambda s, c: jax.random.fold_in(jax.random.key(s), c))(
        seeds, counts
    )


def derive_window_keys(seeds, counts, window: int):
    """[B, window] keys for a speculative window: key j of slot b is
    ``fold_in(key(seeds[b]), counts[b] + j)`` — the same per-emitted-
    count indexing the host-side ``Request.sample_keys`` used."""
    offs = jnp.arange(window, dtype=jnp.int32)
    return jax.vmap(
        lambda s, c: jax.vmap(
            lambda j: jax.random.fold_in(jax.random.key(s), c + j)
        )(offs)
    )(seeds, counts)


class InFlightDecode:
    """One dispatched-but-unconsumed decode step (the overlap pipeline's
    frontier unit). Holds the device result refs, the async host copies
    started at dispatch-return (double-buffered readback), the pre-step
    cache refs for rollback on failure (None when the jit donates its
    cache buffers — a failed donated step is only recoverable by
    ``engine.reset()`` + journal replay), and the dispatch timestamps
    the step-anatomy profiler renders. Created by
    :meth:`GenerationEngine.decode_async`, consumed exactly once by
    :meth:`GenerationEngine.consume_decode`. Loop-thread only."""

    __slots__ = (
        "out", "ok", "prev_k", "prev_v", "ck", "cv", "t0", "t_disp",
        "t_started", "traced", "n_active", "ctx_sum", "consumed",
    )

    def __init__(self, out, ok, prev_k, prev_v, ck, cv, t0, t_disp, traced, n_active, ctx_sum):
        self.out = out
        self.ok = ok
        self.prev_k = prev_k
        self.prev_v = prev_v
        # this step's cache outputs: rollback applies only while these
        # are still the engine's current refs (a failed chain is rolled
        # back once, to the OLDEST intact refs, never forward again)
        self.ck = ck
        self.cv = cv
        self.t0 = t0
        self.t_disp = t_disp
        # restamped by the scheduler when the PREVIOUS in-flight step
        # completes: with a one-deep pipeline this step only starts
        # executing then, so the execute span (and the watchdog's view
        # of its age) is measured from here, not from dispatch
        self.t_started = t_disp
        self.traced = traced
        self.n_active = n_active
        self.ctx_sum = ctx_sum
        self.consumed = False


class GenerationEngine:
    """Owns the cache, the allocator, and the jitted step family. The
    continuous-batching scheduler drives it; ``generate`` is a
    convenience wrapper that spins up a private scheduler."""

    def __init__(
        self,
        params: DecoderParams,
        cfg: TransformerConfig,
        cache_config: Optional[CacheConfig] = None,
        *,
        cache_budget_bytes: Optional[int] = None,
        max_batch_slots: int = 4,
        prompt_buckets: Optional[Sequence[int]] = None,
        max_seq_len: Optional[int] = None,
        block_size: int = 16,
        max_spec_tokens: int = 4,
        prefix_cache: bool = True,
        host_cache_bytes: Optional[int] = None,
        donate_cache: Optional[bool] = None,
        mesh=None,
        tp_degree: Optional[int] = None,
        mesh_devices: Optional[int] = None,
        expected_prefix_sharing: float = 0.0,
    ):
        self.cfg = cfg
        self.max_seq_len = max_seq_len or cfg.seq_length
        self.max_batch_slots = max_batch_slots
        # ------------------------------------------------- serving mesh
        # Mesh-native engine (ISSUE 15): decoder weights and the KV
        # cache shard along the head axis over a "model" mesh axis
        # (generation/sharding.py). Three ways in:
        #   mesh=          an explicit Mesh carrying a "model" axis
        #   tp_degree=N    a pinned degree (serving_mesh over N devices)
        #   mesh_devices=N devices to serve on; the TP degree is CHOSEN
        #                  by the existing Unity-style search + cost
        #                  model (search/serving_strategy.py)
        # All None -> the legacy single-device engine, untouched paths.
        # A 1-device mesh is bit-for-bit the legacy engine — the
        # exactness anchor the multi-device gates compare against.
        self.layout: Optional[ServingLayout] = None
        self.serving_strategy = None
        if mesh is not None or tp_degree is not None or mesh_devices is not None:
            from ..search.serving_strategy import choose_serving_strategy

            if mesh is not None:
                from ..parallel.mesh import MODEL_AXIS

                tp = int(mesh.shape.get(MODEL_AXIS, 1))
                self.layout = ServingLayout.build(cfg.num_heads, tp, mesh=mesh)
            else:
                n_dev = mesh_devices or tp_degree
            self.serving_strategy = choose_serving_strategy(
                cfg,
                mesh_devices=(
                    self.layout.mesh.size if self.layout is not None else n_dev
                ),
                max_batch_slots=max_batch_slots,
                prefill_len=self.max_seq_len,
                pinned_tp=(
                    self.layout.tp_degree if self.layout is not None
                    else tp_degree
                ),
            )
            if self.layout is None:
                self.layout = ServingLayout.build(
                    cfg.num_heads, self.serving_strategy.tp_degree
                )
        self.tp_degree = self.layout.tp_degree if self.layout else 1
        self.mesh_devices = self.layout.mesh.size if self.layout else 1
        self.params = (
            self.layout.shard_params(params) if self.layout else params
        )
        if cache_config is None:
            if cache_budget_bytes is not None:
                # per-device HBM budget: the head-sharded cache holds
                # H/tp heads of every block per chip, so the same chip
                # budget buys tp x the blocks (ISSUE 15 satellite)
                cache_config = CacheConfig.from_budget(
                    cache_budget_bytes,
                    num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    head_dim=cfg.hidden_size // cfg.num_heads,
                    block_size=block_size,
                    kv_shards=self.tp_degree,
                )
            else:
                # enough for every slot to reach max_seq_len (discounted
                # by expected prefix sharing), plus scratch
                cache_config = CacheConfig.for_slots(
                    num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    head_dim=cfg.hidden_size // cfg.num_heads,
                    max_seq_len=self.max_seq_len,
                    max_batch_slots=max_batch_slots,
                    block_size=block_size,
                    expected_prefix_sharing=expected_prefix_sharing,
                )
        self.cache_config = cache_config
        self.cache = KVCache.create(
            cache_config,
            sharding=self.layout.cache_sharding if self.layout else None,
        )
        self.allocator = BlockAllocator(cache_config)
        self.max_blocks_per_seq = cache_config.blocks_for(self.max_seq_len)
        self.buckets = tuple(sorted(prompt_buckets or default_buckets(self.max_seq_len)))
        if self.buckets[-1] > self.max_seq_len:
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds max_seq_len {self.max_seq_len}"
            )
        if self.buckets[-1] < self.max_seq_len:
            # preemption-by-recompute re-prefills prompt + generated,
            # which can reach max_seq_len - 1: there must be a bucket
            # that holds it
            self.buckets = self.buckets + (self.max_seq_len,)
        if max_spec_tokens < 1:
            raise ValueError("max_spec_tokens must be >= 1")
        # speculative verification window: 1 committed token + up to
        # max_spec_tokens drafts, ONE fixed jit shape whatever per-
        # request adaptive k does
        self.max_spec_tokens = max_spec_tokens
        self.spec_window = max_spec_tokens + 1
        self.backend = jax.default_backend()
        # the mesh handed to the Pallas kernel dispatch (ISSUE 15): on
        # TPU backends a tp>1 engine routes decode/append attention
        # through the head-sharded shard_map kernel path; elsewhere the
        # plain-XLA reference composition is partitioned by GSPMD and
        # needs no manual mesh
        self._kernel_mesh = (
            self.layout.mesh
            if self.layout is not None
            and self.tp_degree > 1
            and self.backend in ("tpu", "axon")
            else None
        )
        # retrace counters: the Python body runs only when XLA traces, so
        # these count compiles, not calls (genbench's recompile guard)
        self.trace_counts: Dict[str, int] = {}
        # host-call counters: engine steps actually issued (genbench's
        # tokens-per-engine-step accounting)
        self.step_counts: Dict[str, int] = {"prefill": 0, "decode": 0, "verify": 0}
        # per-kind step-phase seconds (the device_time_s split, ISSUE
        # 12): dispatch = host arg prep + XLA dispatch (jit call entry
        # to return), execute = dispatch-return to block_until_ready
        # completion (actual device compute under async dispatch),
        # readback = device->host result sync + numpy conversion. The
        # old device_time_s total survives as a derived property so the
        # flight/stats consumers keep their series; MFU divides by
        # execute-only seconds (obs/capacity.py convention change,
        # documented in README "Step anatomy").
        self.phase_time_s: Dict[str, Dict[str, float]] = {
            k: {"dispatch": 0.0, "execute": 0.0, "readback": 0.0}
            for k in ("prefill", "decode", "verify")
        }
        # spans of the most recent engine step (obs/steptrace.py):
        # (phase, t0, t1) perf_counter stamps, overwritten per call —
        # read by the scheduler loop thread that made the call, never
        # concurrently
        self.last_step_spans: List[Tuple[str, float, float]] = []
        # serving FLOPs accounting (obs/capacity.py): model-shaped FLOPs
        # per step kind — true prompt lengths and live context only, so
        # MFU = flops / execute seconds / chip peak is padding-honest.
        # Recovery replay / bisection probes accrue in BOTH terms (they
        # are real device work); goodput_ratio is the client-useful view.
        # The chip comes from the detected device kind (the calibration
        # preset table), so MFU and the truth ledger's roofline
        # predictions use real peaks instead of the generic default.
        from ..search.calibration import (
            chip_spec_for,
            detected_device_kind,
            mesh_device_kind,
        )

        # mesh geometry in the chip kind ("TPU v5e x4"): the aggregate
        # spec scales peaks by the shard count, so a multi-chip engine's
        # MFU divides by the MESH's peak FLOPs — against one chip's peak
        # a 4-way engine would report >100% MFU (ISSUE 15 satellite)
        kind = mesh_device_kind(
            detected_device_kind(self.backend), self.tp_degree
        )
        self.flops_model = ServingFlops.from_config(
            cfg, dtype=cache_config.dtype, chip=chip_spec_for(kind)
        )
        # drift alarms only where the roofline means something: on the
        # CPU backend the prediction models a chip that is not there
        # (dispatch overhead dominates, peaks are uncalibrated), so the
        # pairs still record — an operator can read the error — but a
        # permanently-wrong prediction must not spam the flight ring
        self._roofline_alarm = jax.default_backend() != "cpu"
        self.flops_by_kind: Dict[str, float] = {"prefill": 0.0, "decode": 0.0, "verify": 0.0}
        # jit program registry: every traced program's static signature,
        # trace count, and compile wall time; retraces carry blame
        # strings (GET /v2/debug/programs)
        self.programs = ProgramRegistry()
        # cost-model truth ledger (obs/truth.py): every steady-state
        # step pairs its roofline-predicted time (same derate constants
        # as the search cost model) with measured wall seconds; EWMA
        # drift alarms land on the flight ring and
        # GET /v2/debug/predictions serves the pairs. Compile calls are
        # excluded — their wall time is compile cost, stamped into the
        # program registry instead.
        self.ledger = PredictionLedger()
        # per-slot finiteness of the last step's logits (the supervisor's
        # NaN blame vector: a cheap in-jit isfinite reduce, so a poisoned
        # request is pinned to its slot without extra device calls);
        # scalar-shaped [1] after prefill_one
        self.last_finite = np.ones((max_batch_slots,), bool)
        # crash-recovery restarts (generation/recovery.py supervisor)
        self.resets = 0
        # the fault plan's NaN-poison carrier: outside chaos runs inject
        # returns this very object, so the steady-state decode path pays
        # one identity check instead of a fresh alloc + device transfer
        self._zero_bias = np.zeros((max_batch_slots,), np.float32)
        self._zero_bias_dev = self._dev(np.zeros((max_batch_slots,), np.float32))
        # grammar-mask staging (ISSUE 18): per-shape cached zeros for
        # batches with no constrained slot — see _mask_arg
        self._zero_masks: Dict[str, jax.Array] = {}
        # KV-cache buffer donation on the hot fixed-shape programs: the
        # decode/verify jits alias their cache inputs to their cache
        # outputs, so XLA updates the (large) cache in place instead of
        # copying it every step. Auto: on for accelerator backends, off
        # on CPU — donation consumes the input buffers, which makes a
        # FAILED step unrecoverable by retry/bisection (the supervisor
        # then goes straight to reset + journal replay, which is
        # byte-exact); the CPU chaos suites exercise the retry/bisect
        # paths and keep them.
        self.donate = bool(
            donate_cache if donate_cache is not None
            else jax.default_backend() != "cpu"
        )
        # device-resident staging for slot-constant decode/verify args
        # (block tables, sampling params): re-uploaded only when the
        # host-side contents change, not rebuilt via jnp.asarray every
        # step. Keyed by arg name; each entry is (host snapshot, device
        # array). Loop-thread only (like the cache refs).
        self._staged: Dict[str, Tuple[np.ndarray, jax.Array]] = {}
        # sharded jits with EXPLICIT out-shardings (ISSUE 15): cache
        # outputs stay head-sharded across steps (no resharding between
        # chained fixed-shape programs), tokens/ok/emit counts come back
        # replicated so the host bookkeeping reads one copy. On the
        # legacy (no-mesh) path the jits are built exactly as before.
        dec_donate = (3, 4) if self.donate else ()  # cache_k, cache_v
        ver_donate = (4, 5) if self.donate else ()
        if self.layout is None:
            sharded = {}
            dec_sh = ver_sh = {}
        else:
            repl = self.layout.replicated
            csh = self.layout.cache_sharding
            sharded = {"out_shardings": (repl, repl, csh, csh)}
            dec_sh = dict(sharded)
            ver_sh = {"out_shardings": (repl, repl, repl, csh, csh)}
        self._prefill_jit = jax.jit(self._prefill_impl, **sharded)
        self._decode_jit = jax.jit(
            self._decode_impl, donate_argnums=dec_donate, **dec_sh
        )
        self._verify_jit = jax.jit(
            self._verify_impl, donate_argnums=ver_donate, **ver_sh
        )
        # cross-request prefix caching (generation/prefix.py): radix
        # index + refcounted COW blocks + host-RAM offload tier. The
        # block-level device programs below are admission-time only
        # (suffix prefill per bucket, one copy/read/write each) — the
        # steady-state decode/verify programs are untouched.
        self.prefix_cache = PrefixCache(
            self.allocator, cache_config,
            enabled=prefix_cache, host_budget_bytes=host_cache_bytes,
        )
        if self.layout is None:
            blk_sh = rd_sh = {}
        else:
            # block-level programs over the sharded cache: COW copies and
            # swap-in writes keep the cache sharding; a swap-out read
            # gathers the full block to the host tier (replicated out)
            blk_sh = {"out_shardings": (csh, csh)}
            rd_sh = {"out_shardings": (repl, repl)}
        self._prefix_prefill_jit = jax.jit(self._prefix_prefill_impl, **sharded)
        self._copy_block_jit = jax.jit(self._copy_block_impl, **blk_sh)
        self._read_block_jit = jax.jit(self._read_block_impl, **rd_sh)
        self._write_block_jit = jax.jit(self._write_block_impl, **blk_sh)
        # batched handoff-wire programs (one dispatch per payload, not
        # per block): padded to max_blocks_per_seq so ONE fixed-shape
        # program serves every prompt length
        self._read_blocks_jit = jax.jit(self._read_blocks_impl, **rd_sh)
        self._write_blocks_jit = jax.jit(self._write_blocks_impl, **blk_sh)
        self._register_strategy_predictions()

    def _dev(self, x) -> jax.Array:
        """Commit a host array onto the engine's devices. Mesh-native
        engines pin every non-sharded jit input replicated on the mesh
        (call-stable input shardings — a drifting placement would
        recompile the fixed-shape programs); the legacy engine keeps the
        plain uncommitted ``jnp.asarray``."""
        if self.layout is not None:
            return self.layout.put_replicated(x)
        return jnp.asarray(x)

    def _register_strategy_predictions(self) -> None:
        """Put the chosen serving layout's predicted step times into the
        truth ledger (keys ``serving_strategy:prefill`` / ``:decode``)
        so drift telemetry covers the layout DECISION, not just the
        per-step roofline: the engine's measured execute seconds pair
        against the search's estimate on GET /v2/debug/predictions.
        ``alarm=False`` — the strategy simulator is an analytic ranking
        device (fwd cost of a training-shaped graph), expected to miss
        absolute wall seconds; the pairs are for operators, the CHOICE
        is what they grade."""
        ch = self.serving_strategy
        if ch is None:
            return
        prov = (
            f"predict_strategy_time over TP candidates "
            f"{[c['tp_degree'] for c in ch.candidates]} on "
            f"{ch.device_kind}"
        )
        self.ledger.predict(
            "serving_strategy:prefill", ch.prefill_s,
            label=f"serving layout tp={ch.tp_degree} (prefill)",
            provenance=prov, alarm=False,
        )
        self.ledger.predict(
            "serving_strategy:decode", ch.decode_s,
            label=f"serving layout tp={ch.tp_degree} (decode)",
            provenance=prov, alarm=False,
        )

    def serving_strategy_block(self) -> Dict:
        """The ``serving_strategy`` metadata block (engine metadata +
        GET /v2/models/{name} + obsreport summary): mesh geometry, the
        chosen layout, and every scored TP candidate."""
        block: Dict = {
            "tp_degree": self.tp_degree,
            "mesh_devices": self.mesh_devices,
        }
        if self.layout is not None:
            block["layout"] = self.layout.describe()
        if self.serving_strategy is not None:
            block["search"] = self.serving_strategy.describe()
        return block

    # ------------------------------------------------------------ geometry
    def reset(self) -> None:
        """Tear down device-side generation state after a crash or a
        stalled step: rezero the KV cache and restore the allocator's
        free list. The compiled program family and trace counters
        survive (params are unchanged), so recovery costs no
        recompilation — the scheduler journal-replays every live stream
        into the fresh cache."""
        self.cache.reset()
        self.allocator.reset()
        # the prefix index is provenance-bound to the dead cache: drop
        # every entry (resident ids AND host copies) wholesale — replay
        # re-matches against the empty index, which is recompute,
        # which is byte-exact
        self.prefix_cache.reset()
        self.last_finite = np.ones((self.max_batch_slots,), bool)
        self.resets += 1

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket {self.buckets[-1]}"
        )

    # ------------------------------------------------------- jitted bodies
    def _prefill_impl(self, params, tokens, length, cache_k, cache_v, block_table, temp, top_k, key, mask):
        s = tokens.shape[1]
        self.trace_counts[f"prefill[{s}]"] = self.trace_counts.get(f"prefill[{s}]", 0) + 1
        self.programs.note_trace(f"prefill[{s}]", {
            "params": params, "tokens": tokens, "length": length,
            "cache_k": cache_k, "block_table": block_table,
            "temp": temp, "top_k": top_k, "key": key, "mask": mask,
        })
        nb, bs = cache_k.shape[1], cache_k.shape[2]
        logits, ks, vs = prefill(params, tokens, jnp.full((1,), length, jnp.int32))
        positions = jnp.arange(s, dtype=jnp.int32)
        slots = slot_mapping(block_table, positions, bs)
        slots = jnp.where(positions < length, slots, 0)  # padding -> scratch

        def write(cache, layer_kv):
            flat = cache.reshape(nb * bs, *cache.shape[2:])
            return flat.at[slots].set(layer_kv.astype(flat.dtype)).reshape(cache.shape)

        cache_k = jax.vmap(write)(cache_k, ks[:, 0])
        cache_v = jax.vmap(write)(cache_v, vs[:, 0])
        last = logits[0, length - 1]
        ok = jnp.all(jnp.isfinite(last))  # blame: poisoned prompt
        # grammar mask: additive [V] bias, 0 / NEG (finite — the ok gate
        # above still sees model NaN, never the mask)
        last = last + mask
        token = _sample(last[None], temp[None], top_k[None], key[None])[0]
        return token, ok, cache_k, cache_v

    def _decode_impl(
        self, params, tokens, positions, cache_k, cache_v, block_tables, context_lens, temps, top_ks, bias, seeds, counts, mask
    ):
        self.trace_counts["decode"] = self.trace_counts.get("decode", 0) + 1
        self.programs.note_trace("decode", {
            "params": params, "tokens": tokens, "positions": positions,
            "cache_k": cache_k, "block_tables": block_tables,
            "context_lens": context_lens, "temps": temps, "top_ks": top_ks,
            "bias": bias, "seeds": seeds, "counts": counts, "mask": mask,
        })
        logits, cache_k, cache_v = decode_step(
            params, tokens, positions, cache_k, cache_v, block_tables,
            context_lens, backend=self.backend, mesh=self._kernel_mesh,
        )
        # bias is the fault plan's per-slot NaN poison (zeros outside
        # chaos runs); applying it before the finiteness reduce makes the
        # injected poison indistinguishable from model-produced NaN/inf.
        # mask is the grammar constraint: [B, V] additive rows of 0 / NEG
        # (finite, so it commutes with the poison semantics — the ok gate
        # trips on model/injected NaN, never on a banned token)
        logits = logits + bias[:, None] + mask
        ok = jnp.all(jnp.isfinite(logits), axis=-1)
        # sampling keys derive in-jit from (seed, token count): no host
        # fold_in/stack on the critical path, same key bits as before
        keys = derive_keys(seeds, counts)
        return _sample(logits, temps, top_ks, keys), ok, cache_k, cache_v

    def _verify_impl(
        self, params, tokens, start, n_draft, cache_k, cache_v, block_tables, temps, top_ks, bias, seeds, counts, mask
    ):
        """Speculative verification: score a [B, W] window (committed
        token + drafts) in one forward and accept/emit in-jit.
        ``n_draft[b]`` counts the slot's real drafts (0..W-1); -1 marks
        an inactive slot (everything masked to scratch, 0 emitted)."""
        from .speculative.sampling import speculative_accept

        self.trace_counts["verify"] = self.trace_counts.get("verify", 0) + 1
        self.programs.note_trace("verify", {
            "params": params, "tokens": tokens, "start": start,
            "n_draft": n_draft, "cache_k": cache_k,
            "block_tables": block_tables, "temps": temps, "top_ks": top_ks,
            "bias": bias, "seeds": seeds, "counts": counts, "mask": mask,
        })
        w = tokens.shape[1]
        keys = derive_window_keys(seeds, counts, w)  # in-jit, see decode
        offs = jnp.arange(w, dtype=jnp.int32)[None, :]
        # window token j sits at cache position start + j; slots past the
        # drafts (and whole inactive rows) are padding -> position -1
        positions = jnp.where(offs <= n_draft[:, None], start[:, None] + offs, -1)
        logits, cache_k, cache_v = verify_step(
            params, tokens, positions, cache_k, cache_v, block_tables,
            backend=self.backend, mesh=self._kernel_mesh,
        )
        # per-position grammar mask [B, W, V] rides next to the NaN-poison
        # bias; draft and target score the SAME masked logits, so
        # rejection sampling stays distribution-preserving over the
        # constrained support and greedy stays token-for-token exact
        logits = logits + bias[:, None, None] + mask
        # blame vector: finiteness over each slot's REAL window positions
        # only — padded positions (and whole inactive rows) attend to
        # nothing and may hold garbage that must not indict the request
        valid = offs <= jnp.maximum(n_draft, 0)[:, None]
        ok = jnp.all(
            jnp.where(valid[:, :, None], jnp.isfinite(logits), True), axis=(1, 2)
        )
        out, n_emitted = speculative_accept(
            logits, tokens[:, 1:], jnp.maximum(n_draft, 0), temps, top_ks, keys
        )
        return out, jnp.where(n_draft >= 0, n_emitted, 0), ok, cache_k, cache_v

    def _prefix_prefill_impl(
        self, params, tokens, start, n_real, cache_k, cache_v, block_table, temp, top_k, key, mask
    ):
        """Suffix-only prefill against a cached prefix: the [1, W]
        suffix window attends over the block table (shared prefix
        blocks + fresh suffix blocks) via the same chunked-append
        forward speculative verification uses, writes the suffix K/V,
        and samples the first generated token from the last REAL suffix
        position's logits. One program per suffix bucket W — admission
        cost, never steady state."""
        w = tokens.shape[1]
        name = f"prefix_prefill[{w}]"
        self.trace_counts[name] = self.trace_counts.get(name, 0) + 1
        self.programs.note_trace(name, {
            "params": params, "tokens": tokens, "start": start,
            "n_real": n_real, "cache_k": cache_k,
            "block_table": block_table, "temp": temp, "top_k": top_k,
            "key": key, "mask": mask,
        })
        offs = jnp.arange(w, dtype=jnp.int32)
        positions = jnp.where(offs < n_real, start + offs, -1)[None, :]
        logits, cache_k, cache_v = verify_step(
            params, tokens, positions, cache_k, cache_v, block_table[None],
            backend=self.backend, mesh=self._kernel_mesh,
        )
        last = logits[0, n_real - 1]
        ok = jnp.all(jnp.isfinite(last))  # blame: poisoned prompt
        last = last + mask  # grammar mask: [V], finite (see _prefill_impl)
        token = _sample(last[None], temp[None], top_k[None], key[None])[0]
        return token, ok, cache_k, cache_v

    def _copy_block_impl(self, cache_k, cache_v, src, dst):
        """COW: duplicate one block's K/V across all layers (the first
        divergent append into a shared block lands in the copy)."""
        self.trace_counts["kv_cow_copy"] = self.trace_counts.get("kv_cow_copy", 0) + 1
        self.programs.note_trace("kv_cow_copy", {
            "cache_k": cache_k, "src": src, "dst": dst,
        })
        k = jax.lax.dynamic_index_in_dim(cache_k, src, axis=1)
        v = jax.lax.dynamic_index_in_dim(cache_v, src, axis=1)
        return (
            jax.lax.dynamic_update_slice_in_dim(cache_k, k, dst, axis=1),
            jax.lax.dynamic_update_slice_in_dim(cache_v, v, dst, axis=1),
        )

    def _read_block_impl(self, cache_k, cache_v, src):
        """Host-tier swap-out read: one block's K/V ([L, bs, H, D]
        each), fetched with a traced index so every block id shares ONE
        program."""
        self.trace_counts["kv_block_read"] = self.trace_counts.get("kv_block_read", 0) + 1
        self.programs.note_trace("kv_block_read", {"cache_k": cache_k, "src": src})
        return (
            jax.lax.dynamic_index_in_dim(cache_k, src, axis=1, keepdims=False),
            jax.lax.dynamic_index_in_dim(cache_v, src, axis=1, keepdims=False),
        )

    def _write_block_impl(self, cache_k, cache_v, dst, host_k, host_v):
        """Host-tier swap-in write: place one block's K/V back into the
        device cache at ``dst``."""
        self.trace_counts["kv_block_write"] = self.trace_counts.get("kv_block_write", 0) + 1
        self.programs.note_trace("kv_block_write", {
            "cache_k": cache_k, "dst": dst, "host_k": host_k,
        })
        return (
            jax.lax.dynamic_update_slice_in_dim(
                cache_k, host_k[:, None].astype(cache_k.dtype), dst, axis=1
            ),
            jax.lax.dynamic_update_slice_in_dim(
                cache_v, host_v[:, None].astype(cache_v.dtype), dst, axis=1
            ),
        )

    def _read_blocks_impl(self, cache_k, cache_v, srcs):
        """Batched wire read: one payload's blocks ([L, n, bs, H, D]
        each) gathered in a single program. ``srcs`` is padded to
        ``max_blocks_per_seq`` by repeating the last id, so every
        prompt length shares ONE fixed-shape program."""
        self.trace_counts["kv_blocks_read"] = self.trace_counts.get("kv_blocks_read", 0) + 1
        self.programs.note_trace("kv_blocks_read", {"cache_k": cache_k, "srcs": srcs})
        return (
            jnp.take(cache_k, srcs, axis=1),
            jnp.take(cache_v, srcs, axis=1),
        )

    def _write_blocks_impl(self, cache_k, cache_v, dsts, host_ks, host_vs):
        """Batched wire write: commit one payload's blocks in a single
        program. A scan keeps the duplicate padding ids harmless — a
        repeated destination is simply rewritten with the same data."""
        self.trace_counts["kv_blocks_write"] = self.trace_counts.get("kv_blocks_write", 0) + 1
        self.programs.note_trace("kv_blocks_write", {
            "cache_k": cache_k, "dsts": dsts, "host_ks": host_ks,
        })

        def body(carry, x):
            ck, cv = carry
            dst, hk, hv = x
            ck = jax.lax.dynamic_update_slice_in_dim(
                ck, hk[:, None].astype(ck.dtype), dst, axis=1
            )
            cv = jax.lax.dynamic_update_slice_in_dim(
                cv, hv[:, None].astype(cv.dtype), dst, axis=1
            )
            return (ck, cv), None

        (ck, cv), _ = jax.lax.scan(
            body, (cache_k, cache_v), (dsts, host_ks, host_vs)
        )
        return ck, cv

    # ----------------------------------------------------------- host API
    def _record_step_phases(
        self, kind: str, t0: float, t_disp: float, t_exec: float
    ) -> Tuple[float, float]:
        """Stamp one step's dispatch/execute/readback split (called
        after the result readback; stamps t_read itself) and publish
        the spans for the scheduler's step-anatomy profiler. "block"
        (host parked in block_until_ready) and "execute" (device
        computing) cover the same interval today; they separate once
        the overlap refactor dispatches ahead of the bookkeeping.
        Returns (total_elapsed_s, execute_s) — the old conflated total
        and the device-only seconds the truth ledger now pairs."""
        t_read = time.perf_counter()
        ph = self.phase_time_s[kind]
        ph["dispatch"] += t_disp - t0
        ph["execute"] += t_exec - t_disp
        ph["readback"] += t_read - t_exec
        self.last_step_spans = [
            ("dispatch", t0, t_disp),
            ("block", t_disp, t_exec),
            ("execute", t_disp, t_exec),
            ("readback", t_exec, t_read),
        ]
        return t_read - t0, t_exec - t_disp

    def prefill_one(
        self,
        prompt: Sequence[int],
        block_table: Sequence[int],
        sampling: SamplingParams,
        key: jax.Array,
        prefix_len: int = 0,
        mask=None,
    ) -> int:
        """Prefill one sequence into its allocated blocks and sample its
        first generated token. ``block_table`` is the sequence's block
        ids (padded internally to the engine's fixed table width).
        ``prefix_len`` > 0 means positions [0, prefix_len) are already
        cached (shared prefix blocks at the front of the table): only
        the suffix is computed, attending to the cached prefix — the
        O(suffix) admission path prefix caching exists for.
        ``mask`` is an optional [vocab] grammar bias (0 / NEG) applied
        to the sampled position; None stages the shared zeros row."""
        faults.inject(faults.GENERATION_PREFILL, prompt)
        if prefix_len > 0:
            return self._prefill_suffix(prompt, block_table, sampling, key, prefix_len, mask)
        self.step_counts["prefill"] += 1
        t0 = time.perf_counter()
        n = len(prompt)
        bucket = self.bucket_for(n)
        traces_before = self.trace_counts.get(f"prefill[{bucket}]", 0)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[: len(block_table)] = block_table
        token, ok, ck, cv = self._prefill_jit(
            self.params,
            self._dev(tokens),
            jnp.int32(n),
            self.cache.k,
            self.cache.v,
            self._dev(table),
            jnp.float32(sampling.temperature),
            jnp.int32(sampling.top_k),
            self._dev(key),
            self._mask_arg(mask, "prefill_mask", (self.cfg.vocab_size,)),
        )
        t_disp = time.perf_counter()
        jax.block_until_ready((token, ok, ck, cv))  # device execution done
        t_exec = time.perf_counter()
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok).reshape(1)
        out = int(token)  # result sync lands inside the readback span
        elapsed, execute_s = self._record_step_phases("prefill", t0, t_disp, t_exec)
        # FLOPs accrue only on SUCCESS, next to the time they pair with:
        # a step that raises (and is retried by the supervisor) must not
        # count its FLOPs without its time, or MFU inflates under faults
        flops = self.flops_model.prefill_flops(n)
        self.flops_by_kind["prefill"] += flops
        if self.trace_counts.get(f"prefill[{bucket}]", 0) > traces_before:
            # this call traced (first compile or a retrace): its wall
            # time is the program's compile cost, registry-stamped
            self.programs.set_compile_time(f"prefill[{bucket}]", elapsed)
        else:
            # ledger prediction covers EXECUTED work — the program
            # computes the full padded bucket, so predicting from the
            # true prompt length would alarm on every short prompt in a
            # wide bucket. MFU above stays useful-work-only.
            self.ledger.observe(
                f"prefill[{bucket}]",
                self.flops_model.roofline_s(
                    self.flops_model.prefill_flops(bucket),
                    self.flops_model.prefill_bytes(bucket),
                ),
                execute_s,
                label=f"prefill[{bucket}] ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
            if self.serving_strategy is not None:
                # pair the measured step against the layout-search
                # estimate too: drift telemetry covers the DECISION
                self.ledger.measure("serving_strategy:prefill", execute_s)
        return out

    def _prefill_suffix(
        self,
        prompt: Sequence[int],
        block_table: Sequence[int],
        sampling: SamplingParams,
        key: jax.Array,
        prefix_len: int,
        mask=None,
    ) -> int:
        """Suffix-only prefill: positions [prefix_len, len(prompt))
        computed against the cached prefix. Accounting mirrors
        prefill(): step/FLOPs/time under the "prefill" kind, compile
        calls registry-stamped, steady calls ledger-paired."""
        self.step_counts["prefill"] += 1
        t0 = time.perf_counter()
        n = len(prompt)
        suffix = list(prompt[prefix_len:])
        w = self.bucket_for(len(suffix))
        name = f"prefix_prefill[{w}]"
        traces_before = self.trace_counts.get(name, 0)
        tokens = np.zeros((1, w), np.int32)
        tokens[0, : len(suffix)] = suffix
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[: len(block_table)] = block_table
        token, ok, ck, cv = self._prefix_prefill_jit(
            self.params,
            self._dev(tokens),
            jnp.int32(prefix_len),
            jnp.int32(len(suffix)),
            self.cache.k,
            self.cache.v,
            self._dev(table),
            jnp.float32(sampling.temperature),
            jnp.int32(sampling.top_k),
            self._dev(key),
            self._mask_arg(mask, "prefill_mask", (self.cfg.vocab_size,)),
        )
        t_disp = time.perf_counter()
        jax.block_until_ready((token, ok, ck, cv))  # device execution done
        t_exec = time.perf_counter()
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok).reshape(1)
        out = int(token)  # result sync lands inside the readback span
        elapsed, execute_s = self._record_step_phases("prefill", t0, t_disp, t_exec)
        # useful work = suffix tokens only, each attending its full live
        # context (causal): ctx = sum_{p=prefix_len}^{n-1} (p + 1)
        ctx = (n * (n + 1) - prefix_len * (prefix_len + 1)) // 2
        flops = self.flops_model.verify_flops(len(suffix), ctx)
        self.flops_by_kind["prefill"] += flops
        if self.trace_counts.get(name, 0) > traces_before:
            self.programs.set_compile_time(name, elapsed)
        else:
            # EXECUTED work: the program computes the full padded W
            # window (padding attends to nothing — see verify())
            self.ledger.observe(
                name,
                self.flops_model.roofline_s(
                    self.flops_model.verify_flops(w, ctx),
                    self.flops_model.verify_bytes(w, ctx),
                ),
                execute_s,
                label=f"{name} ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
        return out

    # ------------------------------------------------------ prefix caching
    def prefix_plan(self, prompt: Sequence[int]) -> PrefixPlan:
        """Match ``prompt`` against the radix index and decide what to
        reuse. Offloaded entries in the matched run are only used when
        the host->device transfer beats recomputing the same positions
        on the chip roofline (the PR 7 cost-model idiom); otherwise the
        run truncates at the first offloaded entry. A failed lookup
        (``generation.prefix_lookup`` chaos) degrades to a miss — full
        recompute, byte-exact."""
        pc = self.prefix_cache
        if not pc.enabled or len(prompt) < 2:
            return EMPTY_PREFIX_PLAN
        try:
            faults.inject(faults.GENERATION_PREFIX_LOOKUP, list(prompt))
            run = pc.match(prompt)
        except Exception:
            pc.recompute_fallbacks += 1
            return EMPTY_PREFIX_PLAN
        if not run:
            return EMPTY_PREFIX_PLAN
        bs = self.cache_config.block_size
        reuse = min(len(run) * bs, len(prompt) - 1)
        n_shared = reuse // bs
        cow = run[n_shared] if (reuse % bs and len(run) > n_shared) else None
        entries = run[:n_shared]
        off_idx = [i for i, e in enumerate(entries) if not e.resident]
        cow_off = cow is not None and not cow.resident
        if off_idx or cow_off:
            n_off = len(off_idx) + (1 if cow_off else 0)
            first = off_idx[0] if off_idx else n_shared
            # the recompute alternative: truncate at the first offloaded
            # entry and prefill positions [first*bs, reuse) instead
            start = first * bs
            n_tok = reuse - start
            ctx = (reuse * (reuse + 1) - start * (start + 1)) // 2
            recompute_s = self.flops_model.roofline_s(
                self.flops_model.verify_flops(n_tok, ctx),
                self.flops_model.verify_bytes(n_tok, ctx),
            )
            if pc.swap_in_cost_s(n_off) >= recompute_s:
                pc.recompute_fallbacks += 1
                entries = entries[:first]
                reuse = first * bs
                cow = None
        n_resident = sum(1 for e in entries if e.resident)
        return PrefixPlan(entries, cow, reuse, n_resident)

    def prepare_prefix(
        self,
        prompt: Sequence[int],
        plan: PrefixPlan,
        new_blocks: List[int],
    ) -> Optional[Tuple[List[int], set, List[PrefixEntry], int]]:
        """Assemble one admission's block table from a plan: shared
        entries first (swapping offloaded ones back in), then the
        private blocks (COW boundary copy, suffix, growth room).
        Returns (table, shared_idx, held entries, prefix_len), or None
        when a mid-assembly swap-in fallback could not replace the lost
        shared blocks — everything is handed back and the caller
        retries admission later.

        A failed or corrupted swap-in truncates reuse at that entry and
        falls back to recomputing the rest — the exactness invariant
        makes the fallback invisible in the token stream."""
        pc = self.prefix_cache
        bs = self.cache_config.block_size
        entries = list(plan.entries)
        cow = plan.cow
        reuse = plan.reuse_tokens
        pc.acquire(entries)
        if cow is not None:
            # hold the boundary entry too: the reclaim fallback below
            # must not evict the COW source out from under the copy
            pc.acquire([cow])
        pool = list(new_blocks)
        shared: List[int] = []
        kept: List[PrefixEntry] = []
        failed_at: Optional[int] = None
        for i, e in enumerate(entries):
            if e.resident:
                shared.append(e.block)
                kept.append(e)
                continue
            if not pool:
                failed_at = i  # stale plan: no swap target left
                break
            dst = pool.pop(0)
            if self._swap_in(e, dst):
                shared.append(e.block)
                kept.append(e)
            else:
                pool.insert(0, dst)
                failed_at = i
                break
        need_total = self.cache_config.blocks_for(len(prompt) + 1)
        if failed_at is not None:
            pc.release(entries[failed_at:])
            entries = list(kept)
            reuse = len(kept) * bs
            if cow is not None:
                pc.release([cow])
                cow = None
        # re-balance the private pool against the full table budget.
        # The plan's resident count can go stale between planning and
        # assembly: a reclaim (this admission's own, or the allocator
        # retry's) may evict a planned-resident entry, whose swap-in
        # then consumes a pool block budgeted for the suffix — a short
        # table would silently map suffix positions to the scratch
        # block and corrupt the stream. Top the pool back up (or hand
        # everything back and let the caller retry).
        short = need_total - len(shared) - len(pool)
        if short > 0:
            extra = self.allocator.allocate(short)
            if extra is None and self.reclaim_cached(short):
                extra = self.allocator.allocate(short)
            if extra is None:
                if cow is not None:
                    pc.release([cow])
                pc.release(kept)
                self.allocator.free(pool)
                return None
            pool.extend(extra)
        if cow is not None:
            # the boundary block: the copy target doubles as the plain
            # private block when the COW source is unusable (corrupt
            # offloaded content) — the table shape is identical either
            # way, only prefix_len changes
            if pool and self._cow_copy(cow, pool[0]):
                pc.cow_copies_total += 1
            else:
                reuse = len(kept) * bs
            pc.release([cow])
        table = shared + pool
        if len(table) > need_total:
            surplus = table[need_total:]
            del table[need_total:]
            self.allocator.free(surplus)
        return table, set(range(len(shared))), kept, reuse

    def _swap_in(self, entry: PrefixEntry, dst: int) -> bool:
        """Bring one offloaded entry's K/V back to device block ``dst``.
        CRC-verified; the (predicted, measured) transfer time joins the
        PredictionLedger so drift telemetry covers the swap heuristic."""
        pc = self.prefix_cache
        predicted = pc.swap_in_cost_s(1)
        traces_before = self.trace_counts.get("kv_block_write", 0)
        t0 = time.perf_counter()
        try:
            faults.inject(faults.GENERATION_KV_OFFLOAD, ("in", 1))
            buf = pc.take_host_copy(entry)
            if buf is None:  # corrupted or already dropped
                raise ValueError("host-tier block failed CRC verification")
            hk, hv = buf
            ck, cv = self._write_block_jit(
                self.cache.k, self.cache.v, jnp.int32(dst),
                self._dev(hk), self._dev(hv),
            )
            self.cache.update(ck, cv)
        except Exception:
            pc.swap_in_failures += 1
            pc.recompute_fallbacks += 1
            return False
        pc.note_swapped_in(entry, dst)
        elapsed = time.perf_counter() - t0
        if self.trace_counts.get("kv_block_write", 0) == traces_before:
            self.ledger.observe(
                "kv_swap_in", predicted, elapsed,
                label="kv_swap_in (host tier)",
                provenance="host-tier transfer model (link bytes/s)",
                alarm=self._roofline_alarm,
            )
        return True

    def _cow_copy(self, src: PrefixEntry, dst: int) -> bool:
        """Materialize a private copy of ``src``'s block at ``dst`` —
        from device (resident) or the host tier (offloaded). The source
        entry is untouched: its content stays shared."""
        if src.resident:
            ck, cv = self._copy_block_jit(
                self.cache.k, self.cache.v,
                jnp.int32(src.block), jnp.int32(dst),
            )
            self.cache.update(ck, cv)
            return True
        pc = self.prefix_cache
        try:
            faults.inject(faults.GENERATION_KV_OFFLOAD, ("in", 1))
            buf = pc.take_host_copy(src)
            if buf is None:
                raise ValueError("host-tier block failed CRC verification")
            hk, hv = buf
            ck, cv = self._write_block_jit(
                self.cache.k, self.cache.v, jnp.int32(dst),
                self._dev(hk), self._dev(hv),
            )
            self.cache.update(ck, cv)
        except Exception:
            pc.swap_in_failures += 1
            pc.recompute_fallbacks += 1
            return False
        pc.swaps_in_total += 1
        return True

    def register_prefix(
        self,
        prompt: Sequence[int],
        table: List[int],
        shared_idx: set,
        entries: List[PrefixEntry],
        prefix_len: int = 0,
    ) -> None:
        """Post-prefill registration: the prompt's freshly written full
        blocks join the radix index (ownership moves to the index; the
        sequence keeps a ref). Called only after the finiteness check —
        poisoned K/V must never become shared content. Reuse telemetry
        counts HERE, not at table assembly, so a failed or poisoned
        prefill (whose retry would double-count) never inflates
        hit/reuse ratios with reuse that produced no token."""
        pc = self.prefix_cache
        if not pc.enabled:
            return
        pc.lookups += 1
        if prefix_len > 0:
            pc.hits += 1
            pc.tokens_reused_total += prefix_len
            pc.blocks_reused_total += len(entries)
        self.prefix_cache.register_chain(
            prompt, table, shared_idx, entries, len(prompt)
        )

    def stash_prefix(self, state) -> None:
        """Preemption stash: register the victim's full blocks below
        ``cached_len`` (prompt AND generated content) so its recompute
        re-admission — and any request sharing the prefix — matches
        them instead of recomputing; under continued pressure they
        offload to the host tier and swap back in."""
        if not self.prefix_cache.enabled:
            return
        req = state.req
        tokens = list(req.original_prompt) + list(req.generated)
        upto = min(state.cached_len, len(tokens))
        self.prefix_cache.register_chain(
            tokens, state.blocks, state.shared_idx, state.shared_entries, upto
        )

    def release_admission(
        self, table: List[int], shared_idx: set, entries: List[PrefixEntry]
    ) -> None:
        """Undo one admission's block bookkeeping (failed or poisoned
        prefill): private blocks back to the allocator, shared refs
        dropped (the content stays cached for the next request)."""
        self.allocator.free(
            [b for i, b in enumerate(table) if i not in shared_idx]
        )
        self.prefix_cache.release(entries)

    def reclaim_cached(self, n_blocks: int) -> int:
        """Free device blocks held by unreferenced cached prefixes (LRU;
        content offloads to the host tier when budget allows). The
        allocator's last resort before preemption."""
        if not self.prefix_cache.enabled:
            return 0

        def read(block_id: int):
            faults.inject(faults.GENERATION_KV_OFFLOAD, ("out", 1))
            k, v = self._read_block_jit(
                self.cache.k, self.cache.v, jnp.int32(block_id)
            )
            return np.asarray(k), np.asarray(v)

        return self.prefix_cache.reclaim(max(1, n_blocks), read)

    def pack_kv_blocks(
        self, table: List[int], n_positions: int
    ) -> KVHandoffPayload:
        """Pack the blocks covering positions ``[0, n_positions)`` into
        the prefill->decode wire format: full-head host reads through
        the same jitted block reader the host tier uses (the reader's
        replicated out_shardings gather every head even when this
        engine's cache is sharded, so the payload is TP-agnostic), each
        block CRC-stamped at packing time."""
        bs = self.cache_config.block_size
        n_blocks = self.cache_config.blocks_for(n_positions)
        ids = list(table[:n_blocks])
        srcs = ids + [ids[-1]] * (self.max_blocks_per_seq - len(ids))
        ks, vs = self._read_blocks_jit(
            self.cache.k, self.cache.v,
            self._dev(np.asarray(srcs, dtype=np.int32)),
        )
        ks, vs = np.asarray(ks), np.asarray(vs)
        blocks = [
            PackedBlock(np.ascontiguousarray(ks[:, i]),
                        np.ascontiguousarray(vs[:, i]))
            for i in range(len(ids))
        ]
        return KVHandoffPayload(n_positions, bs, blocks)

    def import_kv_block(
        self, dst: int, host_k: np.ndarray, host_v: np.ndarray
    ) -> None:
        """Commit one wire block into this engine's cache at ``dst``
        through the jitted block writer — the write's out_shardings
        reshard the full-head payload onto this engine's own head
        partitioning, so differing pool TP degrees need no explicit
        reshard step."""
        ck, cv = self._write_block_jit(
            self.cache.k, self.cache.v, jnp.int32(dst),
            self._dev(host_k), self._dev(host_v),
        )
        self.cache.update(ck, cv)

    def import_kv_blocks(self, dsts: Sequence[int], blocks) -> None:
        """Commit one payload's wire blocks in a single batched program
        (same resharding semantics as :meth:`import_kv_block`): padded
        to ``max_blocks_per_seq`` by repeating the last block, so a
        decode-pool replica pays one dispatch per adopted stream, not
        one per block, between its decode steps."""
        ids = list(dsts)
        pad = self.max_blocks_per_seq - len(ids)
        idx = ids + [ids[-1]] * pad
        hk = np.stack([b.host_k for b in blocks] + [blocks[-1].host_k] * pad)
        hv = np.stack([b.host_v for b in blocks] + [blocks[-1].host_v] * pad)
        ck, cv = self._write_blocks_jit(
            self.cache.k, self.cache.v,
            self._dev(np.asarray(idx, dtype=np.int32)),
            self._dev(hk), self._dev(hv),
        )
        self.cache.update(ck, cv)

    def _stage(self, name: str, host: np.ndarray) -> jax.Array:
        """Device-resident staging: upload ``host`` once and reuse the
        device array until the contents change. Slot-constant decode/
        verify args (block tables, sampling params, seeds) change only
        on batch-composition events, so steady state stops paying a
        fresh ``jnp.asarray`` per arg per step. The host snapshot is
        copied — callers may mutate their arrays in place afterwards."""
        cached = self._staged.get(name)
        if (
            cached is not None
            and cached[0].shape == host.shape
            and cached[0].dtype == host.dtype
            and np.array_equal(cached[0], host)
        ):
            return cached[1]
        dev = self._dev(host)
        self._staged[name] = (host.copy(), dev)
        return dev

    def _decode_args(self, positions, block_tables, active, temps, top_ks, seeds, counts, bias, mask=None):
        """Assemble the decode jit's argument tuple (minus the token
        array, which the pipelined path carries device-resident)."""
        context_lens = np.where(active, positions + 1, 0).astype(np.int32)
        safe_pos = np.where(active, positions, 0).astype(np.int32)
        # scratch-mask inactive slots' tables too: an inactive slot with
        # a REAL table (a bisection probe deactivating a live slot)
        # would otherwise write its position-0 K/V into that slot's
        # first real block and silently corrupt the surviving stream
        tables = np.where(active[:, None], block_tables, 0).astype(np.int32)
        return (
            self._dev(safe_pos),
            self.cache.k,
            self.cache.v,
            self._stage("decode.tables", tables),
            self._dev(context_lens),
            self._stage("decode.temps", temps.astype(np.float32)),
            self._stage("decode.top_ks", top_ks.astype(np.int32)),
            self._bias_arg(bias),
            self._stage("decode.seeds", seeds.astype(np.uint32)),
            self._dev(counts.astype(np.int32)),
            self._mask_arg(
                mask, "decode_mask",
                (self.max_batch_slots, self.cfg.vocab_size),
            ),
        ), context_lens

    def decode(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        block_tables: np.ndarray,
        active: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        seeds: np.ndarray,
        counts: np.ndarray,
        mask: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """One decode step across all ``max_batch_slots`` slots. Arrays
        are slot-indexed; inactive slots (active[i] False) write to
        scratch and return garbage tokens the scheduler ignores. After
        the call ``last_finite[i]`` says whether slot i's logits were
        finite — the supervisor's per-slot NaN blame vector.
        ``seeds``/``counts`` replace the old host-built key array: the
        per-slot sampling key derives in-jit (see :func:`derive_keys`)."""
        masked = np.where(active, tokens, 0).astype(np.int32)
        masked, bias = faults.inject(faults.GENERATION_DECODE_STEP, (masked, self._zero_bias))
        if self.tp_degree > 1:
            # sharded step: the cross-shard psum boundary can fail or
            # wedge like any device work — chaos plans target it here
            faults.inject(faults.GENERATION_COLLECTIVE, ("decode", self.tp_degree))
        self.step_counts["decode"] += 1
        t0 = time.perf_counter()
        traces_before = self.trace_counts.get("decode", 0)
        args, context_lens = self._decode_args(
            positions, block_tables, active, temps, top_ks, seeds,
            counts, bias, mask,
        )
        out, ok, ck, cv = self._decode_jit(self.params, self._dev(masked), *args)
        t_disp = time.perf_counter()
        jax.block_until_ready((out, ok, ck, cv))  # device execution done
        t_exec = time.perf_counter()
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok)
        result = np.asarray(out)  # result sync lands in the readback span
        elapsed, execute_s = self._record_step_phases("decode", t0, t_disp, t_exec)
        # success-only, paired with the time below (see prefill())
        n_active, ctx_sum = int(active.sum()), int(context_lens.sum())
        self._account_decode(
            n_active, ctx_sum,
            self.trace_counts.get("decode", 0) > traces_before,
            elapsed, execute_s,
        )
        return result

    def _account_decode(self, n_active, ctx_sum, traced, elapsed, execute_s):
        """Post-success decode accounting, shared by the blocking and
        pipelined paths: FLOPs accrue next to the time they pair with;
        a compile call registry-stamps its wall time instead of feeding
        the truth ledger."""
        flops = self.flops_model.decode_flops(n_active, ctx_sum)
        self.flops_by_kind["decode"] += flops
        if traced:
            self.programs.set_compile_time("decode", elapsed)
        else:
            # EXECUTED work: the fixed-shape program runs every batch
            # slot's projections/FFN (inactive rows masked to scratch,
            # but computed); only attention context is truly live-only
            b = self.max_batch_slots
            self.ledger.observe(
                "decode",
                self.flops_model.roofline_s(
                    self.flops_model.decode_flops(b, ctx_sum),
                    self.flops_model.decode_bytes(b, ctx_sum),
                ),
                execute_s,
                label=f"decode ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
            if self.serving_strategy is not None:
                # pair the measured step against the layout-search
                # estimate too: drift telemetry covers the DECISION
                self.ledger.measure("serving_strategy:decode", execute_s)

    def decode_async(
        self,
        tokens: Optional[np.ndarray],
        positions: np.ndarray,
        block_tables: np.ndarray,
        active: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        seeds: np.ndarray,
        counts: np.ndarray,
        tokens_dev: Optional[jax.Array] = None,
        mask: Optional[np.ndarray] = None,
    ) -> InFlightDecode:
        """Dispatch one decode step WITHOUT blocking on it: the overlap
        pipeline's front half. Returns an :class:`InFlightDecode` whose
        result :meth:`consume_decode` collects one scheduler iteration
        later — the async host copy of the sampled tokens starts here,
        at dispatch-return, so the eventual readback is a wait on an
        already-moving transfer (double-buffered readback), not a fresh
        synchronous device round trip.

        ``tokens_dev`` carries the PREVIOUS step's sampled-token device
        array straight back in (device-resident staging: steady-state
        decode uploads no token array at all and XLA chains the steps
        on-device); ``tokens`` is the host token array for the
        pipeline's first step (or None in carry mode — the fault site
        still fires with the same (tokens, bias) value shape). Inactive
        slots in carry mode embed whatever garbage token the dead slot
        sampled; their writes land in scratch and their outputs are
        dropped, exactly like the host-masked path."""
        if tokens_dev is None:
            masked = np.where(active, tokens, 0).astype(np.int32)
        else:
            masked = None
        masked, bias = faults.inject(
            faults.GENERATION_DECODE_STEP, (masked, self._zero_bias)
        )
        if self.tp_degree > 1:
            faults.inject(faults.GENERATION_COLLECTIVE, ("decode", self.tp_degree))
        self.step_counts["decode"] += 1
        t0 = time.perf_counter()
        traces_before = self.trace_counts.get("decode", 0)
        args, context_lens = self._decode_args(
            positions, block_tables, active, temps, top_ks, seeds,
            counts, bias, mask,
        )
        tok_arg = tokens_dev if tokens_dev is not None else self._dev(masked)
        prev_k, prev_v = (None, None) if self.donate else (self.cache.k, self.cache.v)
        out, ok, ck, cv = self._decode_jit(self.params, tok_arg, *args)
        t_disp = time.perf_counter()
        # start the device->host copies NOW; consume_decode's numpy
        # conversion then finds the bytes already resident
        out.copy_to_host_async()
        ok.copy_to_host_async()
        self.cache.update(ck, cv)
        self.phase_time_s["decode"]["dispatch"] += t_disp - t0
        return InFlightDecode(
            out, ok, prev_k, prev_v, ck, cv, t0, t_disp,
            traced=self.trace_counts.get("decode", 0) > traces_before,
            n_active=int(active.sum()), ctx_sum=int(context_lens.sum()),
        )

    def consume_decode(self, step: InFlightDecode) -> np.ndarray:
        """Block on an in-flight decode step and finish its accounting:
        the overlap pipeline's back half. On failure the pre-step cache
        refs are restored (non-donating engines only) so the scheduler
        can re-run the step sequentially under the supervisor's normal
        retry/bisect machinery; a donating engine's failed step is
        handled by reset + journal replay instead."""
        if step.consumed:
            raise RuntimeError("InFlightDecode consumed twice")
        step.consumed = True
        t_block = time.perf_counter()
        try:
            jax.block_until_ready((step.out, step.ok))
        except Exception:
            if step.prev_k is not None:
                # roll the cache back to the pre-step refs: the failed
                # program's outputs (and any successor chained on them)
                # are poisoned, while the inputs are still intact. A
                # successor's own discard must NOT restore forward over
                # this (it checks its outputs are still current).
                self.cache.update(step.prev_k, step.prev_v)
            raise
        t_exec = time.perf_counter()
        self.last_finite = np.asarray(step.ok)
        result = np.asarray(step.out)  # async copy already landed
        t_read = time.perf_counter()
        ph = self.phase_time_s["decode"]
        ph["execute"] += t_exec - step.t_started
        ph["readback"] += t_read - t_exec
        # two-lane spans: "execute" starts at t_started (when the device
        # actually began this step — restamped by the scheduler at the
        # previous step's completion), "block" is only the host's park
        # inside THIS call. The lanes genuinely diverge under overlap.
        self.last_step_spans = [
            ("block", t_block, t_exec),
            ("execute", step.t_started, t_exec),
            ("readback", t_exec, t_read),
        ]
        self._account_decode(
            step.n_active, step.ctx_sum, step.traced,
            elapsed=t_read - step.t0,
            execute_s=t_exec - step.t_started,
        )
        return result

    def _bias_arg(self, bias) -> jax.Array:
        """Device-side logit bias: the cached zeros unless a fault plan
        actually poisoned this call."""
        if bias is self._zero_bias:
            return self._zero_bias_dev
        return self._dev(np.asarray(bias, np.float32))

    def _mask_arg(self, mask, name: str, shape: Tuple[int, ...]) -> jax.Array:
        """Device-side grammar mask: with no constrained slot in the
        batch (mask None — the overwhelmingly common case) every call
        reuses one cached zeros array per shape, so unconstrained
        serving uploads nothing and the jit signature stays fixed.
        Built lazily: the [B, W, V] verify zeros never allocate unless
        speculation actually runs."""
        if mask is None:
            cached = self._zero_masks.get(name)
            if cached is None:
                cached = self._dev(np.zeros(shape, np.float32))
                self._zero_masks[name] = cached
            return cached
        return self._dev(np.asarray(mask, np.float32))

    def verify(
        self,
        window_tokens: np.ndarray,
        start: np.ndarray,
        n_draft: np.ndarray,
        block_tables: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        seeds: np.ndarray,
        counts: np.ndarray,
        mask: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One speculative verification step across all slots.

        ``window_tokens`` [B, spec_window]: per slot, the last committed
        token followed by its drafts (then padding); ``start`` [B]: the
        committed token's cache position (the slot's ``cached_len``);
        ``n_draft`` [B]: real drafts per slot, -1 for inactive slots;
        ``seeds``/``counts`` [B]: per-slot sampling seed and generated-
        token count — the [B, spec_window] per-emitted-count key matrix
        derives in-jit (:func:`derive_window_keys`), deleting the host
        key-assembly phase. Returns (out_tokens [B, spec_window],
        n_emitted [B]) — the scheduler keeps
        ``out_tokens[i, :n_emitted[i]]`` (further truncated by EOS /
        budget). ONE fixed-shape jit: per-request adaptive k only
        changes ``n_draft`` values, never the shape.
        """
        window = window_tokens.astype(np.int32)
        window, bias = faults.inject(faults.GENERATION_VERIFY, (window, self._zero_bias))
        if self.tp_degree > 1:
            faults.inject(faults.GENERATION_COLLECTIVE, ("verify", self.tp_degree))
        self.step_counts["verify"] += 1
        # useful verify work: per live slot, n_draft+1 window tokens;
        # window token j at position start+j attends to start+j+1 live
        # context positions -> (nd+1)(start+1) + nd(nd+1)/2. Computed
        # BEFORE the clock starts: device_time_s is wall seconds inside
        # the step's host API call only, same as prefill/decode
        nd = np.maximum(n_draft, 0).astype(np.int64)
        live = n_draft >= 0
        w_tok = np.where(live, nd + 1, 0)
        ctx = np.where(live, w_tok * (start.astype(np.int64) + 1) + nd * (nd + 1) // 2, 0)
        t0 = time.perf_counter()
        traces_before = self.trace_counts.get("verify", 0)
        out, n_emitted, ok, ck, cv = self._verify_jit(
            self.params,
            self._dev(window),
            self._dev(start.astype(np.int32)),
            self._dev(n_draft.astype(np.int32)),
            self.cache.k,
            self.cache.v,
            self._stage("verify.tables", block_tables.astype(np.int32)),
            self._stage("verify.temps", temps.astype(np.float32)),
            self._stage("verify.top_ks", top_ks.astype(np.int32)),
            self._bias_arg(bias),
            self._stage("verify.seeds", seeds.astype(np.uint32)),
            self._dev(counts.astype(np.int32)),
            self._mask_arg(
                mask, "verify_mask",
                (self.max_batch_slots, self.spec_window, self.cfg.vocab_size),
            ),
        )
        t_disp = time.perf_counter()
        jax.block_until_ready((out, n_emitted, ok, ck, cv))  # execution done
        t_exec = time.perf_counter()
        self.cache.update(ck, cv)
        self.last_finite = np.asarray(ok)
        result = (np.asarray(out), np.asarray(n_emitted))
        elapsed, execute_s = self._record_step_phases("verify", t0, t_disp, t_exec)
        # success-only, paired with the time below (see prefill())
        n_tok, ctx_sum = int(w_tok.sum()), int(ctx.sum())
        flops = self.flops_model.verify_flops(n_tok, ctx_sum)
        self.flops_by_kind["verify"] += flops
        if self.trace_counts.get("verify", 0) > traces_before:
            self.programs.set_compile_time("verify", elapsed)
        else:
            # EXECUTED work: all B x W window positions compute (see
            # decode) — padding only skips attention context
            bw = self.max_batch_slots * self.spec_window
            self.ledger.observe(
                "verify",
                self.flops_model.roofline_s(
                    self.flops_model.verify_flops(bw, ctx_sum),
                    self.flops_model.verify_bytes(bw, ctx_sum),
                ),
                execute_s,
                label=f"verify ({self.flops_model.chip.name})",
                provenance="serving roofline (ServingFlops x chip peak)",
                alarm=self._roofline_alarm,
            )
        return result

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        sampling: Optional[SamplingParams] = None,
        speculation=None,
        **scheduler_kwargs,
    ) -> List[List[int]]:
        """Convenience: run ``prompts`` through a private continuous-
        batching scheduler to completion; returns generated tokens per
        prompt (prompt excluded). ``speculation``: a SpeculationConfig
        to decode speculatively (exact — greedy output is identical)."""
        from .scheduler import ContinuousBatchingScheduler

        sampling = sampling or SamplingParams()
        sched = ContinuousBatchingScheduler(self, **scheduler_kwargs)
        handles = [sched.submit(list(p), sampling, speculation=speculation) for p in prompts]
        while any(not h.done() for h in handles):
            if not sched.step():
                break
        return [h.result(timeout=0) for h in handles]

    def recompiles(self) -> Dict[str, int]:
        """Retraces beyond the first compile, per program."""
        return {k: v - 1 for k, v in self.trace_counts.items() if v > 1}

    def total_flops(self) -> float:
        """Cumulative useful model FLOPs across all step kinds."""
        return sum(self.flops_by_kind.values())

    @property
    def device_time_s(self) -> Dict[str, float]:
        """The pre-split total per kind, derived: dispatch + execute +
        readback — the same wall seconds the old conflated timer
        measured, kept for the flight/stats series' continuity."""
        return {k: sum(v.values()) for k, v in self.phase_time_s.items()}

    def total_device_time_s(self) -> float:
        return sum(self.device_time_s.values())

    def total_execute_time_s(self) -> float:
        """Cumulative device-EXECUTE seconds (dispatch-return to
        block_until_ready) — the MFU denominator after the ISSUE 12
        split; host arg prep and dispatch overhead no longer count as
        device time."""
        return sum(v["execute"] for v in self.phase_time_s.values())

    def mfu(self) -> float:
        """Serving model-FLOPs utilization: useful FLOPs over device
        EXECUTE seconds against the chip's peak for the cache dtype
        (definition changed by ISSUE 12 — previously the denominator
        included host arg prep, XLA dispatch, and readback; see README
        "Step anatomy" for the CPU-backend caveat). 0 before any step
        ran."""
        t = self.total_execute_time_s()
        if t <= 0:
            return 0.0
        return self.total_flops() / t / self.flops_model.peak_flops
