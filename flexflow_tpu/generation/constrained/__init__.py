"""Grammar-constrained decoding (ISSUE 18).

JSON-Schema / regex -> character DFA -> token DFA compiled once per
(grammar, vocabulary) and cached; a per-request MaskState the
scheduler advances during host bookkeeping; cached per-state mask rows
assembled into the fixed-shape additive bias the existing decode and
verify programs already stage. See README "Constrained decoding".
"""
from .automaton import CharDFA, compile_regex
from .errors import GrammarError, MaskAdvanceError, MaskDeadEndError
from .schema import schema_to_regex, validate_json
from .tokens import (
    NEG,
    GrammarCache,
    MaskState,
    TokenDFA,
    compile_response_format,
    decode_text,
    default_vocabulary,
    grammar_alphabet,
)

__all__ = [
    "CharDFA",
    "GrammarCache",
    "GrammarError",
    "MaskAdvanceError",
    "MaskDeadEndError",
    "MaskState",
    "NEG",
    "TokenDFA",
    "compile_regex",
    "compile_response_format",
    "decode_text",
    "default_vocabulary",
    "grammar_alphabet",
    "schema_to_regex",
    "validate_json",
]
