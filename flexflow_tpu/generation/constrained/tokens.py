"""Token-level grammar machinery: the DFA the scheduler actually drives.

`TokenDFA` lifts a character-level automaton (automaton.py) onto the
tokenizer vocabulary once per grammar: for every char-DFA state and
every vocabulary token, walk the token's characters; if the whole walk
survives, the token is a single edge. Liveness pruning then removes
every edge into a state that cannot reach acceptance — so a masked
sampler can never paint itself into a dead end; `dead()` below is
defensive, reachable only under injected faults.

`MaskState` is the per-request cursor. The scheduler advances it during
host bookkeeping (in the overlap pipeline that work hides under device
execution), and reads `mask_row()` — a cached `(vocab,)` float32 row of
0 / NEG — to assemble the fixed-shape `(batch, vocab)` additive bias
staged into the existing decode/verify programs. NEG is a large finite
negative, not -inf: softmax still zeroes banned tokens, argmax still
ignores them, but the engine's isfinite ok-gate (NaN blame) keeps
working.

EOS is not a grammar character: it is allowed exactly at accepting
states and consuming it marks the stream done. Crash-replay rebuilds a
`MaskState` by re-advancing over the journaled emitted tokens
(`TokenDFA.state_after`), which is why `advance` is deliberately
deterministic and why every real advance passes through the
`generation.mask_advance` fault site.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from ...runtime import faults
from .automaton import CharDFA, compile_regex
from .errors import GrammarError, MaskAdvanceError, MaskDeadEndError
from .schema import schema_to_regex

# finite so masked logits survive the engine's isfinite ok-gate; far
# below any real logit so softmax mass and argmax never land on it
NEG = -1.0e30

# single-character tokens first (ids are stable and dense), then the
# JSON keywords/punctuation runs real tokenizers merge, then filler
# pairs — a deterministic stand-in vocabulary for a repo whose prompts
# are raw token-id lists with no tokenizer
_SINGLES = '{}[]":,-. 0123456789abcdefghijklmnopqrstuvwxyz_'
_MULTIS = ("true", "false", "null", '": ', '", "', '":')


def default_vocabulary(vocab_size: int) -> Tuple[str, ...]:
    """Deterministic token-id -> string table of exactly ``vocab_size``
    entries (the engine's logits index straight into it)."""
    toks: List[str] = list(_SINGLES) + list(_MULTIS)
    if len(toks) < vocab_size:
        filler = ("".join(p) for p in itertools.product(_SINGLES[10:], repeat=2))
        toks.extend(itertools.islice(filler, vocab_size - len(toks)))
    return tuple(toks[:vocab_size])


def decode_text(vocab: Sequence[str], ids: Sequence[int], eos_id: int) -> str:
    """Join a token-id stream back to text, skipping EOS."""
    return "".join(vocab[int(i)] for i in ids if int(i) != eos_id)


class TokenDFA:
    """A grammar compiled against one vocabulary. Immutable and shared:
    every request under the same grammar holds the same instance."""

    __slots__ = (
        "char_dfa",
        "vocab_size",
        "spec",
        "schema",
        "_step",
        "_allowed",
        "_accepting",
        "_mask_rows",
        "_rows_lock",
    )

    def __init__(self, char_dfa: CharDFA, vocabulary: Sequence[str],
                 spec: Optional[dict] = None, schema: Optional[dict] = None):
        self.char_dfa = char_dfa
        self.vocab_size = len(vocabulary)
        self.spec = spec
        self.schema = schema
        # raw token edges: for each char-state, token id -> target state
        raw: List[Dict[int, int]] = [{} for _ in range(char_dfa.n_states)]
        for tok_id, text in enumerate(vocabulary):
            if not text:
                continue
            for s in range(char_dfa.n_states):
                t: Optional[int] = s
                for c in text:
                    t = char_dfa.step(t, c)
                    if t is None:
                        break
                if t is not None:
                    raw[s][tok_id] = t
        # liveness: states that can reach acceptance over TOKEN edges
        # (char-level reachability is not enough — a state whose only
        # continuations cross token boundaries no vocabulary token
        # spans is a trap). Backward closure from accepting states.
        reverse: List[List[int]] = [[] for _ in range(char_dfa.n_states)]
        for s, edges in enumerate(raw):
            for t in edges.values():
                reverse[t].append(s)
        live = set(char_dfa.accepting)
        work = list(live)
        while work:
            s = work.pop()
            for p in reverse[s]:
                if p not in live:
                    live.add(p)
                    work.append(p)
        if char_dfa.start not in live:
            raise GrammarError(
                f"grammar {char_dfa.pattern!r} matches nothing this "
                f"vocabulary can emit"
            )
        # pruned edges: only transitions into live states survive, so a
        # masked sampler can never enter a dead end
        self._step: Tuple[Dict[int, int], ...] = tuple(
            {tok: tgt for tok, tgt in edges.items() if tgt in live}
            for edges in raw
        )
        self._allowed: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(sorted(edges)) for edges in self._step
        )
        self._accepting = frozenset(char_dfa.accepting)
        self._mask_rows: Dict[Tuple[int, int], np.ndarray] = {}
        self._rows_lock = threading.Lock()

    # ------------------------------------------------------------ queries
    @property
    def start(self) -> int:
        return self.char_dfa.start

    def step(self, state: int, token: int) -> Optional[int]:
        """Pruned transition: None means the token is banned here."""
        return self._step[state].get(int(token))

    def allowed(self, state: int) -> Tuple[int, ...]:
        return self._allowed[state]

    def accepting(self, state: int) -> bool:
        return state in self._accepting

    def exhausted(self, state: int) -> bool:
        """Accepting with no live continuation: only EOS remains."""
        return state in self._accepting and not self._allowed[state]

    def dead(self, state: int) -> bool:
        """No continuation and not accepting. Pruning makes this
        unreachable by sampling; kept as the defensive backstop."""
        return state not in self._accepting and not self._allowed[state]

    def mask_row(self, state: int, eos_id: Optional[int]) -> np.ndarray:
        """Cached additive-bias row: 0 for allowed tokens, NEG
        elsewhere; EOS (when the request has one) allowed exactly at
        accepting states."""
        key = (state, eos_id)
        row = self._mask_rows.get(key)
        if row is None:
            with self._rows_lock:
                row = self._mask_rows.get(key)
                if row is None:
                    row = np.full((self.vocab_size,), NEG, dtype=np.float32)
                    allowed = self._allowed[state]
                    if allowed:
                        row[np.asarray(allowed, dtype=np.int64)] = 0.0
                    if eos_id is not None:
                        row[eos_id] = 0.0 if state in self._accepting else NEG
                    row.setflags(write=False)
                    self._mask_rows[key] = row
        return row

    def state_after(self, tokens: Sequence[int], eos_id: Optional[int]) -> "MaskState":
        """Replay: rebuild the cursor by re-advancing over already
        emitted tokens (journal recovery, preempt-recompute, adopt)."""
        ms = MaskState(self)
        for t in tokens:
            ms.advance(int(t), eos_id)
        return ms


class MaskState:
    """Per-request automaton cursor, advanced during host bookkeeping."""

    __slots__ = ("dfa", "state", "done", "n_advanced")

    def __init__(self, dfa: TokenDFA):
        self.dfa = dfa
        self.state = dfa.start
        self.done = False
        self.n_advanced = 0

    def advance(self, token: int, eos_id: Optional[int]) -> None:
        """Consume one emitted token. Raises :class:`MaskAdvanceError`
        if the automaton refuses it (replay divergence or an injected
        ``generation.mask_advance`` fault) and :class:`MaskDeadEndError`
        from the landing state's emptiness check."""
        faults.inject(faults.GENERATION_MASK_ADVANCE, (self.state, int(token)))
        if self.done:
            raise MaskAdvanceError(
                f"token {token} after grammar completed (state {self.state})"
            )
        if eos_id is not None and int(token) == eos_id:
            if not self.dfa.accepting(self.state):
                raise MaskAdvanceError(
                    f"EOS at non-accepting grammar state {self.state}"
                )
            self.done = True
            self.n_advanced += 1
            return
        nxt = self.dfa.step(self.state, token)
        if nxt is None:
            raise MaskAdvanceError(
                f"grammar state {self.state} does not allow token {token}"
            )
        self.state = nxt
        self.n_advanced += 1
        if self.dfa.dead(self.state):
            raise MaskDeadEndError(
                f"grammar state {self.state} has an empty mask"
            )

    def mask_row(self, eos_id: Optional[int]) -> np.ndarray:
        return self.dfa.mask_row(self.state, eos_id)

    def exhausted(self) -> bool:
        return self.done or self.dfa.exhausted(self.state)

    def dead_end(self) -> bool:
        return (not self.done) and self.dfa.dead(self.state)

    def filter_draft(self, draft: Sequence[int], eos_id: Optional[int]) -> List[int]:
        """Longest draft prefix the grammar can accept, WITHOUT
        advancing this cursor and without touching the fault site (only
        real emissions count toward injected-fault triggers). The
        verify window is masked identically for draft and target, so a
        grammar-banned draft token would be rejected anyway — trimming
        it here just avoids wasting verify slots."""
        out: List[int] = []
        s = self.state
        if self.done:
            return out
        for t in draft:
            t = int(t)
            if eos_id is not None and t == eos_id:
                if self.dfa.accepting(s):
                    out.append(t)
                break
            nxt = self.dfa.step(s, t)
            if nxt is None:
                break
            out.append(t)
            s = nxt
        return out

    def states_along(self, tokens: Sequence[int], eos_id: Optional[int]) -> List[int]:
        """Grammar states after each token of an (already filtered)
        prefix walk — used to build per-position verify mask rows.
        Non-mutating; a token the grammar refuses stops the walk."""
        states: List[int] = []
        s = self.state
        for t in tokens:
            t = int(t)
            if (eos_id is not None and t == eos_id) or self.dfa.step(s, t) is None:
                break
            s = self.dfa.step(s, t)
            states.append(s)
        return states


# ------------------------------------------------------------- front end
def grammar_alphabet(vocabulary: Sequence[str]) -> FrozenSet[str]:
    """Every character any vocabulary token can emit."""
    return frozenset(c for text in vocabulary for c in text)


def compile_response_format(spec: dict, vocabulary: Sequence[str]) -> TokenDFA:
    """``response_format`` wire spec -> compiled grammar.

    Accepted shapes (anything else is a :class:`GrammarError`, which
    the HTTP layer maps to a 400):

      {"type": "json_schema", "json_schema": {...}}
      {"type": "regex", "pattern": "..."}
    """
    if not isinstance(spec, dict):
        raise GrammarError(
            f"response_format must be an object, got {type(spec).__name__}"
        )
    kind = spec.get("type")
    schema: Optional[dict] = None
    if kind == "json_schema":
        schema = spec.get("json_schema")
        if not isinstance(schema, dict):
            raise GrammarError("response_format.json_schema must be an object")
        pattern = schema_to_regex(schema)
    elif kind == "regex":
        pattern = spec.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise GrammarError("response_format.pattern must be a non-empty string")
    else:
        raise GrammarError(
            f"response_format.type must be 'json_schema' or 'regex', "
            f"got {kind!r}"
        )
    char_dfa = compile_regex(pattern, grammar_alphabet(vocabulary))
    return TokenDFA(char_dfa, vocabulary, spec=spec, schema=schema)


def _cache_key(spec: dict) -> str:
    try:
        return json.dumps(spec, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError) as e:
        raise GrammarError(f"response_format is not JSON-serializable: {e}") from None


class GrammarCache:
    """Per-model compile-once cache keyed by the canonical spec JSON.

    ``stats`` is duck-typed (anything with ``incr(field, n)``); the
    serving layer passes the scheduler's ConstrainedStats so cache
    hits/misses and compile seconds surface on /metrics."""

    def __init__(self, vocabulary: Sequence[str], stats=None):
        self.vocabulary = tuple(vocabulary)
        self.stats = stats
        self._lock = threading.Lock()
        self._grammars: Dict[str, TokenDFA] = {}

    def __len__(self) -> int:
        return len(self._grammars)

    def get(self, spec: dict) -> TokenDFA:
        key = _cache_key(spec)
        with self._lock:
            hit = self._grammars.get(key)
        if hit is not None:
            if self.stats is not None:
                self.stats.incr("grammar_cache_hits")
            return hit
        # compile outside the lock: grammar compilation is the slow
        # path and must not stall concurrent submits on other grammars
        faults.inject(faults.GENERATION_MASK_BUILD, key)
        t0 = time.perf_counter()
        grammar = compile_response_format(spec, self.vocabulary)
        dt = time.perf_counter() - t0
        if self.stats is not None:
            self.stats.incr("grammar_cache_misses")
            self.stats.incr("grammar_compile_seconds", dt)
        with self._lock:
            return self._grammars.setdefault(key, grammar)
