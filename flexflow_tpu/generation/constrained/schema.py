"""JSON-Schema -> regex, plus the matching (dependency-free) validator.

The structured-output subset that tool-calling workloads actually use,
compiled to the regex dialect in automaton.py:

  {"type": "object", "properties": {...}, "required": [...]}
  {"type": "string", "maxLength": n, "pattern"?: safe literal class}
  {"type": "integer"} / {"type": "number"}
  {"type": "boolean"} / {"type": "null"}
  {"type": "array", "items": ..., "minItems": m, "maxItems": n}
  {"enum": [...]} / {"const": ...}

Canonical emission: objects serialize EVERY declared property in
declaration order with no whitespace — the standard trick (Outlines,
XGrammar) that turns JSON generation into a regular language. Every
quantifier is bounded (string/array caps below), so a well-budgeted
request always reaches the grammar's accepting state before max_new
truncates it mid-object.

``validate_json`` implements the same subset semantics the compiler
emits, so genbench/chaoscheck can assert "every constrained stream
parses AND validates" without a jsonschema dependency.
"""
from __future__ import annotations

import json
from typing import Dict, List

from .errors import GrammarError

# bounded-by-construction caps: a grammar with an unbounded quantifier
# could stream past any token budget and end truncated (= invalid JSON)
DEFAULT_MAX_STRING = 16
DEFAULT_MAX_ITEMS = 4
MAX_INT_DIGITS = 9

# characters a generated string value may contain: no quote, no
# backslash, no control chars — keeps the value regex escape-free
STRING_CHARS = "a-z0-9_ \\-"

_REGEX_SPECIALS = set("\\.[](){}|*+?")


def _esc(text: str) -> str:
    """Escape a literal for the automaton.py regex dialect."""
    return "".join(("\\" + c) if c in _REGEX_SPECIALS else c for c in text)


def schema_to_regex(schema: Dict) -> str:
    """Compile a JSON-Schema subset to a full-match regex. Raises
    :class:`GrammarError` on anything outside the subset."""
    if not isinstance(schema, dict):
        raise GrammarError(f"schema must be an object, got {type(schema).__name__}")
    if "enum" in schema:
        opts = schema["enum"]
        if not isinstance(opts, list) or not opts:
            raise GrammarError("enum must be a non-empty list")
        return "(" + "|".join(_esc(json.dumps(v, separators=(",", ":"))) for v in opts) + ")"
    if "const" in schema:
        return _esc(json.dumps(schema["const"], separators=(",", ":")))
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict) or not props:
            raise GrammarError("object schema needs non-empty 'properties'")
        parts = []
        for name, sub in props.items():
            parts.append(_esc(json.dumps(str(name))) + ":" + schema_to_regex(sub))
        return "\\{" + ",".join(parts) + "\\}"
    if t == "string":
        hi = int(schema.get("maxLength", DEFAULT_MAX_STRING))
        lo = int(schema.get("minLength", 0))
        if lo < 0 or hi < lo:
            raise GrammarError(f"bad string bounds [{lo}, {hi}]")
        return f'"[{STRING_CHARS}]{{{lo},{hi}}}"'
    if t == "integer":
        return f"(-?(0|[1-9][0-9]{{0,{MAX_INT_DIGITS - 1}}}))"
    if t == "number":
        return f"(-?(0|[1-9][0-9]{{0,{MAX_INT_DIGITS - 1}}})(\\.[0-9]{{1,6}})?)"
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = schema_to_regex(schema.get("items", {"type": "integer"}))
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", DEFAULT_MAX_ITEMS))
        if lo < 0 or hi < lo:
            raise GrammarError(f"bad array bounds [{lo}, {hi}]")
        if hi == 0:
            return "\\[\\]"
        body = f"{item}(,{item}){{{max(0, lo - 1)},{hi - 1}}}"
        if lo == 0:
            return f"\\[({body})?\\]"
        return f"\\[{body}\\]"
    raise GrammarError(f"unsupported schema: {json.dumps(schema)[:120]}")


# ------------------------------------------------------------- validation
def validate_json(text: str, schema: Dict) -> List[str]:
    """Validate ``text`` against the schema subset. Returns a list of
    problems — empty means valid (parses as JSON and conforms)."""
    try:
        doc = json.loads(text)
    except Exception as e:
        return [f"not valid JSON: {e}"]
    return _check(doc, schema, "$")


def _check(doc, schema: Dict, path: str) -> List[str]:
    if "enum" in schema:
        return [] if doc in schema["enum"] else [f"{path}: {doc!r} not in enum"]
    if "const" in schema:
        return [] if doc == schema["const"] else [f"{path}: {doc!r} != const"]
    t = schema.get("type")
    if t == "object":
        if not isinstance(doc, dict):
            return [f"{path}: expected object"]
        probs = []
        props = schema.get("properties", {})
        for name in schema.get("required", list(props)):
            if name not in doc:
                probs.append(f"{path}.{name}: missing required property")
        for name, val in doc.items():
            if name not in props:
                probs.append(f"{path}.{name}: unexpected property")
            else:
                probs.extend(_check(val, props[name], f"{path}.{name}"))
        return probs
    if t == "string":
        if not isinstance(doc, str):
            return [f"{path}: expected string"]
        hi = int(schema.get("maxLength", DEFAULT_MAX_STRING))
        if len(doc) > hi or len(doc) < int(schema.get("minLength", 0)):
            return [f"{path}: string length {len(doc)} out of bounds"]
        return []
    if t == "integer":
        return [] if isinstance(doc, int) and not isinstance(doc, bool) else [
            f"{path}: expected integer"
        ]
    if t == "number":
        ok = isinstance(doc, (int, float)) and not isinstance(doc, bool)
        return [] if ok else [f"{path}: expected number"]
    if t == "boolean":
        return [] if isinstance(doc, bool) else [f"{path}: expected boolean"]
    if t == "null":
        return [] if doc is None else [f"{path}: expected null"]
    if t == "array":
        if not isinstance(doc, list):
            return [f"{path}: expected array"]
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", DEFAULT_MAX_ITEMS))
        probs = []
        if not (lo <= len(doc) <= hi):
            probs.append(f"{path}: {len(doc)} items out of [{lo}, {hi}]")
        item = schema.get("items", {"type": "integer"})
        for i, v in enumerate(doc):
            probs.extend(_check(v, item, f"{path}[{i}]"))
        return probs
    return [f"{path}: unsupported schema"]
