"""Typed errors for grammar-constrained decoding.

Two failure classes, with deliberately different blast radii:

- :class:`GrammarError` — the grammar itself is unusable (malformed
  regex, unsupported JSON-Schema construct, a pattern the tokenizer
  vocabulary cannot express). Raised at submit time, BEFORE the request
  ever touches the scheduler: the HTTP front end maps it to a 400 like
  any other bad request field.

- :class:`MaskAdvanceError` / :class:`MaskDeadEndError` — a live
  constrained stream can no longer continue (the automaton refused an
  emitted token on replay, or reached a non-accepting state with an
  empty mask). The scheduler wraps these in its standard
  PoisonedRequestError quarantine: the ONE request fails typed, the
  rest of the batch keeps streaming.
"""
from __future__ import annotations


class GrammarError(ValueError):
    """The grammar cannot be compiled against this vocabulary."""


class MaskAdvanceError(RuntimeError):
    """The token automaton could not advance over an emitted token.

    Unreachable when masks are applied (the sampler only sees allowed
    tokens) — this surfaces replay divergence or an injected
    ``generation.mask_advance`` fault."""


class MaskDeadEndError(RuntimeError):
    """A constrained stream reached a state with an empty mask.

    Compile-time liveness pruning removes every transition into a
    dead state, so this is defensive: it fires only under injected
    faults or a grammar/vocabulary mismatch."""
