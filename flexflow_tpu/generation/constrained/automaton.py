"""Regex -> character-level DFA, the grammar front-end's middle layer.

A deliberately small regex dialect — exactly what the JSON-Schema
compiler (schema.py) emits plus what structured-output patterns need:

  literals        a b c (any non-special char)
  escapes         \\. \\{ \\} \\[ \\] \\( \\) \\| \\* \\+ \\? \\\\ \\d \\w \\s
  classes         [a-z0-9_], [^abc] (ranges, negation)
  any             .  (every alphabet char except newline)
  grouping        ( ... )
  alternation     a|b
  repetition      * + ? {m} {m,} {m,n}

Matching is FULL-match (implicitly anchored both ends) — a constrained
stream is done when the automaton says the whole emission matches.

The pipeline is the textbook one: recursive-descent parse to an AST,
Thompson construction to an epsilon-NFA, subset construction to a DFA.
Negated classes and ``.`` need a closed alphabet; the caller passes the
set of characters its tokenizer vocabulary can ever produce (plus the
pattern's own literals), so the DFA is exact over everything the engine
can emit and silently rejects characters no token contains.

Pure host-side compile-time code: nothing here runs on the decode hot
path (the token-level DFA built on top caches per-state masks).
"""
from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .errors import GrammarError

# repetition bound guard: {m,n} expands structurally, and an absurd
# bound would compile forever before the first mask is ever built
MAX_REPEAT = 256

_SPECIALS = set("\\.[](){}|*+?")
_ESCAPE_CLASSES = {
    "d": frozenset("0123456789"),
    "w": frozenset(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
    ),
    "s": frozenset(" \t\n\r"),
}


# ------------------------------------------------------------------ parse
class _Parser:
    """Pattern string -> AST of ('lit', charset) / ('cat'|'alt', a, b) /
    ('star'|'plus'|'opt', a) / ('rep', a, lo, hi) / ('eps',) nodes."""

    def __init__(self, pattern: str, alphabet: FrozenSet[str]):
        self.p = pattern
        self.i = 0
        self.alphabet = alphabet

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise GrammarError(
                f"regex parse error at position {self.i}: {self.p[self.i:]!r}"
            )
        return node

    def _alt(self):
        node = self._cat()
        while self._peek() == "|":
            self.i += 1
            node = ("alt", node, self._cat())
        return node

    def _cat(self):
        parts = []
        while self._peek() not in ("", "|", ")"):
            parts.append(self._repeat())
        if not parts:
            return ("eps",)
        node = parts[0]
        for part in parts[1:]:
            node = ("cat", node, part)
        return node

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self.i += 1
                node = ("star", node)
            elif c == "+":
                self.i += 1
                node = ("plus", node)
            elif c == "?":
                self.i += 1
                node = ("opt", node)
            elif c == "{":
                node = ("rep", node, *self._bounds())
            else:
                return node

    def _bounds(self) -> Tuple[int, Optional[int]]:
        j = self.p.find("}", self.i)
        if j < 0:
            raise GrammarError(f"unterminated {{}} bound at {self.i}")
        body = self.p[self.i + 1 : j]
        self.i = j + 1
        try:
            if "," not in body:
                lo = hi = int(body)
            else:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = None if hi_s == "" else int(hi_s)
        except ValueError:
            raise GrammarError(f"bad repetition bound {{{body}}}") from None
        if lo < 0 or (hi is not None and hi < lo) or (hi or lo) > MAX_REPEAT:
            raise GrammarError(f"repetition bound {{{body}}} out of range")
        return lo, hi

    def _atom(self):
        c = self._peek()
        if c == "":
            raise GrammarError("unexpected end of pattern")
        if c == "(":
            self.i += 1
            node = self._alt()
            if self._peek() != ")":
                raise GrammarError(f"unbalanced '(' at {self.i}")
            self.i += 1
            return node
        if c == "[":
            return ("lit", self._char_class())
        if c == ".":
            self.i += 1
            return ("lit", frozenset(self.alphabet - {"\n"}))
        if c == "\\":
            return ("lit", self._escape())
        if c in _SPECIALS:
            raise GrammarError(f"unexpected {c!r} at position {self.i}")
        self.i += 1
        return ("lit", frozenset((c,)))

    def _escape(self) -> FrozenSet[str]:
        self.i += 1
        if self.i >= len(self.p):
            raise GrammarError("dangling escape at end of pattern")
        c = self.p[self.i]
        self.i += 1
        if c in _ESCAPE_CLASSES:
            return frozenset(_ESCAPE_CLASSES[c] & self.alphabet) or frozenset(
                _ESCAPE_CLASSES[c]
            )
        return frozenset((c,))

    def _char_class(self) -> FrozenSet[str]:
        self.i += 1  # past '['
        negate = self._peek() == "^"
        if negate:
            self.i += 1
        chars: Set[str] = set()
        first = True
        while True:
            c = self._peek()
            if c == "":
                raise GrammarError("unterminated character class")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            if c == "\\":
                chars |= self._escape()
                continue
            self.i += 1
            if self._peek() == "-" and self.i + 1 < len(self.p) and self.p[self.i + 1] != "]":
                hi = self.p[self.i + 1]
                self.i += 2
                if ord(hi) < ord(c):
                    raise GrammarError(f"bad class range {c}-{hi}")
                chars |= {chr(o) for o in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        if negate:
            return frozenset(self.alphabet - chars)
        return frozenset(chars)

    def _peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""


# ---------------------------------------------------------- NFA (Thompson)
class _NFA:
    """Epsilon-NFA fragments: state -> [(charset, target)], eps edges."""

    def __init__(self):
        self.edges: List[List[Tuple[FrozenSet[str], int]]] = []
        self.eps: List[Set[int]] = []

    def state(self) -> int:
        self.edges.append([])
        self.eps.append(set())
        return len(self.edges) - 1

    def build(self, node) -> Tuple[int, int]:
        """Return (entry, exit) of the fragment for ``node``."""
        kind = node[0]
        if kind == "eps":
            s = self.state()
            return s, s
        if kind == "lit":
            a, b = self.state(), self.state()
            self.edges[a].append((node[1], b))
            return a, b
        if kind == "cat":
            a0, a1 = self.build(node[1])
            b0, b1 = self.build(node[2])
            self.eps[a1].add(b0)
            return a0, b1
        if kind == "alt":
            a0, a1 = self.build(node[1])
            b0, b1 = self.build(node[2])
            s, t = self.state(), self.state()
            self.eps[s] |= {a0, b0}
            self.eps[a1].add(t)
            self.eps[b1].add(t)
            return s, t
        if kind == "star":
            a0, a1 = self.build(node[1])
            s = self.state()
            self.eps[s].add(a0)
            self.eps[a1].add(s)
            return s, s
        if kind == "plus":
            return self.build(("cat", node[1], ("star", node[1])))
        if kind == "opt":
            return self.build(("alt", node[1], ("eps",)))
        if kind == "rep":
            _, inner, lo, hi = node
            parts = [inner] * lo
            if hi is None:
                parts.append(("star", inner))
            else:
                parts.extend([("opt", inner)] * (hi - lo))
            if not parts:
                return self.build(("eps",))
            tree = parts[0]
            for p in parts[1:]:
                tree = ("cat", tree, p)
            return self.build(tree)
        raise GrammarError(f"unknown AST node {kind!r}")  # pragma: no cover

    def closure(self, states: Set[int]) -> FrozenSet[int]:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for t in self.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)


# ------------------------------------------------------------------ DFA
class CharDFA:
    """Deterministic character automaton: ``transitions[state][char]``
    -> next state, full-match accepted at ``accepting`` states."""

    __slots__ = ("transitions", "accepting", "start", "pattern")

    def __init__(self, transitions: List[Dict[str, int]], accepting: Set[int],
                 start: int, pattern: str):
        self.transitions = transitions
        self.accepting = accepting
        self.start = start
        self.pattern = pattern

    @property
    def n_states(self) -> int:
        return len(self.transitions)

    def step(self, state: int, char: str) -> Optional[int]:
        return self.transitions[state].get(char)

    def matches(self, text: str) -> bool:
        s: Optional[int] = self.start
        for c in text:
            s = self.transitions[s].get(c)
            if s is None:
                return False
        return s in self.accepting


def compile_regex(pattern: str, alphabet: FrozenSet[str]) -> CharDFA:
    """Compile ``pattern`` to a :class:`CharDFA` over ``alphabet`` (the
    closed character set — negated classes and ``.`` complement against
    it). Raises :class:`GrammarError` on any malformed pattern."""
    # the pattern's own literal chars always belong to the universe,
    # even when no vocabulary token contains them (they then simply
    # have no token-level transition)
    universe = frozenset(alphabet) | frozenset(
        c for c in pattern if c not in _SPECIALS
    )
    ast = _Parser(pattern, universe).parse()
    nfa = _NFA()
    entry, exit_ = nfa.build(ast)
    start = nfa.closure({entry})
    ids: Dict[FrozenSet[int], int] = {start: 0}
    transitions: List[Dict[str, int]] = [{}]
    accepting: Set[int] = set()
    work = [start]
    while work:
        cur = work.pop()
        cid = ids[cur]
        if exit_ in cur:
            accepting.add(cid)
        # chars with any outgoing edge from this state set
        moves: Dict[str, Set[int]] = {}
        for s in cur:
            for charset, t in nfa.edges[s]:
                for c in charset:
                    moves.setdefault(c, set()).add(t)
        for c, targets in moves.items():
            nxt = nfa.closure(targets)
            nid = ids.get(nxt)
            if nid is None:
                nid = len(transitions)
                ids[nxt] = nid
                transitions.append({})
                work.append(nxt)
            transitions[cid][c] = nid
    return CharDFA(transitions, accepting, 0, pattern)
