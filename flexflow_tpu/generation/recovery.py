"""Self-healing layer around the generation engine: journal-replay
recovery, crash supervision, and a step watchdog.

FlexFlow's Legion runtime survives individual task failures by
re-executing tasks from logged state; this module gives the generation
plane the same property. The key observation is that PR 2/3's
determinism work already made every stream *exactly replayable*: the
per-request sampling key is indexed by generated-token count, and
recompute-prefill (the preemption path) reproduces a stream token for
token. Crash recovery therefore needs no device-side checkpoint at all
— only the host-side **generation journal** (prompt, emitted tokens,
sampling/speculation state), which the scheduler keeps anyway.

Three cooperating pieces:

* :class:`GenerationJournal` — the per-request replay log. An entry is
  recorded at admission and discarded when the request leaves its slot
  (finish, fail, preempt, quarantine). After an engine teardown,
  ``drain()`` hands the supervisor everything needed to rebuild every
  live stream by recompute-replay.
* :class:`EngineSupervisor` — wraps every batched device step. On a
  step failure it (1) retries the step once (transient flukes beyond
  the RetryPolicy's retryable set), (2) decides whether the failure is
  *data-dependent* by bisecting the batch with subset probes — a
  request whose subset reproducibly crashes alone is **quarantined**
  (failed alone; the batch survives), (3) otherwise tears the engine
  down (``engine.reset()``: fresh KV cache + allocator, no recompiles)
  and journal-replays every live stream, under an exponential-backoff
  restart budget. NaN/inf logits never raise: the engine's in-jit
  ``isfinite`` reduce surfaces a per-slot blame vector and the poisoned
  request is quarantined directly (partial blame) or the engine is
  restarted (whole-batch blame = not data-dependent).
* :class:`StepWatchdog` — detects *stalled* device steps via a
  heartbeat the scheduler stamps around every device call. A step older
  than ``stall_timeout_s`` trips the per-model circuit breaker (so
  ``/v2/health/*`` and ``ModelReady`` stop reporting a hung device as
  ready), fails deadline-expired requests (handles only — resource
  cleanup stays with the loop thread), and marks the step stale so the
  supervisor discards its late result and restarts when (if) the device
  call finally returns.

Failure taxonomy (the README's failure-semantics table):

  transient device error   -> RetryPolicy retry, invisible
  hard step crash, once    -> supervisor step retry, invisible
  reproducible + isolable  -> quarantine (fails alone, original error)
  NaN logits, some slots   -> quarantine with PoisonedRequestError
  NaN logits, all slots    -> engine restart + journal replay
  crash, not isolable      -> engine restart + journal replay
  stalled step             -> watchdog trip -> restart + journal replay
  restart budget exhausted -> EngineFailedError + breaker OPEN; queued
                              requests are HELD (never failed with the
                              engine's internal error) and admitted
                              again if the breaker's half-open probe
                              succeeds after recovery_s. In fleet mode
                              (scheduler.failover_sink set by
                              serving/fleet.py) nothing is failed at
                              all: every live stream leaves this
                              scheduler and journal-replays onto a
                              surviving replica byte-exactly.

Chaos sites: ``generation.journal_replay`` fires at the top of every
restart, so tests can inject a *double fault* (a crash during recovery)
and watch it consume a second budget unit. All clocks and sleeps are
injectable; tests drive the watchdog with manual ``check()`` calls on a
virtual clock.
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import TYPE_CHECKING, Callable, List, Optional, Sequence

from ..runtime import faults
from ..runtime.backoff import backoff_delay
from ..serving.resilience import DeadlineExceededError

if TYPE_CHECKING:  # import cycle: scheduler imports this module
    from .scheduler import ContinuousBatchingScheduler, Request, _Running


class EngineFailedError(RuntimeError):
    """The generation engine is permanently gone (restart budget
    exhausted) — the typed error truly-lost requests receive instead of
    the engine's raw internal traceback. Raised for streams that have
    already emitted tokens: slot-resident ones and replay-requeued
    mid-stream ones (a blind resubmit could duplicate output). FRESH
    queued requests are HELD rather than failed and stay safe to
    resubmit by construction."""


class PoisonedRequestError(RuntimeError):
    """Structured quarantine error: THIS request's data produced
    non-finite logits and it was failed alone so the rest of the batch
    could keep generating. (A request quarantined by CRASH bisection is
    failed with the original device exception instead — the cause is
    more useful to its client than a wrapper.)"""

    def __init__(self, message: str, *, request_id: int, step: str, reason: str):
        super().__init__(message)
        self.request_id = request_id
        self.step = step  # "prefill" | "decode" | "verify"
        self.reason = reason  # "nan_logits"


class StalledStepError(RuntimeError):
    """A device step exceeded the watchdog's stall timeout; its (late)
    result was discarded and the engine restarted."""


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Supervisor tuning. ``max_restarts`` engine restarts are allowed
    per sliding ``budget_window_s`` (scheduler clock); each restart
    backs off exponentially with seeded jitter (runtime/backoff.py, the
    same curve as ElasticTrainer and RetryPolicy)."""

    max_restarts: int = 4
    budget_window_s: float = 300.0
    retry_step_once: bool = True
    backoff_base_s: float = 0.02
    backoff_max_s: float = 1.0
    backoff_jitter: float = 0.25
    seed: int = 0
    sleep: Callable[[float], None] = time.sleep


@dataclasses.dataclass(frozen=True)
class WatchdogPolicy:
    """Step-watchdog tuning. ``stall_timeout_s`` is measured on the
    scheduler's clock (virtual in tests); ``poll_s`` is the real-time
    cadence of the background thread started by ``scheduler.start()``."""

    enabled: bool = True
    stall_timeout_s: float = 30.0
    poll_s: float = 0.5


class JournalEntry:
    """One replayable stream: the request object itself carries the
    full replay state (original prompt, every emitted token, the seeded
    sampling key stream, speculation config + adaptive-k EMA)."""

    __slots__ = ("req", "admitted_seq")

    def __init__(self, req: "Request", admitted_seq: int):
        self.req = req
        self.admitted_seq = admitted_seq


class GenerationJournal:
    """Replay log of every slot-resident stream, keyed by request id.

    The journal deliberately holds no device state: replay is
    recompute-prefill of ``original_prompt + generated`` (the preempt
    path), which the per-token-count sampling keys make byte-exact.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def record(self, req: "Request", admitted_seq: int) -> None:
        with self._lock:
            self._entries[req.id] = JournalEntry(req, admitted_seq)

    def discard(self, req: "Request") -> None:
        with self._lock:
            self._entries.pop(req.id, None)

    def entries(self) -> List[JournalEntry]:
        """Live entries in admission order (FCFS replay order)."""
        with self._lock:
            return sorted(self._entries.values(), key=lambda e: e.admitted_seq)

    def drain(self) -> List[JournalEntry]:
        with self._lock:
            out = sorted(self._entries.values(), key=lambda e: e.admitted_seq)
            self._entries.clear()
            return out

    # Durable-serving hooks (ISSUE 19): no-ops here, overridden by
    # serving.durable.DurableJournal to mirror the journal into a
    # crash-safe WAL. The scheduler calls note_token from every
    # emitted-token bookkeeping path and flush_step once per scheduling
    # iteration (the group-commit boundary) — keeping both on the base
    # class means the scheduler never imports the serving tier.
    def note_token(self, req: "Request", token: int) -> None:
        pass

    def flush_step(self) -> None:
        pass


class EngineSupervisor:
    """Catches engine-loop step failures and turns them into the
    narrowest possible outcome: absorbed retry > quarantine > engine
    restart + journal replay > declared engine death."""

    def __init__(
        self,
        scheduler: "ContinuousBatchingScheduler",
        policy: Optional[RecoveryPolicy] = None,
    ):
        self.scheduler = scheduler
        self.policy = policy or RecoveryPolicy()
        self.stats = scheduler.recovery_stats
        self._rng = random.Random(f"supervisor|{self.policy.seed}")
        self._restart_times: List[float] = []
        self._consecutive = 0  # restarts since the last healthy step
        self._stall_lock = threading.Lock()
        # heartbeat seq the watchdog tripped on; the overlap pipeline's
        # consume arbitration (scheduler._consume_and_finish) and the
        # sequential run_step/resume_step ladders both pop it via
        # _consume_stall  # guarded-by: _stall_lock
        self._stalled_seq: Optional[int] = None  # guarded-by: _stall_lock
        self.failed = False  # restart budget exhausted; engine declared dead

    def note_engine_recovered(self) -> None:
        """A half-open probe succeeded against a declared-dead engine:
        service resumed, so the spent restart budget is forgiven — the
        next engine-level failure gets a full budget instead of an
        instant give-up inside the stale window."""
        self.failed = False
        self._restart_times.clear()
        self._consecutive = 0

    # ------------------------------------------------------------ watchdog
    def mark_stalled(self, seq: int) -> None:
        """Watchdog: the device call with heartbeat ``seq`` is stale;
        its result must be discarded."""
        with self._stall_lock:
            self._stalled_seq = max(self._stalled_seq or 0, seq)

    def _consume_stall(self, since_seq: int) -> bool:
        """True only when the flagged stall belongs to a device call
        issued after ``since_seq`` — i.e. one of the caller's own calls.
        A trip on some OTHER stamped section (an admission prefill's
        cold compile, a bisection probe, the recovery path itself) must
        not condemn a later healthy step: its result was already
        committed, the breaker is open either way, and a genuinely
        wedged device will re-trip on its next supervised step — while
        discarding healthy steps for it would burn restart budget on,
        say, a compile that merely exceeded the stall timeout."""
        with self._stall_lock:
            seq, self._stalled_seq = self._stalled_seq, None
            return seq is not None and seq > since_seq

    # ---------------------------------------------------------------- step
    def run_step(self, kind: str, step_fn, states: Sequence["_Running"], probe):
        """Run one batched device step under supervision.

        Returns the step's output, or None when the failure was fully
        handled here (quarantine or journal replay) — the scheduler must
        then skip its scatter phase; surviving streams either kept their
        slots or sit requeued for recompute-replay.
        """
        sched = self.scheduler
        seq0 = sched._hb_seq  # stalls flagged past this are OUR calls
        try:
            out = sched._device(step_fn)
        except Exception as e:
            # flight recorder: the failing step is now ON the ring, so
            # every downstream incident snapshot contains it
            sched.flight.record_event("step_failed", step=kind, error=repr(e)[:200])
            if self._consume_stall(seq0):
                self._restart_and_replay(e, kind)
                return None
            if getattr(sched.engine, "donate", False):
                # a donating engine's failed jit call consumed its cache
                # input buffers: retrying the same closure (and every
                # bisection probe) would re-pass deleted arrays and blame
                # innocent requests — reset + journal replay is the only
                # sound recovery (byte-exact; documented with donate_cache)
                self._restart_and_replay(e, kind)
                return None
            if not self.policy.retry_step_once:
                self._handle_double_failure(e, kind, states, probe)
                return None
            self.stats.incr("step_retries")
            sched.flight.record_event("step_retry", step=kind)
            try:
                out = sched._device(step_fn)
            except Exception as e2:
                sched.flight.record_event("step_failed", step=kind, error=repr(e2)[:200])
                if self._consume_stall(seq0):
                    self._restart_and_replay(e2, kind)
                    return None
                self._handle_double_failure(e2, kind, states, probe)
                return None
        if self._consume_stall(seq0):
            # the watchdog already tripped the breaker and reaped
            # deadline-expired handles; the step's late result is stale
            # (the engine may have wedged mid-write), so replay instead
            self._restart_and_replay(
                StalledStepError(f"{kind} step exceeded the watchdog stall timeout"),
                kind,
            )
            return None
        self._consecutive = 0  # healthy step: backoff curve restarts
        return out

    def resume_step(self, kind, first_error, step_fn, states, probe, since_seq):
        """A PIPELINED (async-dispatched) step failed. Resume the
        sequential supervision ladder from the point just after a
        synchronous step's first failure, so the outcome AND the
        accounting match ``run_step`` exactly: a retryable error is
        re-run invisibly (the treatment RetryPolicy.run would have
        given it inside the same ``_device`` call), a hard error pays
        one breaker failure, then the supervised retry -> bisect ->
        restart ladder. ``since_seq`` scopes stall flags to the failed
        chain's own device calls (the overlap frontier's ``seq0``)."""
        sched = self.scheduler
        if sched.retry.would_retry(first_error):
            return self.run_step(kind, step_fn, states, probe)
        sched.flight.record_event(
            "step_failed", step=kind, error=repr(first_error)[:200]
        )
        sched.breaker.record_failure()
        if self._consume_stall(since_seq):
            self._restart_and_replay(first_error, kind)
            return None
        if getattr(sched.engine, "donate", False):
            # unreachable from _pipeline_failure (it checks donate first)
            # but kept for any future caller: see run_step
            self._restart_and_replay(first_error, kind)
            return None
        if not self.policy.retry_step_once:
            self._handle_double_failure(first_error, kind, states, probe)
            return None
        self.stats.incr("step_retries")
        sched.flight.record_event("step_retry", step=kind)
        try:
            out = sched._device(step_fn)
        except Exception as e2:
            sched.flight.record_event("step_failed", step=kind, error=repr(e2)[:200])
            if self._consume_stall(since_seq):
                self._restart_and_replay(e2, kind)
                return None
            self._handle_double_failure(e2, kind, states, probe)
            return None
        if self._consume_stall(since_seq):
            self._restart_and_replay(
                StalledStepError(f"{kind} step exceeded the watchdog stall timeout"),
                kind,
            )
            return None
        self._consecutive = 0
        return out

    def _handle_double_failure(self, err, kind, states, probe) -> None:
        """The step failed twice. Bisect with subset probes to decide
        data-dependence: a strict subset that reproducibly fails alone
        is quarantined (batch-of-one keeps PR 1's fail-the-request
        semantics — with one request there is nothing to bisect
        against); anything else is an engine-level fault."""
        blamed = self._bisect(list(states), probe)
        if blamed and (len(blamed) < len(states) or len(states) == 1):
            for s in blamed:
                self.scheduler._quarantine(s, err)
            return
        self._restart_and_replay(err, kind)

    def _bisect(self, states, probe) -> List["_Running"]:
        """Probe subsets of the failed batch (outputs discarded; cache
        writes are idempotent replays of the same step) to isolate
        requests that crash on their own. Probes bypass retry/breaker:
        an expected crash during blame assignment is not device health
        signal."""

        def failing(sub) -> bool:
            try:
                probe(sub)
            except Exception:
                return True
            return False

        def rec(sub):
            if not failing(sub):
                return []
            if len(sub) == 1:
                return list(sub)
            mid = len(sub) // 2
            return rec(sub[:mid]) + rec(sub[mid:])

        return rec(list(states))

    # ------------------------------------------------------------- restart
    def handle_engine_nan(self, kind: str) -> None:
        """Every live slot's logits went non-finite at once: nothing to
        pin on one request (bad params / numeric collapse / device
        fault), so tear down and replay — the cache rezero also clears
        any NaN the batch wrote."""
        self._restart_and_replay(
            RuntimeError(f"non-finite logits across all slots at {kind} step"), kind
        )

    def _restart_and_replay(self, cause: BaseException, kind: str) -> None:
        """Tear the engine down and rebuild every journaled stream by
        recompute-replay, with backoff and a sliding restart budget. A
        failure *during* recovery (the journal_replay chaos site, or a
        still-broken device) is a double fault: it consumes another
        budget unit and backs off further."""
        sched = self.scheduler
        pol = self.policy
        # the overlap frontier's in-flight step (if any) is chained on
        # state this reset is about to tear down: discard it before
        # touching the engine (idempotent; pipeline callers already did)
        sched._discard_frontier()
        # postmortem FIRST: the snapshot must show the engine's last
        # steps (including the step_failed marker) before reset rebuilds
        # the world; attached to the cause so a later give-up's
        # EngineFailedError still carries the first crash's context
        snap = sched.flight.incident(
            "restart", step=kind, error=repr(cause)[:200],
            journal_entries=len(sched.journal),
        )
        if getattr(cause, "flight_snapshot", None) is None:
            try:
                cause.flight_snapshot = snap
            except Exception:
                pass
        while True:
            now = sched.clock()
            self._restart_times = [
                t for t in self._restart_times if now - t <= pol.budget_window_s
            ]
            if len(self._restart_times) >= pol.max_restarts:
                self._give_up(cause)
                return
            self._restart_times.append(now)
            self._consecutive += 1
            pol.sleep(
                backoff_delay(
                    self._consecutive,
                    base_s=pol.backoff_base_s,
                    max_s=pol.backoff_max_s,
                    jitter=pol.backoff_jitter,
                    rng=self._rng,
                )
            )
            try:
                # stamped: a reset that wedges on a dead device must stay
                # visible to the watchdog (deadline reaping keeps running
                # and a fresh trip is flagged for this section's seq)
                with sched._stamped():
                    faults.inject(faults.GENERATION_JOURNAL_REPLAY, sched.journal.entries())
                    sched.engine.reset()
                    sched._rebuild_from_journal()
            except Exception as e:  # double fault: burn another budget unit
                cause = e
                sched.flight.record_event("double_fault", error=repr(e)[:200])
                continue
            self.stats.incr("recoveries")
            sched.flight.record_event(
                "recovery", step=kind, consecutive=self._consecutive
            )
            # recovery proved the device responsive; close the breaker a
            # watchdog trip (or the crash's recorded failures) opened so
            # admission resumes immediately instead of after recovery_s
            sched.breaker.record_success()
            return

    def _give_up(self, cause: BaseException) -> None:
        self.failed = True
        self.stats.incr("engine_failures")
        err = EngineFailedError(
            f"generation engine failed permanently: {self.policy.max_restarts} "
            f"restarts exhausted within {self.policy.budget_window_s}s "
            f"(last cause: {cause!r})"
        )
        err.__cause__ = cause
        err.flight_snapshot = self.scheduler.flight.incident(
            "engine_failed", error=repr(cause)[:200]
        )
        self.scheduler._fail_running_engine_dead(err)
        # queued-but-never-admitted requests are NOT failed: they hold no
        # slot and streamed nothing, so they wait out the outage behind
        # the breaker (admitted by its half-open probe if the device
        # comes back) or expire at their own deadlines
        self.scheduler.breaker.trip()


class StepWatchdog:
    """Detects device steps that neither return nor raise.

    The scheduler stamps ``_heartbeat = (seq, started_at)`` around every
    device call; ``check()`` compares its age against the stall timeout
    on the scheduler's clock. Tripping is per-step (one trip per seq):
    it opens the circuit breaker, marks the supervisor so the step's
    late result is discarded in favor of a journal-replay restart, and
    fails deadline-expired requests' *handles* (slots/blocks stay with
    the loop thread — the only thread allowed to touch them).

    Overlap pipeline stamping (ISSUE 13): an async-dispatched step
    stamps its heartbeat at DISPATCH and is re-stamped when its
    predecessor COMPLETES — the moment it actually starts executing on
    the serial device queue — so each step's heartbeat age measures its
    OWN device time. Without the completion re-stamp, a one-step-deep
    pipeline at long execute times would accumulate dispatch-to-consume
    windows spanning two steps and be misread as a wedged loop
    (regression-tested on a virtual clock in tests/test_overlap.py).
    A consume that never returns leaves the stamp aging until the trip
    fires, exactly like a wedged synchronous call."""

    def __init__(
        self,
        scheduler: "ContinuousBatchingScheduler",
        policy: Optional[WatchdogPolicy] = None,
    ):
        self.scheduler = scheduler
        self.policy = policy or WatchdogPolicy()
        self.stats = scheduler.recovery_stats
        self._last_tripped_seq = -1
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- checks
    def check(self) -> bool:
        """One inspection (tests call this directly on virtual clocks).
        Returns True if a stall was newly detected."""
        sched = self.scheduler
        hb = sched._heartbeat  # (seq, started_at) or None; atomic read
        if hb is None:
            return False
        seq, started = hb
        if sched.clock() - started < self.policy.stall_timeout_s:
            return False
        tripped = seq != self._last_tripped_seq
        if tripped:
            self._last_tripped_seq = seq
            self.stats.incr("watchdog_trips")
            sched.flight.record_event(
                "watchdog_trip", heartbeat_seq=seq,
                stalled_s=sched.clock() - started,
            )
            sched.breaker.trip()  # health stops lying about a hung device
            sched.supervisor.mark_stalled(seq)
        # while the device is wedged the loop thread cannot expire
        # anything, so deadline enforcement moves here (handles only)
        self._reap_expired()
        return tripped

    def _reap_expired(self) -> None:
        sched = self.scheduler
        now = sched.clock()
        with sched._lock:
            queued = list(sched._queue)
        # _running is loop-thread-private; this snapshot is a single
        # C-level copy (GIL-atomic), safe even if the wedged step
        # returns and the loop resumes mutating at this exact moment
        running = [s.req for s in list(sched._running.values())]
        admitting = sched._admitting  # popped for a (possibly wedged) prefill
        for req in queued + running + ([admitting] if admitting else []):
            if (
                req.deadline is not None
                and now >= req.deadline
                and req.handle._fail(
                    DeadlineExceededError("deadline expired during a stalled engine step")
                )
            ):
                sched.stats.incr("expired")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if not self.policy.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.policy.poll_s):
            try:
                self.check()
            except Exception:
                # the watchdog must never die of a transient inspection
                # race; missing one poll is strictly better than losing
                # stall detection for the process lifetime
                pass
