"""Autoregressive generation engine: KV-cache decode, prefill/decode
split, and continuous batching.

The reference's inference story is a one-shot compiled graph behind a
Triton backend (SURVEY §2.9) — no token generation at all. This package
is the TPU-native serving answer for decoder transformers:

* :mod:`cache` — a preallocated, block-structured KV cache (vLLM /
  PagedAttention-style block tables, SOSP'23) sized against a memory
  budget, with a host-side block allocator;
* :mod:`decoder` — a pure-JAX decoder-only transformer (pre-LN, causal)
  whose full-context forward and incremental cached decode provably
  produce the same logits;
* :mod:`engine` — prefill/decode split with shape-bucketed, separately
  jitted steps (steady-state decode never recompiles) and greedy /
  temperature / top-k sampling;
* :mod:`scheduler` — Orca-style iteration-level continuous batching
  (OSDI'22): requests join the running batch at any decode step,
  finished sequences free their cache blocks immediately, FCFS
  admission is cache-capacity aware, and cache exhaustion preempts by
  recompute.

Serving integration lives in :mod:`flexflow_tpu.serving.generation`
(`GenerationModel`), wired through the same deadline / backpressure /
circuit-breaker paths as `InferenceModel`, with per-token streaming over
HTTP (SSE) and gRPC.
"""
from .cache import BlockAllocator, CacheConfig, KVCache
from .decoder import DecoderParams, forward_full, init_decoder_params
from .engine import GenerationEngine, SamplingParams
from .scheduler import (
    ContinuousBatchingScheduler,
    GenerationHandle,
    Request,
)

__all__ = [
    "BlockAllocator",
    "CacheConfig",
    "ContinuousBatchingScheduler",
    "DecoderParams",
    "GenerationEngine",
    "GenerationHandle",
    "KVCache",
    "Request",
    "SamplingParams",
    "forward_full",
    "init_decoder_params",
]
