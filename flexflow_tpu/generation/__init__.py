"""Autoregressive generation engine: KV-cache decode, prefill/decode
split, and continuous batching.

The reference's inference story is a one-shot compiled graph behind a
Triton backend (SURVEY §2.9) — no token generation at all. This package
is the TPU-native serving answer for decoder transformers:

* :mod:`cache` — a preallocated, block-structured KV cache (vLLM /
  PagedAttention-style block tables, SOSP'23) sized against a memory
  budget, with a host-side block allocator;
* :mod:`decoder` — a pure-JAX decoder-only transformer (pre-LN, causal)
  whose full-context forward and incremental cached decode provably
  produce the same logits;
* :mod:`engine` — prefill/decode split with shape-bucketed, separately
  jitted steps (steady-state decode never recompiles) and greedy /
  temperature / top-k sampling;
* :mod:`scheduler` — Orca-style iteration-level continuous batching
  (OSDI'22): requests join the running batch at any decode step,
  finished sequences free their cache blocks immediately, FCFS
  admission is cache-capacity aware, and cache exhaustion preempts by
  recompute.

* :mod:`prefix` — cross-request prefix caching: a radix index over
  token-block content with refcounted copy-on-write blocks and a
  host-RAM offload tier (swap-in vs recompute decided on the cost-model
  roofline, CRC-verified, chaos-covered). Admission matches the longest
  cached prefix and prefills only the suffix; streams are byte-identical
  with caching on or off.

* :mod:`speculative` — speculative decoding (SpecInfer / Leviathan et
  al.): model-free n-gram and small-draft-model drafters, ONE
  fixed-shape batched verification step over the block cache
  (chunked-append attention), exact greedy acceptance and
  distribution-preserving rejection sampling, with per-request
  adaptive k driven by the scheduler.

* :mod:`recovery` — the self-healing layer: per-request generation
  journal (exact recompute-replay of any stream after an engine
  teardown), an engine supervisor (step retry, poisoned-request
  quarantine via NaN blame vectors + crash bisection, crash-restart
  budget with exponential backoff), and a step watchdog that detects
  stalled device steps and trips the circuit breaker so health
  endpoints stop lying about a hung device.

Serving integration lives in :mod:`flexflow_tpu.serving.generation`
(`GenerationModel`), wired through the same deadline / backpressure /
circuit-breaker paths as `InferenceModel`, with per-token streaming over
HTTP (SSE) and gRPC.
"""
from .cache import BlockAllocator, CacheConfig, KVCache
from .decoder import DecoderParams, forward_full, init_decoder_params
from .engine import GenerationEngine, SamplingParams
from .prefix import PrefixCache, PrefixEntry
from .sharding import ServingLayout
from .recovery import (
    EngineFailedError,
    EngineSupervisor,
    GenerationJournal,
    PoisonedRequestError,
    RecoveryPolicy,
    StalledStepError,
    StepWatchdog,
    WatchdogPolicy,
)
from .scheduler import (
    ContinuousBatchingScheduler,
    GenerationHandle,
    Request,
)
from .speculative import (
    Drafter,
    DraftModelDrafter,
    NgramDrafter,
    SpeculationConfig,
)

__all__ = [
    "BlockAllocator",
    "CacheConfig",
    "ContinuousBatchingScheduler",
    "DecoderParams",
    "Drafter",
    "DraftModelDrafter",
    "EngineFailedError",
    "EngineSupervisor",
    "GenerationEngine",
    "GenerationHandle",
    "GenerationJournal",
    "KVCache",
    "NgramDrafter",
    "PoisonedRequestError",
    "PrefixCache",
    "PrefixEntry",
    "RecoveryPolicy",
    "Request",
    "SamplingParams",
    "SpeculationConfig",
    "StalledStepError",
    "StepWatchdog",
    "WatchdogPolicy",
    "forward_full",
    "init_decoder_params",
]
