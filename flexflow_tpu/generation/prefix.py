"""Cross-request prefix caching: a radix index over token-block content
with refcounted copy-on-write blocks and a host-RAM offload tier.

At scale most requests share long common prefixes (system prompts,
few-shot templates), yet the block cache (cache.py) was slot-private:
every admission prefilled from scratch and preemption discarded KV for
full recompute. This module makes the cache an explicit
content-addressed structure — the vLLM/SGLang lineage (PagedAttention
block sharing, SOSP'23; RadixAttention prefix trees, SGLang) applied to
the existing block-structured cache:

* **Radix index.** Full blocks of prompt (and, after preemption,
  prompt+generated) content are registered in a trie keyed by
  ``(parent entry, block's token tuple)`` — exact-match edges, so a
  hash collision can never alias two different prefixes onto one
  block's KV. Admission walks the trie over the new prompt's full
  blocks and reuses every matched block instead of recomputing it; the
  engine then prefills only the *suffix* (O(suffix), not O(prompt)).

* **Refcounted copy-on-write blocks.** A block referenced by the index
  is immutable and shared: live sequences hold refcounts, and sharing
  is at full-block granularity so the append path never writes into a
  shared block — except the one genuine divergence: a prompt whose
  tokens are FULLY covered by cached blocks must still recompute its
  last position (the sampled first token needs that position's logits,
  which are not cached), and that write lands inside the last matched
  block. That block is COW-copied on device (one fixed-shape jitted
  copy, admission-time only) and the copy becomes sequence-private.

* **Host-RAM offload tier.** Cold blocks (refcount 0, LRU by last
  touch) swap out to host buffers instead of being dropped — including
  preempt-evicted blocks, so a preempted request's re-admission can
  swap its KV back in instead of recomputing it. Swap-in vs recompute
  is decided by the PR 7 cost-model roofline (transfer bytes over the
  host link vs recompute FLOPs/bytes over the chip roofline), and every
  executed swap-in logs its (predicted, measured) transfer time to the
  engine's PredictionLedger so calibration-drift telemetry covers the
  swap heuristic like every other prediction. Host buffers carry a CRC
  so a corrupted swap-in is detected and falls back to recompute —
  byte-exact output either way (the ``generation.kv_offload`` chaos
  site proves it).

Exactness invariant: token streams are byte-identical with caching on
and off — greedy, seeded temperature, and speculative. Sampling keys
are indexed by generated-token count (scheduler.py), so position is the
only state that matters, and reused blocks hold exactly the K/V the
suffix prefill would have recomputed.

Threading: all mutation happens on the scheduler loop thread
(admission, preemption, reclaim); the fleet router's affinity probe
reads from other threads. One lock guards the trie; steady-state decode
never takes it (prefix work is admission-time only).
"""
from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cache import BlockAllocator, CacheConfig

# Host<->device link bandwidth estimate for the swap-vs-recompute
# decision (PCIe gen4 x16 order of magnitude; deliberately conservative
# — a wrong "swap" costs one transfer, a wrong "recompute" costs a full
# prefill). The decision is pure arithmetic on sizes, so it is
# deterministic run to run; drift between this constant and reality is
# exactly what the PredictionLedger pairs surface.
DEFAULT_HOST_LINK_BYTES_PER_S = 16e9
# per-swap fixed cost (dispatch + host sync), same order as the cost
# model's KERNEL_OVERHEAD
SWAP_OVERHEAD_S = 20e-6


class PrefixEntry:
    """One cached block of prefix content: a radix-trie node.

    ``block`` is the device block id while resident; ``host_k/host_v``
    hold the content while offloaded (exactly one tier is populated).
    ``refs`` counts live sequences whose block tables include this
    block; the index itself keeps the entry alive at refs == 0 until
    eviction. ``children`` counts child entries (any tier) — an entry
    with children is never dropped from the trie, or its descendants
    would become unreachable."""

    __slots__ = (
        "eid", "parent_eid", "tokens", "depth", "block",
        "host_k", "host_v", "crc", "refs", "children", "last_touch",
    )

    def __init__(self, eid: int, parent_eid: int, tokens: Tuple[int, ...],
                 depth: int, block: int):
        self.eid = eid
        self.parent_eid = parent_eid
        self.tokens = tokens
        self.depth = depth  # block index within the prefix (0-based)
        self.block: Optional[int] = block
        self.host_k: Optional[np.ndarray] = None
        self.host_v: Optional[np.ndarray] = None
        self.crc: Optional[int] = None
        self.refs = 0
        self.children = 0
        self.last_touch = 0.0

    @property
    def resident(self) -> bool:
        return self.block is not None


def _crc(k: np.ndarray, v: np.ndarray) -> int:
    return zlib.crc32(v.tobytes(), zlib.crc32(k.tobytes()))


class PackedBlock:
    """One KV block on the prefill->decode handoff wire.

    The host tier's pack format promoted to a wire format: full-head
    ``[layers, block_size, heads, head_dim]`` host arrays (the jitted
    block reader gathers all heads regardless of the source engine's
    sharding, so the wire is TP-degree-agnostic) plus the same CRC seam
    the offload tier uses — a block corrupted in flight is detected at
    the decode side before any device write happens."""

    __slots__ = ("host_k", "host_v", "crc")

    def __init__(self, host_k: np.ndarray, host_v: np.ndarray,
                 crc: Optional[int] = None):
        self.host_k = host_k
        self.host_v = host_v
        self.crc = _crc(host_k, host_v) if crc is None else crc

    def verify(self) -> bool:
        """True when the payload still matches its packing-time CRC."""
        try:
            return _crc(self.host_k, self.host_v) == self.crc
        except Exception:
            return False

    @property
    def nbytes(self) -> int:
        return int(self.host_k.nbytes) + int(self.host_v.nbytes)


class KVHandoffPayload:
    """A prefilled prompt's KV state in transit between pools.

    ``n_positions`` is the number of cache positions the payload covers
    (the full prompt length — the prefill side packs every block the
    prompt wrote, including the trailing partial one). ``geometry`` is
    the full-head per-block shape ``(layers, block_size, heads,
    head_dim)``; the importing engine checks it against its own cache
    config, NOT against the source's TP degree — head-axis resharding
    is implicit because the wire carries all heads and the target's
    jitted block writer commits into its own sharded cache."""

    __slots__ = ("n_positions", "block_size", "blocks", "geometry")

    def __init__(self, n_positions: int, block_size: int,
                 blocks: List[PackedBlock]):
        self.n_positions = n_positions
        self.block_size = block_size
        self.blocks = blocks
        self.geometry: Tuple[int, ...] = (
            tuple(blocks[0].host_k.shape) if blocks else ()
        )

    def verify(self) -> bool:
        return all(b.verify() for b in self.blocks)

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.blocks)


class PrefixCache:
    """Radix prefix index + host tier over one engine's block cache.

    Owns no device memory itself: resident entries hold block ids from
    the shared :class:`BlockAllocator` (an index-owned block is
    *outstanding* from the allocator's point of view until eviction
    frees it), and the engine performs all device reads/writes through
    the jitted block-copy programs it passes in.
    """

    ROOT = 0  # parent_eid of depth-0 entries

    def __init__(
        self,
        allocator: BlockAllocator,
        config: CacheConfig,
        *,
        enabled: bool = True,
        host_budget_bytes: Optional[int] = None,
        host_link_bytes_per_s: float = DEFAULT_HOST_LINK_BYTES_PER_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.allocator = allocator
        self.config = config
        self.enabled = enabled
        # default host tier: as large as the device cache — every
        # evicted block has somewhere to go until real pressure
        self.host_budget_bytes = (
            config.total_bytes if host_budget_bytes is None else host_budget_bytes
        )
        self.host_link_bytes_per_s = host_link_bytes_per_s
        self.swap_overhead_s = SWAP_OVERHEAD_S
        self.clock = clock
        self._lock = threading.Lock()
        self._eid = 0
        # (parent_eid, token tuple) -> entry; entries by id — guarded-by: _lock
        self._edges: Dict[Tuple[int, Tuple[int, ...]], PrefixEntry] = {}
        self._by_id: Dict[int, PrefixEntry] = {}
        # telemetry (admission-path writes; gauges read without the
        # lock — plain ints under the GIL, same idiom as CacheTelemetry)
        self.lookups = 0
        self.hits = 0
        self.tokens_reused_total = 0
        self.blocks_reused_total = 0
        self.cow_copies_total = 0
        self.swaps_in_total = 0
        self.swaps_out_total = 0
        self.swap_in_failures = 0
        self.recompute_fallbacks = 0
        self.registered_total = 0
        self.evicted_total = 0
        self.dropped_total = 0
        self.host_bytes = 0

    # ------------------------------------------------------------- queries
    @property
    def resident_blocks(self) -> int:
        with self._lock:
            return sum(1 for e in self._by_id.values() if e.resident)

    @property
    def offloaded_blocks(self) -> int:
        with self._lock:
            return sum(1 for e in self._by_id.values() if not e.resident)

    @property
    def evictable_blocks(self) -> int:
        """Device blocks reclaimable on demand (resident, unreferenced)
        — counted as available by the pressure telemetry."""
        with self._lock:
            return sum(
                1 for e in self._by_id.values() if e.resident and e.refs == 0
            )

    def hit_ratio(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def match(self, prompt: Sequence[int]) -> List[PrefixEntry]:
        """The longest cached run of full blocks along ``prompt``
        (resident and offloaded entries mixed), touched for LRU. Walks
        at most the blocks whose reuse could cover position
        ``len(prompt) - 2`` — the last position is ALWAYS recomputed so
        its logits exist to sample the first generated token from."""
        if not self.enabled or len(prompt) < 2:
            return []
        bs = self.config.block_size
        max_entries = (len(prompt) - 2) // bs + 1
        run: List[PrefixEntry] = []
        now = self.clock()
        with self._lock:
            parent = self.ROOT
            for j in range(max_entries):
                tok = tuple(prompt[j * bs:(j + 1) * bs])
                if len(tok) < bs:
                    break
                entry = self._edges.get((parent, tok))
                if entry is None:
                    break
                entry.last_touch = now
                run.append(entry)
                parent = entry.eid
        return run

    def probe(self, prompt: Sequence[int]) -> int:
        """Read-only matched-token count (fleet router affinity): how
        many of ``prompt``'s leading tokens are covered by cached
        blocks, capped at ``len(prompt) - 1``. No LRU touch, no
        counters — a routing probe must not look like traffic."""
        if not self.enabled or len(prompt) < 2:
            return 0
        bs = self.config.block_size
        matched = 0
        with self._lock:
            parent = self.ROOT
            for j in range((len(prompt) - 2) // bs + 1):
                tok = tuple(prompt[j * bs:(j + 1) * bs])
                if len(tok) < bs:
                    break
                entry = self._edges.get((parent, tok))
                if entry is None:
                    break
                matched += bs
                parent = entry.eid
        return min(matched, len(prompt) - 1)

    # ------------------------------------------------------------ refcounts
    def acquire(self, entries: Sequence[PrefixEntry]) -> None:
        now = self.clock()
        with self._lock:
            for e in entries:
                e.refs += 1
                e.last_touch = now

    def release(self, entries: Sequence[PrefixEntry]) -> None:
        """Drop one reference per entry. Tolerates entries invalidated
        by a wholesale reset (engine crash recovery) — a stale decref
        must not corrupt the fresh index."""
        with self._lock:
            for e in entries:
                if self._by_id.get(e.eid) is e and e.refs > 0:
                    e.refs -= 1

    # ----------------------------------------------------------- registration
    def register_chain(
        self,
        tokens: Sequence[int],
        blocks: Sequence[int],
        shared_idx: set,
        entries: List[PrefixEntry],
        upto_tokens: int,
    ) -> int:
        """Register ``tokens``' full blocks below ``upto_tokens`` into
        the trie, transferring ownership of the newly registered blocks
        from the sequence to the index (the sequence keeps a ref).

        ``blocks``/``shared_idx``/``entries`` are the owning sequence's
        block table, its set of already-index-owned table positions, and
        its held entries — updated in place. Existing entries are left
        alone (the sequence's own copy of that content stays private),
        except an offloaded entry holding the same content, which
        adopts the sequence's resident block (free device promotion:
        the host copy is dropped). Returns the number of entries
        registered or promoted."""
        if not self.enabled:
            return 0
        bs = self.config.block_size
        n_new = 0
        now = self.clock()
        with self._lock:
            parent = self.ROOT
            for j in range(upto_tokens // bs):
                tok = tuple(tokens[j * bs:(j + 1) * bs])
                if len(tok) < bs:
                    break
                entry = self._edges.get((parent, tok))
                if entry is None:
                    if j in shared_idx:
                        # chain broken upstream of a block we believed
                        # shared (reset raced us): stop registering
                        break
                    self._eid += 1
                    entry = PrefixEntry(self._eid, parent, tok, j, blocks[j])
                    self._edges[(parent, tok)] = entry
                    self._by_id[entry.eid] = entry
                    if parent != self.ROOT:
                        self._by_id[parent].children += 1
                    entry.refs += 1
                    entry.last_touch = now
                    shared_idx.add(j)
                    entries.append(entry)
                    self.registered_total += 1
                    n_new += 1
                elif j not in shared_idx and not entry.resident:
                    # promote: the index already knows this content but
                    # only on the host tier; adopt our resident block
                    self._drop_host(entry)
                    entry.block = blocks[j]
                    entry.refs += 1
                    entry.last_touch = now
                    shared_idx.add(j)
                    entries.append(entry)
                    n_new += 1
                entry.last_touch = now
                parent = entry.eid
        return n_new

    # ------------------------------------------------------------- eviction
    def _drop_host(self, entry: PrefixEntry) -> None:
        if entry.host_k is not None:
            self.host_bytes -= self.config.bytes_per_block
        entry.host_k = None
        entry.host_v = None
        entry.crc = None

    def _remove(self, entry: PrefixEntry) -> None:
        """Drop ``entry`` from the trie entirely. Caller holds _lock
        (reclaim and _enforce_host_budget both invoke this inside their
        ``with self._lock:`` blocks)."""
        self._drop_host(entry)
        del self._edges[(entry.parent_eid, entry.tokens)]  # flexlint: disable=lock-discipline — caller holds _lock (see docstring)
        del self._by_id[entry.eid]
        parent = self._by_id.get(entry.parent_eid)
        if parent is not None:
            parent.children -= 1
        self.dropped_total += 1

    def reclaim(
        self,
        n_blocks: int,
        read_block: Optional[Callable[[int], Tuple[np.ndarray, np.ndarray]]] = None,
    ) -> int:
        """Free up to ``n_blocks`` device blocks by evicting refcount-0
        resident entries, LRU by last touch. Each eviction offloads the
        block's content to the host tier when ``read_block`` is given
        and the host budget allows (the caller wraps the device read
        with the ``generation.kv_offload`` fault site and may raise to
        simulate a failed swap-out — the entry is then dropped instead,
        which is always safe: a dropped block is just a future
        recompute). Returns blocks actually freed."""
        if not self.enabled:
            return 0
        freed = 0
        while freed < n_blocks:
            with self._lock:
                cands = [
                    e for e in self._by_id.values() if e.resident and e.refs == 0
                ]
                if not cands:
                    break
                victim = min(cands, key=lambda e: (e.last_touch, -e.depth))
                # an orphan (its parent already dropped from the trie)
                # can never be reached by match() again: drop it free
                # instead of paying a device read + host budget for
                # permanently dead content
                reachable = (
                    victim.parent_eid == self.ROOT
                    or victim.parent_eid in self._by_id
                )
            offloaded = False
            if (
                reachable
                and read_block is not None
                and self.host_bytes + self.config.bytes_per_block
                <= self.host_budget_bytes
            ):
                try:
                    hk, hv = read_block(victim.block)
                    with self._lock:
                        victim.host_k = np.asarray(hk)
                        victim.host_v = np.asarray(hv)
                        victim.crc = _crc(victim.host_k, victim.host_v)
                        self.host_bytes += self.config.bytes_per_block
                        self.swaps_out_total += 1
                    offloaded = True
                except Exception:
                    offloaded = False  # failed swap-out: drop instead
            with self._lock:
                block, victim.block = victim.block, None
                if not offloaded:
                    # dropped: no tier holds the content, so the node
                    # leaves the trie. Descendants are orphaned (the
                    # match walk can no longer reach them) but stay
                    # evictable — reclaim scans all entries, so their
                    # blocks still come back under pressure and their
                    # own removal tolerates the missing parent.
                    self._remove(victim)
                self.evicted_total += 1
            self.allocator.free([block])
            freed += 1
        self._enforce_host_budget()
        return freed

    def _enforce_host_budget(self) -> None:
        """Drop LRU offloaded leaves until the host tier fits its
        budget. Internal offloaded entries (with children) are kept —
        dropping them would strand reachable descendants; the overshoot
        is bounded by the trie's internal-node count and drains as
        children age out."""
        while True:
            with self._lock:
                if self.host_bytes <= self.host_budget_bytes:
                    return
                leaves = [
                    e for e in self._by_id.values()
                    if not e.resident and e.children == 0 and e.refs == 0
                    and e.host_k is not None
                ]
                if not leaves:
                    return
                victim = min(leaves, key=lambda e: e.last_touch)
                self._remove(victim)

    # --------------------------------------------------------------- tiers
    def take_host_copy(
        self, entry: PrefixEntry
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """The entry's host buffers, CRC-verified. None (and the entry
        dropped from the trie entirely) when the content is corrupt —
        the caller falls back to recompute. Removal, not just a host
        drop: a tier-less node would still count as ``offloaded`` and
        break the host-bytes conservation invariant on the next scrape
        (a held ref is safe — release() ignores removed entries)."""
        with self._lock:
            hk, hv, crc = entry.host_k, entry.host_v, entry.crc
        if hk is None or hv is None:
            return None
        if _crc(hk, hv) != crc:
            with self._lock:
                if self._by_id.get(entry.eid) is entry:
                    self._remove(entry)
                else:
                    self._drop_host(entry)
            return None
        return hk, hv

    def note_swapped_in(self, entry: PrefixEntry, block: int) -> None:
        """The entry's content was written into device ``block``: it is
        resident again; the host copy is retained only if budget is
        slack (re-offload is then free) — dropped here for simplicity
        and budget honesty."""
        with self._lock:
            self._drop_host(entry)
            entry.block = block
            entry.last_touch = self.clock()
            self.swaps_in_total += 1

    # ------------------------------------------------------ decision model
    def swap_in_cost_s(self, n_blocks: int) -> float:
        bytes_total = n_blocks * self.config.bytes_per_block
        return self.swap_overhead_s + bytes_total / self.host_link_bytes_per_s

    # ------------------------------------------------------------ lifecycle
    def reset(self) -> None:
        """Wholesale invalidation after an engine crash/reset: the
        allocator's free list was restored and the device cache
        rezeroed, so every entry — resident ids AND host copies (their
        provenance is the dead cache) — is dropped without per-block
        frees. Journal replay then re-matches against an empty index,
        which is trivially correct (recompute)."""
        with self._lock:
            self._edges.clear()
            self._by_id.clear()
            self.host_bytes = 0

    # -------------------------------------------------------------- report
    def snapshot(self) -> Dict:
        with self._lock:
            resident = sum(1 for e in self._by_id.values() if e.resident)
            offloaded = len(self._by_id) - resident
            shared = sum(1 for e in self._by_id.values() if e.refs > 0)
        return {
            "enabled": self.enabled,
            "resident_blocks": resident,
            "offloaded_blocks": offloaded,
            "shared_blocks": shared,  # resident entries referenced by >=1 stream
            "host_bytes": self.host_bytes,
            "host_budget_bytes": self.host_budget_bytes,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_ratio": self.hit_ratio(),
            "tokens_reused_total": self.tokens_reused_total,
            "blocks_reused_total": self.blocks_reused_total,
            "cow_copies_total": self.cow_copies_total,
            "swaps_in_total": self.swaps_in_total,
            "swaps_out_total": self.swaps_out_total,
            "swap_in_failures": self.swap_in_failures,
            "recompute_fallbacks": self.recompute_fallbacks,
            "registered_total": self.registered_total,
            "evicted_total": self.evicted_total,
        }

    def tier_residency(self) -> List[Dict]:
        """Per-entry tier table for ``obsreport cache`` (bounded: the
        trie never exceeds the allocator's block count plus the host
        budget's block count)."""
        with self._lock:
            return [
                {
                    "depth": e.depth,
                    "tier": "device" if e.resident else "host",
                    "block": e.block,
                    "refs": e.refs,
                    "last_touch": e.last_touch,
                }
                for e in sorted(
                    self._by_id.values(), key=lambda e: (e.depth, e.eid)
                )
            ]
