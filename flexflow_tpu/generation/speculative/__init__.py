"""Speculative decoding: drafters + fixed-shape batched verification.

Steady-state decode is memory-bandwidth-bound — one fixed-shape jit per
generated token (engine.py). Speculative decoding (Leviathan et al.
2023; SpecInfer, Miao et al. 2024, from the FlexFlow lineage this repo
reproduces) amortizes that cost: a cheap *drafter* guesses up to k
tokens, ONE fixed-shape ``verify`` forward scores the whole batch ×
(k+1) window against the block-table KV cache (chunked-append
attention, ops/attention.py), and exact acceptance keeps the output
distribution identical to non-speculative decoding:

* greedy verification reproduces the non-speculative greedy stream
  token-for-token — unconditionally (any drafter, any preemption or
  load pattern);
* temperature/top-k sampling uses distribution-preserving rejection
  sampling on the engine's per-token-count seeded keys: every emitted
  token's marginal is exactly the target distribution, identical
  scheduling replays the identical stream, and preemption never
  rewrites emitted tokens (window layout — hence the realized draw —
  can differ under different load; only greedy is
  realization-invariant).

The speculation-aware ContinuousBatchingScheduler (generation/
scheduler.py) drives it: multi-token cache append with block allocation
for up to k+1 tokens per step, per-request adaptive k (shrink on low
acceptance, cap on cache pressure), and exact accounting when a
partially-accepted window crosses a block boundary or EOS lands
mid-window.
"""
from .drafter import (
    Drafter,
    DraftModelDrafter,
    NgramDrafter,
    SpeculationConfig,
    build_drafter,
)
from .sampling import rejection_sample, residual_distribution, speculative_accept

__all__ = [
    "Drafter",
    "DraftModelDrafter",
    "NgramDrafter",
    "SpeculationConfig",
    "build_drafter",
    "rejection_sample",
    "residual_distribution",
    "speculative_accept",
]
