"""Drafters: cheap token proposers for speculative decoding.

A drafter guesses the next ``k`` tokens of a sequence; the target model
verifies the whole guess in one fixed-shape forward (engine.verify).
Because verification is exact (speculative/sampling.py), a drafter can
NEVER change what tokens come out — only how many engine steps they
take. Both in-tree drafters propose deterministically (point-mass
proposals), which keeps replay-after-preemption deterministic: a
drafter's output is a pure function of the sequence prefix.

* :class:`NgramDrafter` — model-free prompt-lookup decoding (Saxena
  2023; SpecInfer's match-based speculation): find the most recent
  earlier occurrence of the sequence's trailing n-gram and propose the
  tokens that followed it. Zero extra FLOPs; strong on code,
  summarization, and any self-repetitive stream.
* :class:`DraftModelDrafter` — a small decoder (same DecoderParams
  pytree as the target) greedily proposes ``k`` tokens via its padded
  full forward, one fixed-shape jit per prompt bucket (SpecInfer's
  small-speculative-model regime, collapsed to a single sequence
  instead of a tree).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..decoder import DecoderParams, forward_full
from ..engine import default_buckets


@dataclasses.dataclass(frozen=True)
class SpeculationConfig:
    """Per-request speculation policy.

    ``k`` is the MAXIMUM drafted tokens per window (clamped to the
    engine's compiled window); the scheduler adapts the live k inside
    [1, k] when ``adaptive`` — shrinking while the acceptance EMA sits
    below ``low_acceptance``, regrowing above ``high_acceptance`` — and
    additionally caps any single window on cache pressure.
    """

    enabled: bool = True
    k: int = 4
    method: str = "ngram"  # "ngram" | "draft_model"
    max_ngram: int = 3
    min_ngram: int = 1
    adaptive: bool = True
    low_acceptance: float = 0.3
    high_acceptance: float = 0.8
    ema_alpha: float = 0.5

    def __post_init__(self):
        if self.k < 1:
            raise ValueError("speculation k must be >= 1")
        if self.method not in ("ngram", "draft_model"):
            raise ValueError(f"unknown speculation method {self.method!r}")
        if not 1 <= self.min_ngram <= self.max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")


class Drafter:
    """Interface: propose up to ``k`` next tokens for ``prefix``.

    ``propose`` must be a pure function of ``prefix`` (no hidden state,
    no randomness) so preempt-and-recompute replays identically. It may
    return fewer than ``k`` tokens — including none, which degrades that
    window to a plain (still exact) decode step.
    """

    def propose(self, prefix: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup decoding: match the prefix's trailing n-gram
    (longest first, ``max_ngram`` down to ``min_ngram``) against the
    MOST RECENT earlier occurrence in the prefix and propose the tokens
    that followed it.

    ``max_lookback`` bounds the host-side scan to the trailing window
    of the prefix — the drafter sits on the scheduler's critical path
    once per verify step per request, and an unbounded right-to-left
    rescan of a multi-thousand-token prefix would leave the device
    idling on Python list compares. Still a pure function of the prefix
    (the window is a deterministic suffix), so replay stays exact.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1, max_lookback: int = 512):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        if max_lookback < min_ngram + 1:
            raise ValueError("max_lookback must cover at least one n-gram + continuation")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.max_lookback = max_lookback

    def propose(self, prefix: Sequence[int], k: int) -> List[int]:
        seq = list(prefix)[-self.max_lookback:]
        n = len(seq)
        if k <= 0 or n < self.min_ngram + 1:
            return []
        for size in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            pattern = seq[n - size:]
            # most recent earlier occurrence: scan right-to-left, the
            # match must end BEFORE the final position so a continuation
            # exists
            for start in range(n - size - 1, -1, -1):
                if seq[start:start + size] == pattern:
                    cont = seq[start + size:start + size + k]
                    if cont:
                        return [int(t) for t in cont]
        return []


class DraftModelDrafter(Drafter):
    """Greedy proposals from a small draft decoder.

    Runs the draft model's full forward over the (bucket-padded) prefix
    once per proposed token — k small-model forwards to save up to k
    large-model steps, the classic draft/target FLOPs trade. Jits are
    cached per prompt bucket so steady-state drafting never retraces.
    """

    def __init__(
        self,
        params: DecoderParams,
        max_seq_len: int,
        buckets: Optional[Sequence[int]] = None,
    ):
        self.params = params
        self.max_seq_len = max_seq_len
        self.buckets = tuple(sorted(buckets or default_buckets(max_seq_len)))
        # one jit; jax's own cache keys on the padded shape, giving
        # exactly one trace per bucket
        self._forward = jax.jit(
            lambda p, t, n: forward_full(p, t, n)[jnp.arange(t.shape[0]), n - 1]
        )

    def _last_logits(self, seq: List[int]) -> jax.Array:
        bucket = next((b for b in self.buckets if len(seq) <= b), self.buckets[-1])
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, : len(seq)] = seq
        return self._forward(
            self.params, jnp.asarray(tokens), jnp.full((1,), len(seq), jnp.int32)
        )[0]

    def propose(self, prefix: Sequence[int], k: int) -> List[int]:
        seq = list(prefix)
        out: List[int] = []
        while len(out) < k and len(seq) < self.max_seq_len and len(seq) <= self.buckets[-1]:
            out.append(int(jnp.argmax(self._last_logits(seq))))
            seq.append(out[-1])
        return out


def build_drafter(
    config: SpeculationConfig,
    draft_params: Optional[DecoderParams] = None,
    max_seq_len: int = 0,
) -> Drafter:
    """Drafter factory for a request's SpeculationConfig."""
    if config.method == "ngram":
        return NgramDrafter(max_ngram=config.max_ngram, min_ngram=config.min_ngram)
    if draft_params is None:
        raise ValueError(
            "speculation method 'draft_model' needs draft params "
            "(ContinuousBatchingScheduler(draft_params=...))"
        )
    return DraftModelDrafter(draft_params, max_seq_len)
