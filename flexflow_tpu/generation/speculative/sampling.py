"""Speculative acceptance: exact verification of drafted windows.

Leviathan et al. 2023 ("Fast Inference from Transformers via Speculative
Decoding"): score k drafted tokens with ONE target forward, accept the
longest prefix the target agrees with, and emit one extra token from the
target's own distribution at the first disagreement (the correction) or
after a fully-accepted window (the bonus) — so every window emits
between 1 and k+1 tokens and the output distribution is EXACTLY the
target model's.

Two exactness regimes, both implemented here and jit-composed into the
engine's fixed-shape verify step:

* **Greedy** (``temperature <= 0``): a draft is accepted iff it equals
  the target argmax. Emitted tokens are the target argmax chain — a
  speculative greedy stream is token-for-token identical to the
  non-speculative one, whatever the drafter proposes.
* **Temperature/top-k sampling**: distribution-preserving rejection
  sampling against a point-mass proposal (both in-tree drafters propose
  deterministically): draft ``d`` with target probability ``p`` is
  accepted with probability ``min(1, p(d)/q(d)) = p(d)``; on first
  rejection the emitted token is drawn from the normalized residual
  ``max(p - q, 0)`` (``p`` with ``d`` excluded); after a fully-accepted
  window the bonus token is drawn from ``p`` itself. The marginal of
  every emitted token is exactly ``p``.

**Constrained decoding** (ISSUE 18) composes with both regimes without
touching this module: the grammar mask is a pre-softmax additive bias
applied IDENTICALLY to the draft logits that proposed each window
position and to the target logits that verify it. Over the grammar's
support the masked target distribution is still a distribution and the
masked proposal is still its point-mass/q proposal, so the acceptance
identities above hold verbatim and the emitted marginal is exactly the
masked target's. Tokens outside the support have ``p(d) = 0`` — a
grammar-banned draft is rejected with certainty and the residual/bonus
draws renormalize over legal tokens only (the scheduler additionally
trims banned drafts before verify so they never waste window slots).

Key discipline mirrors the engine's per-token-count seeded streams: the
token emitted at generated-count ``n`` consumes keys derived ONLY from
``fold_in(base_key, n)`` — the accept coin from ``fold_in(key, 1)``,
the residual draw from ``fold_in(key, 2)``, and the bonus draw from the
raw key, which makes a zero-draft verify step sample *identically* to
the non-speculative decode step (same Gumbel trick on the same raw
key). Consequences: greedy streams are realization-invariant (argmax
consumes no key); temperature streams replay identically under
identical scheduling, and preempt-and-recompute preserves the emitted
prefix verbatim while the continuation draws from the same per-count
key stream. Which derivation a count consumes depends on where it
lands in a window (draft / rejection / bonus), so a different window
layout — different load, different adaptive k — may realize a
different, equally-distributed temperature stream.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..engine import topk_scaled_logits


def residual_distribution(p: jax.Array, q: jax.Array) -> jax.Array:
    """Normalized rejection residual ``max(p - q, 0)`` over the last
    axis. Degenerate case (q covers p everywhere, so rejection has
    probability zero): fall back to ``p`` instead of NaN."""
    res = jnp.maximum(p - q, 0.0)
    total = jnp.sum(res, axis=-1, keepdims=True)
    return jnp.where(total > 1e-12, res / jnp.maximum(total, 1e-30), p)


def rejection_sample(
    p: jax.Array, q: jax.Array, draft: jax.Array, key: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """One general-proposal rejection-sampling step (the textbook rule,
    exposed for tests and soft-q drafters): accept ``draft`` with
    probability ``min(1, p[draft]/q[draft])``, else sample from the
    normalized residual. ``p``/``q``: [V] target and proposal
    probabilities. Returns (token, accepted) — the marginal of ``token``
    is exactly ``p`` for ANY proposal ``q``."""
    p_d = p[draft]
    q_d = jnp.maximum(q[draft], 1e-30)
    u = jax.random.uniform(jax.random.fold_in(key, 1))
    accepted = u < jnp.minimum(1.0, p_d / q_d)
    res = residual_distribution(p, q)
    gumbel = jax.random.gumbel(jax.random.fold_in(key, 2), res.shape)
    resampled = jnp.argmax(jnp.log(jnp.maximum(res, 1e-30)) + gumbel, axis=-1)
    return jnp.where(accepted, draft, resampled).astype(jnp.int32), accepted


def speculative_accept(
    logits: jax.Array,
    draft_tokens: jax.Array,
    n_draft: jax.Array,
    temps: jax.Array,
    top_ks: jax.Array,
    keys: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Vectorized window acceptance for the engine's verify step.

    logits: [B, W, V] target logits over the window (index ``j`` scores
    the token at emitted-count offset ``j``); draft_tokens: [B, W-1]
    int32 (point-mass proposals; entries past ``n_draft`` ignored);
    n_draft: [B] int32 in [0, W-1]; temps/top_ks: [B]; keys: [B, W]
    PRNG keys, one per emitted-count offset.

    Returns (out_tokens [B, W], n_emitted [B]): ``out_tokens[b, :a+1]``
    are the emitted tokens where ``a`` is the accepted-prefix length —
    accepted drafts followed by the correction (first rejection) or
    bonus (full acceptance) token; entries past ``n_emitted`` are
    garbage. ``n_draft == 0`` degenerates to exactly the engine's
    non-speculative sampling of one token with ``keys[:, 0]``.
    """
    b, w, v = logits.shape
    kd = w - 1
    greedy = temps <= 0.0
    offs = jnp.arange(w, dtype=jnp.int32)[None, :]
    # the engine's own sampling transform, broadcast over the window —
    # sharing it keeps zero-draft verify bit-identical to decode
    masked = topk_scaled_logits(
        logits,
        jnp.broadcast_to(temps[:, None], (b, w)),
        jnp.broadcast_to(top_ks[:, None], (b, w)),
    )
    p = jax.nn.softmax(masked, axis=-1)  # [B, W, V] target sampling dist
    g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, W] greedy chain

    # -- acceptance of each draft (point-mass proposal) ------------------
    d = draft_tokens.astype(jnp.int32)  # [B, kd]
    p_d = jnp.take_along_axis(p[:, :kd], d[..., None], axis=-1)[..., 0]  # [B, kd]
    accept_key = jax.vmap(jax.vmap(lambda kk: jax.random.fold_in(kk, 1)))(keys[:, :kd])
    u = jax.vmap(jax.vmap(jax.random.uniform))(accept_key)  # [B, kd]
    acc = jnp.where(greedy[:, None], d == g[:, :kd], u < p_d)
    acc = jnp.logical_and(acc, offs[:, :kd] < n_draft[:, None])
    a = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)  # [B]

    # -- correction / bonus token at every offset (selected at j == a) ---
    # residual draw (rejection at offset j < n_draft): p_j minus the
    # drafted token's mass, renormalized
    res = residual_distribution(p[:, :kd], jax.nn.one_hot(d, v, dtype=p.dtype) * p_d[..., None])
    res_key = jax.vmap(jax.vmap(lambda kk: jax.random.fold_in(kk, 2)))(keys[:, :kd])
    res_gumbel = jax.vmap(jax.vmap(lambda kk: jax.random.gumbel(kk, (v,))))(res_key)
    r_res = jnp.argmax(jnp.log(jnp.maximum(res, 1e-30)) + res_gumbel, axis=-1)  # [B, kd]
    r_res = jnp.concatenate([r_res, jnp.zeros((b, 1), r_res.dtype)], axis=1)
    # bonus draw (offset j == n_draft, nothing proposed): sample from p_j
    # with the RAW key — byte-identical to engine._sample's Gumbel trick
    bonus_gumbel = jax.vmap(jax.vmap(lambda kk: jax.random.gumbel(kk, (v,))))(keys)
    r_bonus = jnp.argmax(masked + bonus_gumbel, axis=-1)  # [B, W]
    corr = jnp.where(offs < n_draft[:, None], r_res, r_bonus)
    corr = jnp.where(greedy[:, None], g, corr)

    # -- emitted tokens: accepted drafts then the correction/bonus -------
    out_draft = jnp.concatenate([d, jnp.zeros((b, 1), d.dtype)], axis=1)
    out = jnp.where(offs < a[:, None], out_draft, corr)
    out = jnp.where(greedy[:, None], g, out)  # accepted greedy drafts ARE g
    return out.astype(jnp.int32), (a + 1).astype(jnp.int32)
