"""Decoder-only transformer for the generation engine: a pure-JAX
params pytree + three forward modes that provably agree.

The graph-built transformers (models/transformer.py) lower to one-shot
jitted programs with no state; generation needs a forward that can split
into prefill (write the prompt's K/V into the cache) and decode (one
token against cached K/V). This module keeps the same layer recipe as
``attention_encoder_layer`` with ``causal=True`` — pre-LN residual
blocks, GELU FFN, the ops/attention.py weight layouts ([E, H, D]
projections, [H, D, E] output) — plus a learned absolute position
embedding (cache positions index it directly) and a token-embedding
front end with an LM head.

Three forwards over one params pytree:

* :func:`forward_full` — full-context causal forward, [B, S] -> logits
  [B, S, V]. The parity oracle.
* :func:`prefill` — forward_full that also returns every layer's K/V
  ([L, B, S, H, D]) for the engine to scatter into the block cache,
  with per-sequence length masking so padded prompt buckets match the
  unpadded forward.
* :func:`decode_step` — one token per sequence against the cache
  (writes the token's K/V, then decode-mode attention), [B] -> logits
  [B, V].
* :func:`verify_step` — a W-token append window per sequence against
  the cache (writes all W tokens' K/V, then chunked-append attention
  with causal-within-window masking), [B, W] -> logits [B, W, V]. The
  speculative-decoding verification forward: W sequential decode_steps
  in ONE call, with identical logits.

``forward_full(tokens)[b, i] == decode logits after caching tokens[:i]``
within fp32 tolerance — asserted by tests/test_generation.py;
``verify_step`` agrees with ``decode_step`` token-for-token — asserted
by tests/test_speculative.py.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.transformer import TransformerConfig
from ..ops.attention import append_attention_core, decode_attention_core, masked_attention
from .cache import slot_mapping

# a decoder is a plain pytree: jit-friendly, checkpoint-friendly
DecoderParams = Dict[str, Any]


def _glorot(rng, shape):
    fan_in, fan_out = shape[0], shape[-1]
    if len(shape) == 3:  # [E, H, D] / [H, D, E] projections
        fan_in = shape[0] if shape[0] > shape[2] else shape[0] * shape[1]
        fan_out = shape[1] * shape[2] if shape[0] > shape[2] else shape[2]
    lim = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(rng, shape, jnp.float32, -lim, lim)


def init_decoder_params(
    rng: jax.Array, cfg: TransformerConfig, max_positions: Optional[int] = None
) -> DecoderParams:
    """Initialize the decoder pytree for ``cfg`` (``vocab_size`` > 0)."""
    if cfg.vocab_size <= 0:
        raise ValueError("generation decoder needs cfg.vocab_size > 0")
    e, h = cfg.hidden_size, cfg.num_heads
    d = e // h
    f, v = cfg.ff_size, cfg.vocab_size
    p = max_positions or cfg.seq_length
    keys = iter(jax.random.split(rng, 4 + 6 * cfg.num_layers))
    params: DecoderParams = {
        "tok_embed": _glorot(next(keys), (v, e)),
        "pos_embed": 0.02 * jax.random.normal(next(keys), (p, e), jnp.float32),
        "final_ln_g": jnp.ones((e,), jnp.float32),
        "final_ln_b": jnp.zeros((e,), jnp.float32),
        "lm_head": _glorot(next(keys), (e, v)),
        "layers": [],
    }
    for _ in range(cfg.num_layers):
        params["layers"].append(
            {
                "ln1_g": jnp.ones((e,), jnp.float32),
                "ln1_b": jnp.zeros((e,), jnp.float32),
                "wq": _glorot(next(keys), (e, h, d)),
                "wk": _glorot(next(keys), (e, h, d)),
                "wv": _glorot(next(keys), (e, h, d)),
                "wo": _glorot(next(keys), (h, d, e)),
                "ln2_g": jnp.ones((e,), jnp.float32),
                "ln2_b": jnp.zeros((e,), jnp.float32),
                "ff1": _glorot(next(keys), (e, f)),
                "ff1_b": jnp.zeros((f,), jnp.float32),
                "ff2": _glorot(next(keys), (f, e)),
                "ff2_b": jnp.zeros((e,), jnp.float32),
            }
        )
    return params


def _ln(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _embed(params, tokens, positions):
    return params["tok_embed"][tokens] + params["pos_embed"][positions]


def _ffn(layer, x):
    h = _ln(x, layer["ln2_g"], layer["ln2_b"])
    h = jax.nn.gelu(h @ layer["ff1"] + layer["ff1_b"])
    return x + h @ layer["ff2"] + layer["ff2_b"]


def forward_full(
    params: DecoderParams,
    tokens: jax.Array,
    lengths: Optional[jax.Array] = None,
) -> jax.Array:
    """Full-context causal forward: [B, S] int32 -> logits [B, S, V].
    ``lengths`` masks padded key positions (bucketed prompts)."""
    b, s = tokens.shape
    x = _embed(params, tokens, jnp.arange(s)[None, :])
    lens = lengths if lengths is not None else jnp.full((b,), s, jnp.int32)
    for layer in params["layers"]:
        h = _ln(x, layer["ln1_g"], layer["ln1_b"])
        q = jnp.einsum("bse,ehd->bshd", h, layer["wq"])
        k = jnp.einsum("bse,ehd->bshd", h, layer["wk"])
        v = jnp.einsum("bse,ehd->bshd", h, layer["wv"])
        ctx = masked_attention(q, k, v, lens, causal=True)
        x = x + jnp.einsum("bshd,hde->bse", ctx, layer["wo"])
        x = _ffn(layer, x)
    x = _ln(x, params["final_ln_g"], params["final_ln_b"])
    return x @ params["lm_head"]


def prefill(
    params: DecoderParams,
    tokens: jax.Array,
    lengths: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill forward: logits [B, S, V] plus every layer's K/V
    ([L, B, S, H, D] each) for the engine to write into the cache."""
    b, s = tokens.shape
    x = _embed(params, tokens, jnp.arange(s)[None, :])
    ks, vs = [], []
    for layer in params["layers"]:
        h = _ln(x, layer["ln1_g"], layer["ln1_b"])
        q = jnp.einsum("bse,ehd->bshd", h, layer["wq"])
        k = jnp.einsum("bse,ehd->bshd", h, layer["wk"])
        v = jnp.einsum("bse,ehd->bshd", h, layer["wv"])
        ks.append(k)
        vs.append(v)
        ctx = masked_attention(q, k, v, lengths, causal=True)
        x = x + jnp.einsum("bshd,hde->bse", ctx, layer["wo"])
        x = _ffn(layer, x)
    x = _ln(x, params["final_ln_g"], params["final_ln_b"])
    return x @ params["lm_head"], jnp.stack(ks), jnp.stack(vs)


def decode_step(
    params: DecoderParams,
    tokens: jax.Array,
    positions: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    backend: str = "cpu",
    mesh=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for every batch slot.

    tokens/positions: [B] int32 (the token being decoded and its cache
    position); cache_k/cache_v: [L, num_blocks, block_size, H, D];
    block_tables: [B, max_blocks]; context_lens: [B] — valid cache
    positions INCLUDING this token (``positions + 1`` for live slots,
    0 for inactive ones, whose writes land in scratch block 0).
    Returns (logits [B, V], cache_k, cache_v) with the K/V written.
    """
    nb, bs = cache_k.shape[1], cache_k.shape[2]
    x = _embed(params, tokens, positions)  # [B, E]
    slots = jax.vmap(lambda bt, p: slot_mapping(bt, p, bs))(block_tables, positions)
    for li, layer in enumerate(params["layers"]):
        h = _ln(x, layer["ln1_g"], layer["ln1_b"])
        q = jnp.einsum("be,ehd->bhd", h, layer["wq"])
        k = jnp.einsum("be,ehd->bhd", h, layer["wk"])
        v = jnp.einsum("be,ehd->bhd", h, layer["wv"])
        # write this token's K/V, then attend over the updated cache so
        # the token sees itself (context_lens includes it)
        flat_k = cache_k[li].reshape(nb * bs, *cache_k.shape[3:])
        flat_v = cache_v[li].reshape(nb * bs, *cache_v.shape[3:])
        flat_k = flat_k.at[slots].set(k.astype(flat_k.dtype))
        flat_v = flat_v.at[slots].set(v.astype(flat_v.dtype))
        cache_k = cache_k.at[li].set(flat_k.reshape(cache_k.shape[1:]))
        cache_v = cache_v.at[li].set(flat_v.reshape(cache_v.shape[1:]))
        ctx = decode_attention_core(
            q, cache_k[li], cache_v[li], block_tables, context_lens,
            backend=backend, mesh=mesh,
        )
        x = x + jnp.einsum("bhd,hde->be", ctx, layer["wo"])
        x = _ffn(layer, x)
    x = _ln(x, params["final_ln_g"], params["final_ln_b"])
    return x @ params["lm_head"], cache_k, cache_v


def verify_step(
    params: DecoderParams,
    tokens: jax.Array,
    positions: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    block_tables: jax.Array,
    backend: str = "cpu",
    mesh=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One chunked-append (speculative verification) step for every
    batch slot.

    tokens/positions: [B, W] int32 — the window being scored (the last
    committed token followed by up to W-1 drafted tokens) and each
    window token's cache position. ``positions < 0`` marks padding
    window slots (fixed-shape windows with fewer real drafts): their
    K/V scatter to scratch block 0 and their attention/logits rows are
    meaningless (the caller's acceptance logic never reads them).
    cache_k/cache_v: [L, num_blocks, block_size, H, D]; block_tables:
    [B, max_blocks]. Returns (logits [B, W, V], cache_k, cache_v) with
    all W tokens' K/V written — accepted positions hold exactly the K/V
    sequential decode would have written (a window token's K/V depends
    only on its prefix, which is valid up to the first rejection);
    rejected/later positions hold garbage that the next window
    overwrites before any masked read can see it.
    """
    nb, bs = cache_k.shape[1], cache_k.shape[2]
    safe_pos = jnp.maximum(positions, 0)
    x = _embed(params, tokens, safe_pos)  # [B, W, E]
    slots = jax.vmap(lambda bt, p: slot_mapping(bt, p, bs))(block_tables, safe_pos)
    slots = jnp.where(positions >= 0, slots, 0)  # padding -> scratch
    flat_slots = slots.reshape(-1)
    for li, layer in enumerate(params["layers"]):
        h = _ln(x, layer["ln1_g"], layer["ln1_b"])
        q = jnp.einsum("bwe,ehd->bwhd", h, layer["wq"])
        k = jnp.einsum("bwe,ehd->bwhd", h, layer["wk"])
        v = jnp.einsum("bwe,ehd->bwhd", h, layer["wv"])
        # write the whole window's K/V, then attend over the updated
        # cache with per-query position masks (each token sees itself
        # and everything before it, nothing after)
        flat_k = cache_k[li].reshape(nb * bs, *cache_k.shape[3:])
        flat_v = cache_v[li].reshape(nb * bs, *cache_v.shape[3:])
        flat_k = flat_k.at[flat_slots].set(k.reshape(-1, *k.shape[2:]).astype(flat_k.dtype))
        flat_v = flat_v.at[flat_slots].set(v.reshape(-1, *v.shape[2:]).astype(flat_v.dtype))
        cache_k = cache_k.at[li].set(flat_k.reshape(cache_k.shape[1:]))
        cache_v = cache_v.at[li].set(flat_v.reshape(cache_v.shape[1:]))
        ctx = append_attention_core(
            q, cache_k[li], cache_v[li], block_tables, positions,
            backend=backend, mesh=mesh,
        )
        x = x + jnp.einsum("bwhd,hde->bwe", ctx, layer["wo"])
        x = _ffn(layer, x)
    x = _ln(x, params["final_ln_g"], params["final_ln_b"])
    return x @ params["lm_head"], cache_k, cache_v
