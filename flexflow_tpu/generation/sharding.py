"""Serving layout: how the generation engine's state maps onto a device
mesh (ISSUE 15 — multi-chip sharded generation).

The layout is Megatron/Pope-style intra-layer tensor parallelism over
the ``"model"`` mesh axis (parallel/mesh.py ``serving_mesh``), chosen
for DECODE: the KV cache — the thing that actually outgrows one chip in
serving — shards along its head axis, each shard's attention runs over
its LOCAL KV heads only, and the single cross-shard boundary is the
partial-sum reduction at the attention output projection (GSPMD lowers
it to a psum on ICI, exactly the collective ops/parallel_ops.py's
``ReductionOp`` annotates in the training path).

Per-leaf placement of the decoder pytree (decoder.py):

  wq/wk/wv  [E, H, D]   head axis sharded      P(None, "model", None)
  wo        [H, D, E]   head axis sharded      P("model", None, None)
                        (row-parallel: contraction over the sharded H
                        produces partials -> ONE psum per layer at the
                        attention output)
  ff1       [E, F]      column-parallel        P(None, "model")
  ff2       [F, E]      row-parallel           P("model", None)
                        (only when tp divides F; otherwise replicated —
                        the layout degrades, it never errors)
  everything else       replicated             P()

and of the engine's runtime state:

  KV cache k/v [L, num_blocks, block_size, H, D]  P(None, None, None,
                                                    "model", None)
  block tables / positions / sampling params / tokens   replicated

Block tables and the host-side allocator are therefore device-count-
agnostic: a block id means the same (block, offset) slot on every
shard, only the head slice living there differs. A 1-device mesh makes
every spec a no-op — the engine is bit-for-bit the single-device
engine, which is the exactness anchor the multi-device tests and
``genbench --mesh`` compare against.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import MODEL_AXIS, serving_mesh


def validate_kv_shards(num_kv_heads: int, tp_degree: int) -> None:
    """KV heads divide across shards — a non-dividing degree would need
    uneven head slices the fixed-shape jits cannot express."""
    if tp_degree < 1:
        raise ValueError(f"tp_degree must be >= 1, got {tp_degree}")
    if num_kv_heads % tp_degree != 0:
        raise ValueError(
            f"num_kv_heads % tp_degree != 0: {num_kv_heads} KV heads do "
            f"not divide across {tp_degree} shards; pick a tp_degree "
            f"that divides the head count"
        )


@dataclasses.dataclass(frozen=True)
class ServingLayout:
    """One engine's mesh + the NamedShardings its jits are built with."""

    mesh: Mesh
    tp_degree: int
    num_heads: int

    @classmethod
    def build(
        cls,
        num_heads: int,
        tp_degree: int = 1,
        mesh: Optional[Mesh] = None,
        devices=None,
    ) -> "ServingLayout":
        validate_kv_shards(num_heads, tp_degree)
        if mesh is None:
            mesh = serving_mesh(tp_degree, devices)
        elif MODEL_AXIS not in mesh.axis_names:
            raise ValueError(
                f"serving mesh must carry a '{MODEL_AXIS}' axis, got "
                f"{mesh.axis_names}"
            )
        return cls(mesh=mesh, tp_degree=tp_degree, num_heads=num_heads)

    # ------------------------------------------------------------ shardings
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return self.sharding()

    @property
    def cache_sharding(self) -> NamedSharding:
        """KV cache [L, num_blocks, block_size, H, D]: heads sharded."""
        return self.sharding(None, None, None, MODEL_AXIS, None)

    def param_shardings(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Per-leaf NamedSharding pytree matching the decoder params."""
        repl = self.replicated
        head_in = self.sharding(None, MODEL_AXIS, None)  # wq/wk/wv [E,H,D]
        head_out = self.sharding(MODEL_AXIS, None, None)  # wo [H,D,E]

        def layer_shardings(layer: Dict[str, Any]) -> Dict[str, Any]:
            out = {k: repl for k in layer}
            out["wq"] = out["wk"] = out["wv"] = head_in
            out["wo"] = head_out
            # Megatron MLP: column-parallel up, row-parallel down — only
            # when the mesh degree divides the ff width; an odd width
            # degrades to replicated FFN compute instead of failing the
            # build
            if layer["ff1"].shape[1] % self.tp_degree == 0:
                out["ff1"] = self.sharding(None, MODEL_AXIS)
                out["ff2"] = self.sharding(MODEL_AXIS, None)
            return out

        return {
            **{k: repl for k in params if k != "layers"},
            "layers": [layer_shardings(l) for l in params["layers"]],
        }

    def shard_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Commit the decoder pytree onto the mesh per the layout."""
        return jax.tree_util.tree_map(
            jax.device_put, params, self.param_shardings(params)
        )

    def put_replicated(self, x):
        """Commit a host array onto the mesh, replicated. Every
        non-sharded jit input goes through here so input shardings are
        identical call to call — a drifting placement would recompile
        the fixed-shape programs (the zero-steady-state-retrace
        contract)."""
        return jax.device_put(x, self.replicated)

    def describe(self) -> Dict[str, Any]:
        """Metadata block: mesh geometry + the per-tensor specs."""
        return {
            "tp_degree": self.tp_degree,
            "mesh_devices": self.mesh.size,
            "mesh_axes": {
                name: int(size) for name, size in self.mesh.shape.items()
            },
            "kv_heads_per_shard": self.num_heads // self.tp_degree,
            "specs": {
                "cache_kv": f"[L, blocks, block, H/{self.tp_degree}, D]",
                "wq/wk/wv": f"[E, H/{self.tp_degree}, D]",
                "wo": f"[H/{self.tp_degree}, D, E]",
                "block_tables": "replicated",
                "sampling_state": "replicated",
            },
        }
