"""Block-structured KV cache: preallocated device storage + host-side
block accounting.

vLLM/PagedAttention (SOSP'23) adapted to XLA's static-shape constraint:
the cache is ONE preallocated array per K/V — ``[L, num_blocks,
block_size, H, D]`` — and a sequence's cache is a *block table* (list of
block ids) into it. Appending a token writes one ``(block, offset)``
slot; nothing is ever moved or reallocated, so every jitted step sees
the same cache shape regardless of how many sequences are live or how
long they've grown. The reference has no KV cache at all (its attention
is a one-shot cuDNN call, SURVEY §2.2).

Block 0 is reserved as a **scratch block**: padded prompt positions and
inactive decode slots scatter their (meaningless) K/V there, so the
jitted steps never need dynamic shapes or masked scatters to avoid
corrupting live sequences. The allocator simply never hands out
block 0.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.types import DataType


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Geometry of the block-structured cache.

    ``num_blocks`` INCLUDES the reserved scratch block 0, so the usable
    capacity is ``(num_blocks - 1) * block_size`` token positions.
    """

    num_layers: int
    num_heads: int
    head_dim: int
    num_blocks: int
    block_size: int = 16
    dtype: DataType = DataType.FLOAT

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is scratch)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")

    @property
    def bytes_per_block(self) -> int:
        """K + V bytes one block occupies across all layers."""
        return (
            2
            * self.num_layers
            * self.block_size
            * self.num_heads
            * self.head_dim
            * self.dtype.size_bytes
        )

    @property
    def total_bytes(self) -> int:
        return self.num_blocks * self.bytes_per_block

    @property
    def usable_tokens(self) -> int:
        return (self.num_blocks - 1) * self.block_size

    def blocks_for(self, num_tokens: int) -> int:
        """Blocks needed to hold ``num_tokens`` cache positions."""
        return -(-max(0, num_tokens) // self.block_size)

    @classmethod
    def from_budget(
        cls,
        budget_bytes: int,
        num_layers: int,
        num_heads: int,
        head_dim: int,
        block_size: int = 16,
        dtype: DataType = DataType.FLOAT,
        kv_shards: int = 1,
    ) -> "CacheConfig":
        """Size the cache against a PER-DEVICE HBM budget:

            num_blocks = budget * kv_shards
                         // (2 * L * block_size * H * D * dtype_bytes)

        (the README's cache-budget sizing formula). ``kv_shards`` is the
        serving mesh's tensor-parallel degree: the cache shards along
        the head axis (generation/sharding.py), so each device holds
        ``H / kv_shards`` heads of every block and the SAME byte budget
        per chip buys ``kv_shards`` x the block count — the whole point
        of sharded serving. Raises when the heads don't divide across
        the shards, or when the budget cannot hold even scratch + one
        usable block.
        """
        from .sharding import validate_kv_shards

        validate_kv_shards(num_heads, kv_shards)
        per_block = 2 * num_layers * block_size * num_heads * head_dim * dtype.size_bytes
        num_blocks = budget_bytes * kv_shards // per_block
        if num_blocks < 2:
            raise ValueError(
                f"cache budget {budget_bytes}B x {kv_shards} shard(s) holds "
                f"{num_blocks} blocks of {per_block}B; need >= 2 "
                f"(scratch + one usable)"
            )
        return cls(
            num_layers=num_layers,
            num_heads=num_heads,
            head_dim=head_dim,
            num_blocks=int(num_blocks),
            block_size=block_size,
            dtype=dtype,
        )

    @classmethod
    def for_slots(
        cls,
        num_layers: int,
        num_heads: int,
        head_dim: int,
        max_seq_len: int,
        max_batch_slots: int,
        block_size: int = 16,
        dtype: DataType = DataType.FLOAT,
        expected_prefix_sharing: float = 0.0,
    ) -> "CacheConfig":
        """Worst-case slot sizing with the sharing-aware discount
        (ROADMAP item 2): the default bound gives every slot room to
        reach ``max_seq_len``, but a fleet of templated traffic shares
        long prompt prefixes through the radix cache
        (generation/prefix.py) and needs far fewer private blocks per
        slot. ``expected_prefix_sharing`` in [0, 1) discounts the
        aggregate bound by the fraction of cache positions expected to
        be shared — 0.5 on a two-template workload roughly halves the
        reservation — floored at one slot's full bound plus one block
        per remaining slot, so a single unshared stream can always run
        to ``max_seq_len`` and every slot can hold at least its COW
        boundary block.
        """
        if not 0.0 <= expected_prefix_sharing < 1.0:
            raise ValueError(
                f"expected_prefix_sharing must be in [0, 1), got "
                f"{expected_prefix_sharing}"
            )
        per_seq = -(-max_seq_len // block_size)
        worst = per_seq * max_batch_slots
        discounted = int(-(-worst * (1.0 - expected_prefix_sharing) // 1))
        floor = per_seq + max(0, max_batch_slots - 1)
        return cls(
            num_layers=num_layers,
            num_heads=num_heads,
            head_dim=head_dim,
            num_blocks=1 + max(floor, discounted),
            block_size=block_size,
            dtype=dtype,
        )


class KVCache:
    """Device storage: ``k``/``v`` of shape [L, num_blocks, block_size,
    H, D]. Functional updates — jitted steps take the arrays and return
    replacements; this object just holds the current ones.

    ``sharding`` (a NamedSharding over the serving mesh, heads sharded —
    generation/sharding.py) commits the arrays across the mesh at
    creation AND at every :meth:`reset`: crash recovery must hand the
    jits a cache with the exact sharding they were compiled for, or the
    first replay step would silently recompile every program."""

    def __init__(self, config: CacheConfig, k: jax.Array, v: jax.Array,
                 sharding=None):
        self.config = config
        self.k = k
        self.v = v
        self.sharding = sharding

    @classmethod
    def create(cls, config: CacheConfig, sharding=None) -> "KVCache":
        shape = (
            config.num_layers,
            config.num_blocks,
            config.block_size,
            config.num_heads,
            config.head_dim,
        )
        zeros = jnp.zeros(shape, config.dtype.jnp)
        if sharding is not None:
            zeros = jax.device_put(zeros, sharding)
        return cls(config, zeros, zeros, sharding=sharding)

    def update(self, k: jax.Array, v: jax.Array) -> None:
        self.k = k
        self.v = v

    def reset(self) -> None:
        """Drop all cached K/V (engine crash recovery): every position is
        rewritten by recompute-replay prefills, and rezeroing also clears
        any NaN a poisoned batch may have written."""
        zeros = jnp.zeros(self.k.shape, self.config.dtype.jnp)
        if self.sharding is not None:
            zeros = jax.device_put(zeros, self.sharding)
        self.k = zeros
        self.v = zeros


class BlockAllocator:
    """Host-side free list over the cache's blocks. Thread-safe: the
    scheduler's admission path and the serving layer's cancellation path
    may free concurrently. Block 0 (scratch) is never handed out.

    Telemetry (obs/capacity.py reads these; all maintained under the
    existing lock so they cost a few integer ops): cumulative
    ``total_allocated`` / ``total_freed`` block counts,
    ``total_reset_reclaimed`` (blocks reclaimed wholesale by
    :meth:`reset` — NOT counted in ``total_freed``, so conservation is
    ``total_allocated == total_freed + total_reset_reclaimed +
    outstanding``), and free-list ``low_water`` / ``high_water`` marks.
    """

    def __init__(self, config: CacheConfig):
        self.config = config
        self._lock = threading.Lock()
        self._free: List[int] = list(range(config.num_blocks - 1, 0, -1))
        self.total_allocated = 0
        self.total_freed = 0
        self.total_reset_reclaimed = 0
        self.low_water = len(self._free)
        self.high_water = len(self._free)

    def reset(self) -> None:
        """Restore the full free list (engine crash recovery): every
        outstanding block table is invalidated wholesale, so per-block
        frees — which would double-free against the fresh list — must
        not follow."""
        with self._lock:
            outstanding = (self.config.num_blocks - 1) - len(self._free)
            self.total_reset_reclaimed += outstanding
            self._free = list(range(self.config.num_blocks - 1, 0, -1))
            self.high_water = len(self._free)

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def num_total(self) -> int:
        return self.config.num_blocks - 1

    def can_allocate(self, n: int) -> bool:
        return self.num_free >= n

    def allocate(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` blocks, or None (atomically — no partial grabs)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        with self._lock:
            if len(self._free) < n:
                return None
            taken, self._free = self._free[:n], self._free[n:]
            self.total_allocated += n
            if len(self._free) < self.low_water:
                self.low_water = len(self._free)
            return taken

    def free(self, blocks: List[int]) -> None:
        with self._lock:
            for b in blocks:
                if b == 0:
                    raise ValueError("block 0 is scratch; it is never allocated")
                if b in self._free:
                    raise ValueError(f"double free of block {b}")
                self._free.append(b)
            self.total_freed += len(blocks)
            if len(self._free) > self.high_water:
                self.high_water = len(self._free)


def slot_mapping(
    block_table: jnp.ndarray, positions: jnp.ndarray, block_size: int
) -> jnp.ndarray:
    """Flat cache slot (block * block_size + offset) for each position.

    ``block_table``: [max_blocks] int32; ``positions``: [...] int32 of
    cache positions. Positions past the table's coverage land in the
    scratch block (block 0) instead of indexing out of bounds — callers
    mask those positions out of attention anyway.
    """
    block_idx = positions // block_size
    offset = positions % block_size
    in_range = block_idx < block_table.shape[0]
    block = jnp.where(in_range, block_table[jnp.clip(block_idx, 0, block_table.shape[0] - 1)], 0)
    return block * block_size + jnp.where(in_range, offset, 0)
