"""flexlint: repo-invariant static analysis for flexflow_tpu.

Every recent PR's review-fix list repeated the same four mechanical bug
classes: shared stats mutated outside their lock, wall-clock /
injectable-clock mixing, stringly-typed fault-site and metric names a
typo silently disables, and host Python that risks retraces or syncs
inside the fixed-shape jit programs. These invariants belong to a
checker that fails CI, not to a reviewer's memory — this package is
that checker.

Rules (ids are the suppression/baseline keys):

  clock-discipline     direct time.time()/monotonic()/perf_counter()
                       outside the whitelist (analysis/config.py)
  lock-discipline      `# guarded-by: <lock>` attributes touched
                       outside `with self.<lock>:`
  jit-discipline       host sync / retrace-risk constructs inside
                       jit-traced functions
  fault-site-registry  inject()/FaultPlan sites + README table vs
                       runtime/faults.py::SITES
  metric-name-registry prom.py families vs the Prometheus golden file
                       + naming/label conventions

Run it: ``python tools/flexlint.py`` (CI gates on exit status; ``--json``
emits the machine-readable report). Suppress a single finding with
``# flexlint: disable=<rule> — <reason>`` on the offending line.

stdlib-only by design (``ast`` + ``re``): the linter must run before —
and regardless of — whether the package's heavy deps import.
"""
from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from .clocks import ClockRule
from .core import (
    Context,
    Finding,
    Report,
    Rule,
    SourceFile,
    load_baseline,
    run_rules,
)
from .faultsites import FaultSiteRule, emit_site_table, parse_registry
from .jitsafety import JitRule
from .locks import LockRule
from .metricnames import MetricNameRule

ALL_RULES: List[Rule] = [
    ClockRule(),
    LockRule(),
    JitRule(),
    FaultSiteRule(),
    MetricNameRule(),
]

DEFAULT_BASELINE = "tools/flexlint_baseline.json"


def rules_by_name(names: Optional[Sequence[str]] = None) -> List[Rule]:
    if not names:
        return list(ALL_RULES)
    by_name = {r.name: r for r in ALL_RULES}
    unknown = [n for n in names if n not in by_name]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}; "
                       f"known: {', '.join(sorted(by_name))}")
    return [by_name[n] for n in names]


def analyze_repo(
    root: Path,
    rule_names: Optional[Sequence[str]] = None,
    baseline_path: Optional[Path] = None,
) -> Report:
    """Run the rule suite over the repo at ``root`` (the entrypoint for
    tools/flexlint.py and the repo-clean meta-test)."""
    ctx = Context(root=root)
    if baseline_path is None:
        baseline_path = root / DEFAULT_BASELINE
    return run_rules(rules_by_name(rule_names), ctx,
                     load_baseline(baseline_path))


def analyze_source(
    text: str,
    relpath: str = "flexflow_tpu/example.py",
    rule_names: Optional[Sequence[str]] = None,
    ctx: Optional[Context] = None,
) -> Report:
    """Run rules over one in-memory file — the fixture seam the
    per-rule tests use."""
    if ctx is None:
        ctx = Context(files=[SourceFile(relpath, text)])
    return run_rules(rules_by_name(rule_names), ctx)


__all__ = [
    "ALL_RULES",
    "ClockRule",
    "Context",
    "DEFAULT_BASELINE",
    "FaultSiteRule",
    "Finding",
    "JitRule",
    "LockRule",
    "MetricNameRule",
    "Report",
    "Rule",
    "SourceFile",
    "analyze_repo",
    "analyze_source",
    "emit_site_table",
    "load_baseline",
    "parse_registry",
    "rules_by_name",
    "run_rules",
]
