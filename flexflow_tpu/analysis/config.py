"""flexlint policy: which files are exempt from which rule, and why.

This file is the reviewed, centralized counterpart to inline
``# flexlint: disable=`` comments: inline suppressions are for single
statements; entries here are for whole files whose PURPOSE exempts them
(a calibration harness exists to measure physical wall time). Every
entry carries its reason so a reviewer can re-litigate it.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Union

# --------------------------------------------------------------- clocks
# Wall-clock whitelist for the clock-discipline rule. Keys are
# repo-relative paths (a trailing "/" whitelists the directory); values
# are "*" (any of time.time / time.monotonic / time.perf_counter) or
# the frozenset of allowed function names. Everything else must take an
# injectable clock so virtual-clock tests control time.
CLOCK_WHITELIST: Dict[str, Union[str, FrozenSet[str]]] = {
    # Offline bench/diagnostic harnesses: measuring physical wall time
    # is their job (genbench/perfwatch/chaoscheck/obsreport/calib_debug
    # / mfu_profile / tpu_evidence), and their watchdog waits bound
    # real blocking calls.
    "tools/": "*",
    # Kernel calibration measures device wall time by definition.
    "flexflow_tpu/search/calibration.py": "*",
    # The op profiler is a physical-time measurement instrument.
    "flexflow_tpu/runtime/profiling.py": "*",
    # PR 6 dual-stamp decision: device-step phase DURATIONS are
    # physical profiling data (perf_counter) even in virtual-clock
    # tests; scheduler-plane timestamps still ride the injectable
    # clock. Only perf_counter is exempt — time.time/monotonic in these
    # files is still a violation.
    "flexflow_tpu/generation/engine.py": frozenset({"perf_counter"}),
    "flexflow_tpu/generation/scheduler.py": frozenset({"perf_counter"}),
    "flexflow_tpu/runtime/executor.py": frozenset({"perf_counter"}),
    # Grammar-compile telemetry (ISSUE 18): compile_seconds is physical
    # profiling data like the engine's phase spans — perf_counter only.
    "flexflow_tpu/generation/constrained/tokens.py": frozenset({"perf_counter"}),
    # Step-anatomy profiler (ISSUE 12): perf_counter-only physical
    # profiling per the PR 6 dual-clock decision — it aggregates the
    # engine/scheduler perf_counter span stamps and must never mix in
    # the scheduler's injectable (possibly virtual) clock.
    "flexflow_tpu/obs/steptrace.py": frozenset({"perf_counter"}),
    # Durable WAL (ISSUE 19): fsync DURATION is physical profiling data
    # (perf_counter only). Journal-record wall stamps ride the
    # injectable wall_clock passed to WriteAheadLog — time.time /
    # monotonic calls in this file are still violations.
    "flexflow_tpu/runtime/wal.py": frozenset({"perf_counter"}),
}

# Paths where clock-discipline runs in STRICT virtual-time mode: ANY
# reference to a real clock — a call, a bare name, an injectable
# default argument, even perf_counter — is a violation, and the
# whitelist above does not apply. The fleet digital twin
# (flexflow_tpu/sim/) is deterministic by contract: its only time
# source is the event loop's virtual clock, and a single real stamp
# breaks byte-identical replay and the simcheck divergence gate.
CLOCK_STRICT_PATHS = ("flexflow_tpu/sim/",)

# ----------------------------------------------------------- fault sites
# Files the fault-site rule does not police: the registry itself (it
# DEFINES the literals) and this analysis package (rule fixtures).
SITE_RULE_EXCLUDE = (
    "flexflow_tpu/runtime/faults.py",
    "flexflow_tpu/analysis/",
)

# Site literals must start with one of these segments to be treated as
# fault-site names when passed to FaultPlan.on(...) (tests register
# synthetic sites like "site.a"; those live under tests/ which is not
# scanned, but the prefix filter also keeps .on(...) of unrelated APIs
# out of this rule's jurisdiction).
SITE_PREFIXES = (
    "executor.", "elastic.", "checkpoint.", "serving.", "generation.",
    "fleet.",
)
