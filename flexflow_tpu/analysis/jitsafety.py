"""jit-discipline: host-Python constructs that break the fixed-shape
single-program contract inside jit-traced function bodies.

The whole serving design (SURVEY.md §2.2) rests on ONE fixed-shape
decode/verify program and zero steady-state retraces — tools/genbench.py
measures that invariant, this rule prevents the code shapes that
violate it from landing at all.

Which functions are "jitted": a function is in scope when it

* contains a ``...note_trace(...)`` call (the engine's traced bodies
  self-register in the ProgramRegistry from INSIDE the trace), or
* is passed by name to ``<registry>.instrument(name, fn)`` (the
  executor's train/eval/forward programs), or
* is referenced by name in a ``jax.jit(...)`` call or decorated with
  ``jax.jit`` / ``partial(jax.jit, ...)``.

Inside such a function the rule flags:

* ``.item()`` — host sync (and a concretization error at trace time),
* ``int(x)`` / ``float(x)`` / ``bool(x)`` on a traced value — host
  concretization; per-value retraces if hoisted to a static,
* ``np.*``/``numpy.*`` calls — host numpy inside a traced body forces
  materialization; use ``jnp``/``jax.lax``,
* ``if``/``while`` on a traced value — Python control flow on tensors
  is a trace-time concretization error (or a retrace per branch when
  fed via a static),
* ``for`` iterating a traced value — unrolls or syncs.

"Traced value" is a lexical taint: the function's parameters, spread
through assignments — except through ``.shape``/``.dtype``/``.ndim``/
``len()``, which yield static Python values at trace time (bucketed
shapes are the engine's dispatch keys and are fine to branch on).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Context, Finding, Rule, SourceFile, attr_chain, call_name

_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}
_CONCRETIZERS = {"int", "float", "bool", "len"}
_NP_ROOTS = {"np", "numpy"}


def _param_names(args: ast.arguments) -> Set[str]:
    """EVERY parameter name: positional-only, positional, keyword-only,
    *args, **kwargs — all are traced values inside a jitted body."""
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg is not None:
        names.add(args.vararg.arg)
    if args.kwarg is not None:
        names.add(args.kwarg.arg)
    return names


def _jit_function_names(tree: ast.AST) -> Set[str]:
    """Names of functions registered for jit elsewhere in the module:
    ``reg.instrument("prog", fn)`` second args and ``jax.jit(fn)`` /
    ``jax.jit(self.fn)`` arguments."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        if cn == "instrument" and len(node.args) >= 2:
            target = node.args[1]
            if isinstance(target, ast.Name):
                names.add(target.id)
            elif isinstance(target, ast.Attribute):
                names.add(target.attr)
        elif cn == "jit" and attr_chain(node.func) in ("jax.jit", "jit"):
            for target in node.args[:1]:
                if isinstance(target, ast.Name):
                    names.add(target.id)
                elif isinstance(target, ast.Attribute):
                    names.add(target.attr)
    return names


def _has_note_trace(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and call_name(node) == "note_trace":
            return True
    return False


def _jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        chain = attr_chain(dec)
        if chain in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            if attr_chain(dec.func) in ("jax.jit", "jit"):
                return True
            if attr_chain(dec.func) in ("partial", "functools.partial"):
                for a in dec.args[:1]:
                    if attr_chain(a) in ("jax.jit", "jit"):
                        return True
    return False


class _TaintChecker(ast.NodeVisitor):
    """Single forward pass over one jitted function body."""

    def __init__(self, rule: "JitRule", src: SourceFile, fn_name: str,
                 tainted: Set[str]):
        self.rule = rule
        self.src = src
        self.fn_name = fn_name
        self.tainted = set(tainted)
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            self.rule.name, self.src.relpath, node.lineno,
            f"in jit-traced `{self.fn_name}`: {what}",
        ))

    def _expr_tainted(self, node: Optional[ast.AST]) -> bool:
        """Any tainted Name reachable without crossing a static-shape
        attribute (.shape/.dtype/...) or len()."""
        if node is None:
            return False
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name):
                if n.id in self.tainted:
                    return True
                continue
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                continue  # static at trace time
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "len"
            ):
                continue  # len() of anything is a static int
            stack.extend(ast.iter_child_nodes(n))
        return False

    def _taint_targets(self, target: ast.AST) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                self.tainted.add(n.id)

    # ------------------------------------------------------- statements
    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        if self._expr_tainted(node.value):
            for t in node.targets:
                self._taint_targets(t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)

    def visit_If(self, node: ast.If) -> None:
        if self._expr_tainted(node.test):
            self._flag(node, "Python `if` on a traced value (host "
                             "concretization / retrace risk); use jnp.where "
                             "or lax.cond")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._expr_tainted(node.test):
            self._flag(node, "Python `while` on a traced value; use "
                             "lax.while_loop")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self._expr_tainted(node.iter):
            self._flag(node, "Python iteration over a traced value "
                             "(unrolls the trace or syncs); use lax.scan "
                             "or vmap")
            self._taint_targets(node.target)  # elements are traced too
        self.generic_visit(node)

    # ------------------------------------------------------------ calls
    def visit_Call(self, node: ast.Call) -> None:
        cn = call_name(node)
        if cn == "item" and isinstance(node.func, ast.Attribute):
            self._flag(node, "`.item()` forces a host sync")
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in _CONCRETIZERS
            and node.func.id != "len"
            and any(self._expr_tainted(a) for a in node.args)
        ):
            self._flag(node, f"`{node.func.id}()` on a traced value "
                             "concretizes at trace time")
        else:
            chain = attr_chain(node.func)
            if chain is not None and chain.split(".")[0] in _NP_ROOTS:
                self._flag(node, f"host numpy call `{chain}` inside a "
                                 "traced body; use jnp/jax.lax")
        self.generic_visit(node)

    # nested defs/lambdas trace inline with the enclosing program: their
    # parameters are traced values too (vmap/scan bodies)
    def _visit_nested(self, node) -> None:
        prev = set(self.tainted)
        self.tainted |= _param_names(node.args)
        if isinstance(node, ast.Lambda):
            self.visit(node.body)
        else:
            for stmt in node.body:
                self.visit(stmt)
        self.tainted = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested(node)


class JitRule(Rule):
    name = "jit-discipline"
    description = (
        "host sync / retrace-risk constructs (.item, int()/float() on "
        "traced values, np.*, Python control flow on tensors) inside "
        "jit-traced functions"
    )

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        for f in ctx.files:
            if f.tree is None:
                continue
            registered = _jit_function_names(f.tree)
            for node in ast.walk(f.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if not (
                    node.name in registered
                    or _has_note_trace(node)
                    or _jit_decorated(node)
                ):
                    continue
                params = _param_names(node.args) - {"self", "cls"}
                checker = _TaintChecker(self, f, node.name, params)
                for stmt in node.body:
                    checker.visit(stmt)
                out.extend(checker.findings)
        return out
