"""lock-discipline: attributes declared ``# guarded-by: <lock>`` may
only be touched inside ``with self.<lock>:`` in the declaring class.

Why: PR 5's review caught the gauges dict mutated outside
ServingStats._lock; PR 8's caught the fleet's folded counters read
outside Fleet._lock. Both were point fixes found by hand. Declaring the
guard next to the attribute turns the whole class into checked
territory.

Mechanics:

* Declaration: a ``self.attr = ...`` assignment whose source line ends
  with ``# guarded-by: <lockname>`` (conventionally in ``__init__``).
* Check: every OTHER method of the class — including the bodies of
  lambdas and nested functions, which execute LATER with no lock held,
  the exact shape of the PR 5 gauge bug — must only read or write
  ``self.attr`` lexically inside ``with self.<lockname>:``.
* Exemptions: ``__init__``/``__post_init__`` (happens-before
  publication) and methods whose name ends in ``_locked`` (the
  documented called-with-lock-held convention, e.g.
  PredictionLedger._evict_one_locked).

Known limits (documented, deliberate): accesses from OUTSIDE the
declaring class and dynamic ``getattr``/``setattr`` field access are
invisible to a lexical checker; keep cross-object reads behind locked
snapshot methods.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from .core import Context, Finding, Rule, SourceFile

# the marker may follow prose in the same comment ("# ring is bounded;
# guarded-by: _lock") — require only that it sits in a comment
GUARD_RE = re.compile(r"#.*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_EXEMPT_METHODS = ("__init__", "__post_init__")


def _self_name(fn: ast.AST) -> str:
    args = getattr(fn, "args", None)
    if args is not None and args.args:
        return args.args[0].arg
    return "self"


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking which guard locks are lexically
    held. Crossing into a Lambda or nested def RESETS the held set:
    those bodies run at some later call time, not under the enclosing
    ``with``."""

    def __init__(self, rule: "LockRule", src: SourceFile, cls: str,
                 guarded: Dict[str, str], self_name: str):
        self.rule = rule
        self.src = src
        self.cls = cls
        self.guarded = guarded
        self.self_name = self_name
        self.held: Set[str] = set()
        self.findings: List[Finding] = []

    def _is_self_attr(self, node: ast.AST, attr: str) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == attr
            and isinstance(node.value, ast.Name)
            and node.value.id == self.self_name
        )

    def visit_With(self, node: ast.With) -> None:
        acquired = set()
        # items evaluate left-to-right with earlier locks already held:
        # in `with self._lock, f(self.guarded):` the second item runs
        # under the lock, so held updates BETWEEN items
        for item in node.items:
            is_lock = False
            for lock in set(self.guarded.values()):
                if self._is_self_attr(item.context_expr, lock):
                    # a lock already held (re-entrant RLock shape) must
                    # not be released when THIS with exits — the outer
                    # with still holds it
                    if lock not in self.held:
                        acquired.add(lock)
                        self.held.add(lock)
                    is_lock = True
            if not is_lock:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= acquired

    def _visit_deferred(self, node: ast.AST) -> None:
        prev, self.held = self.held, set()
        self.generic_visit(node)
        self.held = prev

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_deferred(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_deferred(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_deferred(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == self.self_name
            and node.attr in self.guarded
        ):
            lock = self.guarded[node.attr]
            if lock not in self.held:
                self.findings.append(Finding(
                    self.rule.name, self.src.relpath, node.lineno,
                    f"{self.cls}.{node.attr} is guarded-by {lock} but "
                    f"accessed outside `with self.{lock}:`",
                ))
        self.generic_visit(node)


class LockRule(Rule):
    name = "lock-discipline"
    description = (
        "attributes declared `# guarded-by: <lock>` accessed outside "
        "`with self.<lock>:` in the declaring class"
    )

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        for f in ctx.files:
            if f.tree is None or "guarded-by" not in f.text:
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(f, node))
        return out

    def _declarations(self, src: SourceFile, cls: ast.ClassDef) -> Dict[str, str]:
        """attr -> lock name, from guarded-by comments on self-attribute
        assignment lines anywhere in the class body."""
        guarded: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            # the comment rides the assignment line, or a comment-ONLY
            # line directly above (a trailing comment on the previous
            # statement must not leak onto this one)
            m = GUARD_RE.search(src.line_text(node.lineno))
            if not m:
                above = src.line_text(node.lineno - 1).strip()
                if above.startswith("#"):
                    m = GUARD_RE.search(above)
            if not m:
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                ):
                    guarded[t.attr] = m.group(1)
        return guarded

    def _check_class(self, src: SourceFile, cls: ast.ClassDef) -> List[Finding]:
        guarded = self._declarations(src, cls)
        if not guarded:
            return []
        out: List[Finding] = []
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in _EXEMPT_METHODS or item.name.endswith("_locked"):
                continue
            checker = _MethodChecker(
                self, src, cls.name, guarded, _self_name(item)
            )
            for stmt in item.body:
                checker.visit(stmt)
            out.extend(checker.findings)
        return out
