"""clock-discipline: direct wall-clock reads are forbidden outside the
whitelist — scheduler/SLO/burn-window/fleet code must use its
injectable clock.

Why this is a rule and not a review habit: PR 6's flight-ring audit
found wall-clock and injectable-clock stamps mixed on one timeline,
which produced incoherent interleavings in every virtual-clock test
that touched it. The fix (dual stamps, scheduler-plane code on the
injected clock) only stays fixed if new code cannot silently call
``time.time()`` again.

What counts as a violation: a CALL to ``time.time`` /
``time.monotonic`` / ``time.perf_counter`` (including ``from time
import monotonic`` aliases). A bare REFERENCE as a default argument
(``clock: Callable[[], float] = time.monotonic``) is the injectable
pattern itself and is always allowed.

Exception: under ``CLOCK_STRICT_PATHS`` (the digital twin,
``flexflow_tpu/sim/``) the rule runs in strict virtual-time mode —
ANY reference to a real clock, call or not, perf_counter included, is
a violation and the whitelist does not apply. The sim's determinism
contract (two replays → byte-identical event traces) dies the moment
one real stamp leaks in, and the simcheck gate's sim-vs-live bound
stops meaning anything.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Union

from .config import CLOCK_STRICT_PATHS, CLOCK_WHITELIST
from .core import Context, Finding, Rule, SourceFile

CLOCK_FUNCS = frozenset({"time", "monotonic", "perf_counter"})


def _whitelisted(relpath: str, func: str) -> bool:
    for key, allowed in CLOCK_WHITELIST.items():
        if key.endswith("/"):
            if not relpath.startswith(key):
                continue
        elif relpath != key:
            continue
        if allowed == "*" or func in allowed:
            return True
    return False


class ClockRule(Rule):
    name = "clock-discipline"
    description = (
        "time.time()/monotonic()/perf_counter() calls outside the "
        "whitelist; use the component's injectable clock"
    )

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        for f in ctx.files:
            if f.tree is None:
                continue
            out.extend(self._check_file(f))
        return out

    def _check_file(self, f: SourceFile) -> List[Finding]:
        # names bound by `from time import monotonic [as m]`, and
        # module aliases from `import time [as t]` — an alias must not
        # evade the rule
        aliases: Dict[str, str] = {}
        mod_aliases = {"time"}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in CLOCK_FUNCS:
                        aliases[a.asname or a.name] = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mod_aliases.add(a.asname or a.name)
        if any(f.relpath.startswith(p) for p in CLOCK_STRICT_PATHS):
            return self._check_strict(f, aliases, mod_aliases)
        out: List[Finding] = []
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            func = None
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in mod_aliases
                and node.func.attr in CLOCK_FUNCS
            ):
                func = node.func.attr
            elif isinstance(node.func, ast.Name) and node.func.id in aliases:
                func = aliases[node.func.id]
            if func is None or _whitelisted(f.relpath, func):
                continue
            out.append(Finding(
                self.name, f.relpath, node.lineno,
                f"direct wall-clock call time.{func}(); use the injectable "
                "clock (or whitelist the file in analysis/config.py with a "
                "reason)",
            ))
        return out

    def _check_strict(
        self,
        f: SourceFile,
        aliases: Dict[str, str],
        mod_aliases: FrozenSet[str],
    ) -> List[Finding]:
        """Strict virtual-time mode: every reference counts, imports
        included, whitelist ignored. Flagging the reference (not just
        the call) means even the injectable-default idiom is out —
        the sim has exactly one clock and it is the event loop's."""
        out: List[Finding] = []
        for node in ast.walk(f.tree):
            func = None
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in CLOCK_FUNCS:
                        out.append(Finding(
                            self.name, f.relpath, node.lineno,
                            f"real-clock import time.{a.name} under the "
                            "strict virtual-time path; the sim runs on the "
                            "event loop's virtual clock only",
                        ))
                continue
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in mod_aliases
                and node.attr in CLOCK_FUNCS
            ):
                func = node.attr
            elif isinstance(node, ast.Name) and node.id in aliases:
                func = aliases[node.id]
            if func is None:
                continue
            out.append(Finding(
                self.name, f.relpath, node.lineno,
                f"real-clock reference time.{func} under the strict "
                "virtual-time path (flexflow_tpu/sim/ is deterministic by "
                "contract); use the event loop's virtual clock",
            ))
        return out
