"""metric-name-registry: Prometheus families emitted by obs/prom.py
must be pinned in tests/data/prometheus_golden.txt and follow the
``flexflow_*`` naming/label conventions.

A renamed or typo'd family doesn't crash anything — dashboards and
alerts silently go blank. The golden file is the registry of record
(the observability suite pins the full exposition against it); this
rule closes the loop statically:

1. every literal ``flexflow_*`` family name in obs/prom.py appears in
   the golden file (as a ``# TYPE`` family),
2. every golden family follows the conventions: ``flexflow_`` prefix,
   ``[a-z0-9_]`` names, counters end ``_total``, histogram/summary
   families end ``_seconds``,
3. label names in golden samples are ``[a-z_][a-z0-9_]*``.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List

from .core import Context, Finding, Rule

_FAMILY_RE = re.compile(r"^flexflow_[a-z0-9_]+$")
_TYPE_RE = re.compile(r"^#\s*TYPE\s+(\S+)\s+(\S+)")
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^{}]*)\})?\s")
_LABEL_RE = re.compile(r'([^=,{]+)="')
_LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
# suffixes Prometheus appends to base families in sample lines
_SAMPLE_SUFFIXES = ("_bucket", "_sum", "_count")


def golden_families(golden: str) -> Dict[str, str]:
    """family -> kind from the golden file's # TYPE lines."""
    out: Dict[str, str] = {}
    for line in golden.splitlines():
        m = _TYPE_RE.match(line)
        if m:
            out[m.group(1)] = m.group(2)
    return out


class MetricNameRule(Rule):
    name = "metric-name-registry"
    description = (
        "Prometheus families in obs/prom.py must be pinned in the "
        "golden exposition file and follow flexflow_* conventions"
    )

    def run(self, ctx: Context) -> List[Finding]:
        prom = ctx.prom()
        golden = ctx.golden()
        if prom is None or golden is None:
            missing = Context.PROM_PATH if prom is None else Context.GOLDEN_PATH
            return [Finding(self.name, missing, 1, "file not found")]
        fams = golden_families(golden)
        out: List[Finding] = []
        out.extend(self._check_prom_literals(prom, fams))
        out.extend(self._check_conventions(fams))
        out.extend(self._check_labels(golden))
        return out

    def _check_prom_literals(self, prom: str, fams: Dict[str, str]) -> List[Finding]:
        """Every fully-literal family name in prom.py is golden-pinned.
        Format templates ("flexflow_serving_%s") and prefixes (trailing
        underscore) are skipped — their expansions are pinned by the
        golden test dynamically."""
        out: List[Finding] = []
        try:
            tree = ast.parse(prom)
        except SyntaxError as e:
            return [Finding(self.name, Context.PROM_PATH, e.lineno or 1,
                            f"prom module unparseable: {e.msg}")]
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            s = node.value
            if not s.startswith("flexflow_") or "%" in s or "{" in s:
                continue
            if " " in s or s.endswith("_"):
                # prose (HELP text fragments) / prefix constants used
                # with startswith — not family names
                continue
            base = s
            for suf in _SAMPLE_SUFFIXES:
                if base.endswith(suf) and base[: -len(suf)] in fams:
                    base = base[: -len(suf)]
                    break
            if not _FAMILY_RE.match(base):
                out.append(Finding(
                    self.name, Context.PROM_PATH, node.lineno,
                    f"family {s!r} violates naming convention "
                    "(lowercase [a-z0-9_] only)",
                ))
                continue
            if base not in fams:
                out.append(Finding(
                    self.name, Context.PROM_PATH, node.lineno,
                    f"family {s!r} is not pinned in the golden exposition "
                    "file; add it to tests/data/prometheus_golden.txt "
                    "(regenerate via the golden test) or fix the name",
                ))
        return out

    def _check_conventions(self, fams: Dict[str, str]) -> List[Finding]:
        out: List[Finding] = []
        for fam, kind in sorted(fams.items()):
            if not _FAMILY_RE.match(fam):
                out.append(Finding(
                    self.name, Context.GOLDEN_PATH, 1,
                    f"golden family {fam!r} violates the flexflow_* "
                    "naming convention",
                ))
                continue
            if kind == "counter" and not fam.endswith("_total"):
                out.append(Finding(
                    self.name, Context.GOLDEN_PATH, 1,
                    f"counter family {fam!r} must end in _total",
                ))
            if kind in ("histogram", "summary") and not fam.endswith("_seconds"):
                out.append(Finding(
                    self.name, Context.GOLDEN_PATH, 1,
                    f"{kind} family {fam!r} must end in _seconds "
                    "(all current timing families are in seconds)",
                ))
        return out

    def _check_labels(self, golden: str) -> List[Finding]:
        out: List[Finding] = []
        seen = set()
        for i, line in enumerate(golden.splitlines(), start=1):
            if not line or line.startswith("#"):
                continue
            m = _SAMPLE_RE.match(line)
            if m is None or not m.group(3):
                continue
            for lm in _LABEL_RE.finditer(m.group(3)):
                label = lm.group(1).strip().lstrip(",")
                if label in seen:
                    continue
                seen.add(label)
                if not _LABEL_NAME_RE.match(label):
                    out.append(Finding(
                        self.name, Context.GOLDEN_PATH, i,
                        f"label name {label!r} violates the snake_case "
                        "label convention",
                    ))
        return out
