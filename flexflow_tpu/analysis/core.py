"""flexlint core: the shared visitor/runner framework the rule modules
plug into.

Every rule is a :class:`Rule` with a stable ``name`` (the id used in
suppression comments, baselines, and ``--rules`` filters) and a
``run(ctx)`` returning :class:`Finding` objects. The runner owns the
repo walk, suppression comments, the baseline, and JSON output; rules
own nothing but their invariant.

Suppressions: a finding on line N is suppressed by a
``# flexlint: disable=<rule>[,<rule>...]`` comment on line N (or on
line N-1 when the flagged statement has no room). Suppressions should
carry a one-line reason after the rule list — they are reviewed like
code.

Baseline: grandfathered findings are keyed ``(rule, path, message)``
(line numbers churn; messages are written to be stable). A finding in
the baseline is reported as ``baselined`` and does not fail the run;
the intended steady state of this repo is an EMPTY baseline, with
intentional exemptions carried as inline suppressions instead.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(r"#\s*flexlint:\s*disable=([a-z0-9_,\- ]+)")

# Directories scanned for per-file rules, relative to the repo root.
SCAN_DIRS: Tuple[str, ...] = ("flexflow_tpu", "tools")
_SKIP_PARTS = {"__pycache__", ".git", "build", "dist"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation. ``path`` is repo-relative POSIX; ``message``
    is written to be stable across unrelated edits (no line numbers in
    it) so baselines survive code motion."""

    rule: str
    path: str
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_json(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """A parsed python file plus its per-line suppression sets."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:  # surfaced as a finding by the runner
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        self._suppressions: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                # split on commas AND whitespace: the documented
                # "disable=<rule> — reason" form must keep suppressing
                # when the reason is separated by a plain hyphen/space
                # (stray reason words become harmless non-rule tokens)
                rules = {r for r in re.split(r"[,\s]+", m.group(1)) if r}
                self._suppressions[i] = rules

    def suppressed(self, line: int, rule: str) -> bool:
        """A suppression comment covers its own line, or — when it is a
        comment-ONLY line — the statement below it (a trailing comment
        on the previous statement must not leak downward)."""
        rules = self._suppressions.get(line)
        if rules and (rule in rules or "all" in rules):
            return True
        above = self.line_text(line - 1).strip()
        if above.startswith("#"):
            rules = self._suppressions.get(line - 1)
            if rules and (rule in rules or "all" in rules):
                return True
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Context:
    """Everything a rule may need: the parsed scan set plus lazy repo
    resources (README, the Prometheus golden file, the fault-site
    registry parsed out of runtime/faults.py). Tests override the
    ``*_text`` attributes to run rules against synthetic inputs."""

    README_PATH = "README.md"
    GOLDEN_PATH = "tests/data/prometheus_golden.txt"
    FAULTS_PATH = "flexflow_tpu/runtime/faults.py"
    PROM_PATH = "flexflow_tpu/obs/prom.py"

    def __init__(self, root: Optional[Path] = None,
                 files: Optional[Sequence[SourceFile]] = None):
        self.root = Path(root) if root is not None else None
        self._files: Optional[List[SourceFile]] = (
            list(files) if files is not None else None
        )
        # test seams: assign to override what the repo provides
        self.readme_text: Optional[str] = None
        self.golden_text: Optional[str] = None
        self.faults_source: Optional[str] = None
        self.prom_source: Optional[str] = None

    # ------------------------------------------------------------ files
    @property
    def files(self) -> List[SourceFile]:
        if self._files is None:
            self._files = list(self._walk())
        return self._files

    def _walk(self) -> Iterable[SourceFile]:
        assert self.root is not None, "Context needs a root or explicit files"
        for d in SCAN_DIRS:
            base = self.root / d
            if not base.is_dir():
                continue
            for p in sorted(base.rglob("*.py")):
                if _SKIP_PARTS.intersection(p.parts):
                    continue
                rel = p.relative_to(self.root).as_posix()
                yield SourceFile(rel, p.read_text(encoding="utf-8"))

    def file(self, relpath: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.relpath == relpath:
                return f
        return None

    # -------------------------------------------------------- resources
    def _read(self, relpath: str) -> Optional[str]:
        if self.root is None:
            return None
        p = self.root / relpath
        return p.read_text(encoding="utf-8") if p.is_file() else None

    def readme(self) -> Optional[str]:
        if self.readme_text is None:
            self.readme_text = self._read(self.README_PATH)
        return self.readme_text

    def golden(self) -> Optional[str]:
        if self.golden_text is None:
            self.golden_text = self._read(self.GOLDEN_PATH)
        return self.golden_text

    def faults(self) -> Optional[str]:
        if self.faults_source is None:
            f = self.file(self.FAULTS_PATH)
            self.faults_source = f.text if f else self._read(self.FAULTS_PATH)
        return self.faults_source

    def prom(self) -> Optional[str]:
        if self.prom_source is None:
            f = self.file(self.PROM_PATH)
            self.prom_source = f.text if f else self._read(self.PROM_PATH)
        return self.prom_source


class Rule:
    """Base class; subclasses set ``name``/``description`` and implement
    ``run``. Rules emit EVERY violation they see — suppression and
    baseline filtering happen in the runner, so ``--json`` reports can
    show suppressed counts honestly."""

    name: str = "abstract"
    description: str = ""

    def run(self, ctx: Context) -> List[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # actionable (not suppressed, not baselined)
    suppressed: List[Finding]
    baselined: List[Finding]
    files_scanned: int

    def to_json(self) -> Dict:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "baselined": [f.to_json() for f in self.baselined],
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
        }


def load_baseline(path: Path) -> Set[Tuple[str, str, str]]:
    if not path.is_file():
        return set()
    data = json.loads(path.read_text(encoding="utf-8"))
    return {
        (e["rule"], e["path"], e["message"]) for e in data.get("findings", [])
    }


def run_rules(
    rules: Sequence[Rule],
    ctx: Context,
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
) -> Report:
    """Run every rule, then split raw findings into actionable /
    suppressed / baselined. Unparseable files in the scan set become
    findings themselves (a lint that silently skips broken files hides
    exactly the files most likely to be broken)."""
    baseline = baseline or set()
    raw: List[Finding] = []
    for f in ctx.files:
        if f.parse_error is not None:
            raw.append(Finding("parse", f.relpath, 1, f.parse_error))
    for rule in rules:
        raw.extend(rule.run(ctx))
    raw.sort(key=lambda x: (x.path, x.line, x.rule, x.message))

    by_path = {f.relpath: f for f in ctx.files}
    actionable: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    for fi in raw:
        src = by_path.get(fi.path)
        if src is not None and src.suppressed(fi.line, fi.rule):
            suppressed.append(fi)
        elif fi.key() in baseline:
            baselined.append(fi)
        else:
            actionable.append(fi)
    return Report(actionable, suppressed, baselined, len(ctx.files))


# ---------------------------------------------------------------- helpers
def attr_chain(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """The trailing identifier of the called function (``inject`` for
    both ``inject(...)`` and ``faults.inject(...)``)."""
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
