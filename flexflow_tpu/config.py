"""FFConfig: runtime + search configuration.

Reference: include/flexflow/config.h:92-170 (FFConfig fields) and
src/runtime/model.cc:4027-4170 (parse_args). Field names keep the
reference's flag spellings so existing FlexFlow launch scripts map 1:1;
GPU-specific knobs (workspace sizes, cudnn) become TPU/XLA knobs or
no-ops kept for CLI compatibility.
"""
from __future__ import annotations

import argparse
import dataclasses
from typing import List, Optional, Sequence, Tuple


@dataclasses.dataclass
class FFConfig:
    # training flags (reference: model.cc:4041-4075)
    epochs: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    printing_interval: int = 10
    dataset_path: str = ""
    # machine (reference: -ll:gpu / -ll:cpu / numNodes)
    num_nodes: int = 1
    workers_per_node: int = 0  # 0 -> all local devices
    # search flags (reference: config.h:128-163)
    search_budget: int = 0
    search_alpha: float = 1.05
    only_data_parallel: bool = False
    enable_sample_parallel: bool = True
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    enable_inplace_optimizations: bool = False
    search_overlap_backward_update: bool = False
    search_num_nodes: int = -1
    search_num_workers: int = -1
    base_optimize_threshold: int = 10
    enable_control_replication: bool = True
    substitution_json_path: Optional[str] = None
    memory_search: bool = False
    machine_model_version: int = 0
    machine_model_file: str = ""
    simulator_segment_size: int = 16777216
    simulator_max_num_segments: int = 1
    # None = auto (class-level calibration only); True = measure every
    # uncached candidate op live on the device (reference behavior,
    # operator.h:127); False = purely analytic
    measure_op_costs: Optional[bool] = None
    # pipeline parallelism (new capability; reference's OP_PIPELINE is an
    # unimplemented placeholder, ffconst.h:160)
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0  # 0 -> auto (parallel/strategy.py)
    # activation rematerialization: recompute each repeated block's
    # activations in the backward pass instead of storing them
    # (jax.checkpoint per block) — the TPU-native HBM/FLOPs trade the
    # reference never had; pairs with the memory-aware λ search
    remat_blocks: bool = False
    # iteration-tracing window: fit() scans this many optimizer steps
    # inside ONE XLA program (the reference amortizes per-iteration
    # runtime analysis with Legion traces, begin_trace/end_trace
    # flexflow_cffi.py:2079-2086; here the trace is a lax.scan over
    # stacked batches, which also removes per-step host dispatch —
    # dominant over tunneled/remote device transports). 1 = eager.
    trace_window: int = 1
    # ZeRO-1 optimizer-state sharding over the data axis (beyond-parity:
    # the reference replicates optimizer state everywhere; PS/NCCL only
    # choose the gradient-sync transport, optimizer.cc:200,261)
    zero_optimizer: bool = False
    # gradient accumulation: microbatches per optimizer update (scan of
    # grads; one microbatch's activations live at a time). 1 = off.
    grad_accum_steps: int = 1
    # execution flags
    perform_fusion: bool = False  # XLA fuses regardless; kept for CLI parity
    profiling: bool = False
    allow_tensor_op_math_conversion: bool = True  # -> bf16 matmuls on TPU
    seq_length: Optional[int] = None
    # export flags
    export_strategy_file: str = ""
    import_strategy_file: str = ""
    export_strategy_task_graph_file: str = ""
    export_strategy_computation_graph_file: str = ""
    include_costs_dot_graph: bool = False
    # fork flags (topology-aware allreduce optimization)
    topo_file: str = ""
    iteration: int = 1
    allreduce_optimize: bool = False

    @property
    def num_devices(self) -> int:
        import jax

        per_node = self.workers_per_node or (len(jax.devices()) // max(1, self.num_nodes))
        return max(1, self.num_nodes * per_node)

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "FFConfig":
        """Parse the reference's CLI surface (model.cc:4027)."""
        p = argparse.ArgumentParser("flexflow_tpu", allow_abbrev=False)
        p.add_argument("-e", "--epochs", type=int, default=1)
        p.add_argument("-b", "--batch-size", type=int, default=64)
        p.add_argument("--lr", type=float, default=0.01)
        p.add_argument("--wd", type=float, default=0.0001)
        p.add_argument("-p", "--print-freq", type=int, default=10)
        p.add_argument("-d", "--dataset", type=str, default="")
        p.add_argument("--budget", "--search-budget", dest="budget", type=int, default=0)
        p.add_argument("--alpha", "--search-alpha", dest="alpha", type=float, default=1.05)
        p.add_argument("--only-data-parallel", action="store_true")
        p.add_argument("--enable-parameter-parallel", action="store_true")
        p.add_argument("--enable-attribute-parallel", action="store_true")
        p.add_argument("--enable-inplace-optimizations", action="store_true")
        p.add_argument("--fusion", action="store_true")
        p.add_argument("--profiling", action="store_true")
        p.add_argument("--overlap", action="store_true")
        p.add_argument("--search-num-nodes", type=int, default=-1)
        p.add_argument("--search-num-workers", type=int, default=-1)
        p.add_argument("--base-optimize-threshold", type=int, default=10)
        p.add_argument("--substitution-json", type=str, default=None)
        p.add_argument("--memory-search", action="store_true")
        p.add_argument("--machine-model-version", type=int, default=0)
        p.add_argument("--machine-model-file", type=str, default="")
        p.add_argument("--simulator-segment-size", type=int, default=16777216)
        p.add_argument("--simulator-max-num-segments", type=int, default=1)
        p.add_argument("--export", "--export-strategy", dest="export_strategy", type=str, default="")
        p.add_argument("--import", "--import-strategy", dest="import_strategy", type=str, default="")
        p.add_argument("--taskgraph", type=str, default="")
        p.add_argument("--compgraph", type=str, default="")
        p.add_argument("--include-costs-dot-graph", action="store_true")
        p.add_argument("--pipeline-stages", type=int, default=1)
        p.add_argument("--remat-blocks", action="store_true")
        p.add_argument("--trace-window", type=int, default=1)
        p.add_argument("--zero-optimizer", action="store_true")
        p.add_argument("--grad-accum-steps", type=int, default=1)
        p.add_argument("--pipeline-microbatches", type=int, default=0)
        p.add_argument("--topo-file", type=str, default="")
        p.add_argument("--iteration", type=int, default=1)
        p.add_argument("--nodes", type=int, default=1)
        p.add_argument("--ll:gpu", dest="ll_gpu", type=int, default=0)  # reference CLI parity
        ns, _ = p.parse_known_args(argv)
        return cls(
            epochs=ns.epochs,
            batch_size=ns.batch_size,
            learning_rate=ns.lr,
            weight_decay=ns.wd,
            printing_interval=ns.print_freq,
            dataset_path=ns.dataset,
            num_nodes=ns.nodes,
            workers_per_node=ns.ll_gpu,
            search_budget=ns.budget,
            search_alpha=ns.alpha,
            only_data_parallel=ns.only_data_parallel,
            enable_parameter_parallel=ns.enable_parameter_parallel,
            enable_attribute_parallel=ns.enable_attribute_parallel,
            enable_inplace_optimizations=ns.enable_inplace_optimizations,
            perform_fusion=ns.fusion,
            profiling=ns.profiling,
            search_overlap_backward_update=ns.overlap,
            search_num_nodes=ns.search_num_nodes,
            search_num_workers=ns.search_num_workers,
            base_optimize_threshold=ns.base_optimize_threshold,
            substitution_json_path=ns.substitution_json,
            memory_search=ns.memory_search,
            machine_model_version=ns.machine_model_version,
            machine_model_file=ns.machine_model_file,
            simulator_segment_size=ns.simulator_segment_size,
            simulator_max_num_segments=ns.simulator_max_num_segments,
            export_strategy_file=ns.export_strategy,
            import_strategy_file=ns.import_strategy,
            export_strategy_task_graph_file=ns.taskgraph,
            export_strategy_computation_graph_file=ns.compgraph,
            include_costs_dot_graph=ns.include_costs_dot_graph,
            pipeline_stages=ns.pipeline_stages,
            remat_blocks=ns.remat_blocks,
            trace_window=ns.trace_window,
            zero_optimizer=ns.zero_optimizer,
            grad_accum_steps=ns.grad_accum_steps,
            pipeline_microbatches=ns.pipeline_microbatches,
            topo_file=ns.topo_file,
            iteration=ns.iteration,
        )


@dataclasses.dataclass
class FFIterationConfig:
    """Per-iteration config (reference: config.h:165-170)."""

    seq_length: int = -1

    def reset(self):
        self.seq_length = -1
