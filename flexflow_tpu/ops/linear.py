"""Dense (Linear) operator.

Reference: src/ops/linear.cc (1149 LoC) + kernels/linear_kernels.cu
(cublasGemmEx at linear_kernels.cu:213). TPU-native: a single jnp.dot —
XLA tiles it onto the MXU and fuses bias + activation; no hand-written
GEMM kernel needed. Convention: y = x @ W + b with x[..., in_dim],
W[in_dim, out_dim] (row-major, batch-first; the reference is column-major).
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import ActiMode, DataType, OpType
from .base import LowerCtx, OpCost, OpDef, WeightSpec, io_cost, register_op
from .elementwise import apply_activation


@dataclasses.dataclass(frozen=True)
class LinearParams:
    out_dim: int
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE
    dtype: DataType = DataType.FLOAT
    kernel_initializer: str = "glorot_uniform"
    bias_initializer: str = "zeros"


@register_op
class LinearOp(OpDef):
    op_type = OpType.LINEAR
    params_cls = LinearParams

    @staticmethod
    def infer_output_specs(params: LinearParams, input_specs: List[TensorSpec]) -> List[TensorSpec]:
        (x,) = input_specs
        return [TensorSpec(x.shape[:-1] + (params.out_dim,), params.dtype)]

    @staticmethod
    def weight_specs(params: LinearParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        (x,) = input_specs
        in_dim = x.shape[-1]
        ws = [WeightSpec("kernel", TensorSpec((in_dim, params.out_dim), params.dtype), params.kernel_initializer)]
        if params.use_bias:
            ws.append(WeightSpec("bias", TensorSpec((params.out_dim,), params.dtype), params.bias_initializer))
        return ws

    @staticmethod
    def lower(params: LinearParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        y = jnp.dot(x, weights["kernel"], preferred_element_type=jnp.float32)
        y = y.astype(params.dtype.jnp)
        # manual tensor parallelism (inside shard_map — GPipe stages):
        # a kernel sharded on its INPUT dim is Megatron row-parallel;
        # the local matmul contracted a sharded dim, so the partial
        # outputs reduce over the tp axis before the (replicated) bias
        if ctx.weight_sharded_dim("kernel") == 0:
            y = jax.lax.psum(y, ctx.tp_axis)
        if params.use_bias:
            y = y + weights["bias"]
        return [apply_activation(params.activation, y)]

    @staticmethod
    def cost(params: LinearParams, input_specs, output_specs) -> OpCost:
        (x,) = input_specs
        in_dim = x.shape[-1]
        batch = x.num_elements // in_dim
        flops = 2.0 * batch * in_dim * params.out_dim
        w_bytes = in_dim * params.out_dim * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=flops, extra_mem=w_bytes)
        c.bytes_accessed += w_bytes
        return c
