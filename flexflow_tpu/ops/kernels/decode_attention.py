"""Paged decode/append attention: a window of query tokens per sequence
attending over a block-structured KV cache.

The generation engine's decode step calls this once per layer with a
one-token window (``q`` [B, H, D]); the speculative-verification step
calls the generalized *chunked-append* form with a W = k+1 token window
(``q`` [B, W, H, D]) — the W drafted-window tokens are scored against
the cache in ONE forward instead of W sequential decode steps. Each
window query has its own cache position; masking keeps only cache
positions ``<= q_position`` in its softmax (causal within the window,
full history before it), so chunked verification reproduces the
sequential decode logits exactly. ``q_position < 0`` marks a padding
query (fixed-shape windows with fewer real draft tokens): it attends to
nothing and emits zeros.

Two lowerings:

* :func:`reference_paged_append_attention` — gather the table'd blocks
  and run a masked softmax in plain XLA. This is the CPU/test path and
  the parity oracle. :func:`reference_paged_attention` is its W = 1
  wrapper (the original decode form).
* :func:`paged_append_attention` — a Pallas TPU kernel gridded over
  (batch, cache blocks) with the block tables AND per-query positions
  scalar-prefetched (``pltpu.PrefetchScalarGridSpec``), so each grid
  step DMAs exactly one cache block into VMEM (the PagedAttention
  access pattern) and accumulates per-query online-softmax state in
  scratch across the sequential grid. Out-of-range table entries point
  at the scratch block 0 and are masked, never read out of bounds.
  :func:`paged_decode_attention` is its W = 1 wrapper.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # the XLA reference path below must work without pallas at all
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas-less jax build
    pl = pltpu = None

NEG_INF = -1e30


def on_tpu() -> bool:
    """True on real TPU backends (incl. the tunneled 'axon' platform)."""
    return jax.default_backend() in ("tpu", "axon")


def reference_paged_append_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    scale: Optional[float] = None,
) -> jax.Array:
    """Masked window attention over gathered cache blocks, in plain XLA.

    q: [B, W, H, D] (a W-token append window per sequence, K/V already
    written into the cache); k_cache/v_cache: [num_blocks, block_size,
    H, D]; block_tables: [B, max_blocks] int32; q_positions: [B, W]
    int32 — each window query's cache position. Query (b, w) attends to
    cache positions ``<= q_positions[b, w]`` (its own history including
    itself); ``q_positions[b, w] < 0`` marks a padding query, which
    produces zeros, not NaN. Returns [B, W, H, D].
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bs = k_cache.shape[1]
    b, max_blocks = block_tables.shape
    # [B, max_blocks, bs, H, D] -> [B, S_max, H, D]
    k = k_cache[block_tables].reshape(b, max_blocks * bs, *k_cache.shape[2:])
    v = v_cache[block_tables].reshape(b, max_blocks * bs, *v_cache.shape[2:])
    s = jnp.einsum("bwhd,bkhd->bhwk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]  # key positions
    valid = pos <= q_positions[:, None, :, None]  # [B, 1, W, S_max]
    s = jnp.where(valid, s, NEG_INF)
    # max over an all-masked row is NEG_INF; subtracting keeps exp at 1
    # on masked lanes, so zero the probabilities explicitly instead of
    # relying on exp(-inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhwk,bkhd->bwhd", p / l, v.astype(jnp.float32))
    return out.astype(q.dtype)


def reference_paged_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
) -> jax.Array:
    """One-token (decode) form: q [B, H, D], context_lens [B] int32 (the
    number of valid cache positions INCLUDING the current token's
    already-written K/V; 0 marks an inactive slot). The W = 1 special
    case of :func:`reference_paged_append_attention`."""
    out = reference_paged_append_attention(
        q[:, None], k_cache, v_cache, block_tables, context_lens[:, None] - 1, scale
    )
    return out[:, 0]


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _append_kernel(
    bt_ref,  # scalar-prefetch: [B, max_blocks] block tables
    qpos_ref,  # scalar-prefetch: [B, W] per-query cache positions (-1 = pad)
    q_ref,  # [W, H, D] this sequence's query window
    k_ref,  # [block_size, H, D] the grid step's cache block
    v_ref,  # [block_size, H, D]
    o_ref,  # [W, H, D]
    m_ref,  # scratch [H, W] running max per query
    l_ref,  # scratch [H, W] running denominator per query
    acc_ref,  # scratch [H, W, D] running numerator per query
    *,
    scale,
    block_size,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nblocks = pl.num_programs(1)
    qp = qpos_ref[b, :]  # [W] each query's own cache position
    max_qp = jnp.max(qp)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # whole block past every query's position: nothing to accumulate
    # (its DMA read the scratch block; the data is ignored)
    @pl.when(j * block_size <= max_qp)
    def _accum():
        q = jnp.swapaxes(q_ref[:].astype(jnp.float32), 0, 1) * scale  # [H, W, D]
        k = k_ref[:].astype(jnp.float32)  # [bs, H, D]
        v = v_ref[:].astype(jnp.float32)
        # s[h, w, t] = sum_d q[h, w, d] * k[t, h, d] — batch over H on the MXU
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, W, bs]
        pos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        valid = pos <= qp[None, :, None]  # causal-within-window + history
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]  # [H, W]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.where(valid, jnp.exp(s - m_new[:, :, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1)
        # acc[h, w, d] += sum_t p[h, w, t] * v[t, h, d]
        pv = jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, W, D]
        acc_ref[:] = acc_ref[:] * corr[:, :, None] + pv

    @pl.when(j == nblocks - 1)
    def _finish():
        l = jnp.maximum(l_ref[:], 1e-30)  # [H, W]
        # a padding query (qp < 0) accumulated nothing: emit zeros
        out = jnp.where(qp[None, :, None] >= 0, acc_ref[:] / l[:, :, None], 0.0)
        o_ref[:] = jnp.swapaxes(out, 0, 1).astype(o_ref.dtype)


def _append_kernel_split(
    bt_ref,  # scalar-prefetch: [B, max_blocks] block tables
    qpos_ref,  # scalar-prefetch: [B, W] per-query cache positions (-1 = pad)
    q_ref,  # [W, H, D] this sequence's query window
    k_ref,  # [block_size, H, D] the grid step's cache block
    v_ref,  # [block_size, H, D]
    acc_out_ref,  # [W, H, D] this split's UNNORMALIZED numerator
    m_out_ref,  # [H, W] this split's running max
    l_out_ref,  # [H, W] this split's denominator
    m_ref,  # scratch [H, W]
    l_ref,  # scratch [H, W]
    acc_ref,  # scratch [H, W, D]
    *,
    scale,
    block_size,
    blocks_per_split,
    max_blocks,
):
    """Split-KV (flash-decoding) variant of :func:`_append_kernel`: the
    grid gains a KV-split axis, each split accumulates online-softmax
    state over its contiguous slice of cache blocks INDEPENDENTLY (the
    splits can run in parallel — the sequential-grid data dependence is
    broken), and emits unnormalized partials (acc, m, l) that
    :func:`_combine_splits` recombines exactly. Long-context
    single-stream decode stops serializing over the whole block table."""
    b = pl.program_id(0)
    s = pl.program_id(1)
    j = pl.program_id(2)
    nblocks = pl.num_programs(2)  # blocks per split
    jj = s * blocks_per_split + j  # global block-table column
    qp = qpos_ref[b, :]  # [W] each query's own cache position
    max_qp = jnp.max(qp)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip padding grid steps (the split axis may overshoot the table;
    # their DMA re-read a clamped block — the data is ignored) and
    # whole blocks past every query's position
    @pl.when(jnp.logical_and(jj < max_blocks, jj * block_size <= max_qp))
    def _accum():
        q = jnp.swapaxes(q_ref[:].astype(jnp.float32), 0, 1) * scale  # [H, W, D]
        k = k_ref[:].astype(jnp.float32)  # [bs, H, D]
        v = v_ref[:].astype(jnp.float32)
        s_ = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, W, bs]
        pos = jj * block_size + jax.lax.broadcasted_iota(jnp.int32, s_.shape, 2)
        valid = pos <= qp[None, :, None]
        s_ = jnp.where(valid, s_, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1))
        p = jnp.where(valid, jnp.exp(s_ - m_new[:, :, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )
        acc_ref[:] = acc_ref[:] * corr[:, :, None] + pv

    @pl.when(j == nblocks - 1)
    def _finish():
        # UNNORMALIZED partials out: the cheap [B, S, W, H(, D)] combine
        # in plain XLA finishes the softmax exactly
        m_out_ref[:] = m_ref[:]
        l_out_ref[:] = l_ref[:]
        acc_out_ref[:] = jnp.swapaxes(acc_ref[:], 0, 1).astype(acc_out_ref.dtype)


def _combine_splits(acc, m, l, q_positions, out_dtype):
    """Exact partial-softmax recombination across the KV-split axis.

    acc: [B, S, W, H, D] unnormalized numerators; m/l: [B, S, H, W]
    per-split running max / denominator. An empty split carries
    (m=NEG_INF, l=0, acc=0) and contributes nothing; a padding query
    (q_position < 0) has EVERY split empty and emits zeros, matching
    the single-pass kernel."""
    m = jnp.swapaxes(m, 2, 3)  # [B, S, W, H]
    l = jnp.swapaxes(l, 2, 3)
    m_max = jnp.max(m, axis=1, keepdims=True)  # [B, 1, W, H]
    # all-empty guard: exp(NEG_INF - NEG_INF) is NaN; rescale against 0
    # instead (every alpha then underflows to exp(NEG_INF) = 0)
    safe_max = jnp.where(m_max > NEG_INF / 2, m_max, 0.0)
    alpha = jnp.exp(m - safe_max)  # [B, S, W, H]
    denom = jnp.sum(l * alpha, axis=1)  # [B, W, H]
    numer = jnp.sum(acc * alpha[..., None], axis=1)  # [B, W, H, D]
    out = numer / jnp.maximum(denom, 1e-30)[..., None]
    out = jnp.where(q_positions[:, :, None, None] >= 0, out, 0.0)
    return out.astype(out_dtype)


def paged_append_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    kv_splits: int = 1,
) -> jax.Array:
    """Pallas paged chunked-append attention (shapes as in
    :func:`reference_paged_append_attention`). ``interpret=None``
    auto-selects interpret mode off-TPU so the kernel path is testable
    on CPU. ``kv_splits > 1`` selects the flash-decoding split-KV
    kernel: the cache-block grid axis splits into ``kv_splits``
    independent slices whose partial softmaxes recombine exactly —
    parallelism across the KV length for long-context, small-batch
    decode, where the sequential block grid otherwise serializes the
    whole chip on one sequence's history."""
    if pl is None or pltpu is None:
        return reference_paged_append_attention(
            q, k_cache, v_cache, block_tables, q_positions, scale
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not on_tpu()
    b, w, h, d = q.shape
    _, block_size, _, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    kv_splits = max(1, min(int(kv_splits), max_blocks))
    if kv_splits > 1:
        bps = -(-max_blocks // kv_splits)  # blocks per split (ceil)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, kv_splits, bps),
            in_specs=[
                pl.BlockSpec((None, w, h, d), lambda i, s, j, bt, qp: (i, 0, 0, 0)),
                pl.BlockSpec(
                    (None, block_size, h, d),
                    lambda i, s, j, bt, qp: (
                        bt[i, jnp.minimum(s * bps + j, max_blocks - 1)], 0, 0, 0
                    ),
                ),
                pl.BlockSpec(
                    (None, block_size, h, d),
                    lambda i, s, j, bt, qp: (
                        bt[i, jnp.minimum(s * bps + j, max_blocks - 1)], 0, 0, 0
                    ),
                ),
            ],
            out_specs=[
                pl.BlockSpec(
                    (None, None, w, h, d), lambda i, s, j, bt, qp: (i, s, 0, 0, 0)
                ),
                pl.BlockSpec((None, None, h, w), lambda i, s, j, bt, qp: (i, s, 0, 0)),
                pl.BlockSpec((None, None, h, w), lambda i, s, j, bt, qp: (i, s, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((h, w), jnp.float32),
                pltpu.VMEM((h, w), jnp.float32),
                pltpu.VMEM((h, w, d), jnp.float32),
            ],
        )
        kernel = functools.partial(
            _append_kernel_split, scale=float(scale), block_size=block_size,
            blocks_per_split=bps, max_blocks=max_blocks,
        )
        acc, m, l = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((b, kv_splits, w, h, d), jnp.float32),
                jax.ShapeDtypeStruct((b, kv_splits, h, w), jnp.float32),
                jax.ShapeDtypeStruct((b, kv_splits, h, w), jnp.float32),
            ],
            interpret=interpret,
        )(
            block_tables.astype(jnp.int32), q_positions.astype(jnp.int32),
            q, k_cache, v_cache,
        )
        return _combine_splits(acc, m, l, q_positions.astype(jnp.int32), q.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=[
            pl.BlockSpec((None, w, h, d), lambda i, j, bt, qp: (i, 0, 0, 0)),
            pl.BlockSpec((None, block_size, h, d), lambda i, j, bt, qp: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((None, block_size, h, d), lambda i, j, bt, qp: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, w, h, d), lambda i, j, bt, qp: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, w), jnp.float32),
            pltpu.VMEM((h, w), jnp.float32),
            pltpu.VMEM((h, w, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_append_kernel, scale=float(scale), block_size=block_size)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, w, h, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), q_positions.astype(jnp.int32), q, k_cache, v_cache)


def default_kv_splits(batch: int, max_blocks: int) -> int:
    """Flash-decoding split heuristic: split the KV axis only where the
    sequential block grid is the bottleneck — small batch (little
    batch-axis parallelism) over a long table. Capped so each split
    still covers >= 4 blocks (partials below that are overhead-bound).

    STATIC shapes only: the grid must be fixed at trace time, so
    ``batch`` is the engine's padded slot count and ``max_blocks`` its
    table width — NOT live occupancy or live context. The auto path
    therefore engages for <=2-slot engine configurations (the dedicated
    long-context single-stream deployment shape flash-decoding exists
    for); wider-batch engines can opt in explicitly via ``kv_splits``,
    accepting the recombination overhead when their batches run
    under-occupied."""
    if batch > 2 or max_blocks < 16:
        return 1
    return max(1, min(8, max_blocks // 4))


def paged_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    kv_splits: Optional[int] = None,
) -> jax.Array:
    """One-token (decode) form of :func:`paged_append_attention`
    (shapes as in :func:`reference_paged_attention`). ``kv_splits``
    None auto-selects via :func:`default_kv_splits` — the
    flash-decoding path for long-context single/dual-stream decode."""
    if kv_splits is None:
        kv_splits = default_kv_splits(q.shape[0], block_tables.shape[1])
    out = paged_append_attention(
        q[:, None],
        k_cache,
        v_cache,
        block_tables,
        context_lens[:, None] - 1,
        scale=scale,
        interpret=interpret,
        kv_splits=kv_splits,
    )
    return out[:, 0]


def sharded_paged_append_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    mesh,
    axis: str = "model",
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    kv_splits: int = 1,
) -> jax.Array:
    """Head-sharded paged append attention over a serving mesh (ISSUE
    15): each shard runs the SINGLE-DEVICE Pallas kernel over its local
    slice of KV heads — attention is embarrassingly parallel across
    heads, so no collective runs inside the kernel at all. The one
    cross-shard boundary lives at the attention OUTPUT projection,
    where the decoder's head-sharded ``wo`` contraction produces
    partial sums and GSPMD inserts the psum (the same reduction
    ops/parallel_ops.py's ``ReductionOp`` annotates in the training
    path). Shapes as in :func:`paged_append_attention`; ``q`` is
    [B, W, H, D] with H sharded on ``axis``, the caches shard their head
    dim, tables/positions are replicated, and the output keeps H
    sharded.

    ``scale`` must be passed explicitly when H is sharded — the default
    would be computed from a LOCAL shape inside shard_map; head_dim is
    unsharded so the usual ``d ** -0.5`` default stays correct."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = q.shape[-1] ** -0.5

    def local(q_, k_, v_, bt_, qp_):
        return paged_append_attention(
            q_, k_, v_, bt_, qp_, scale=scale, interpret=interpret,
            kv_splits=kv_splits,
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(None, None, axis, None),  # q [B, W, H, D]
            P(None, None, axis, None),  # k_cache [nb, bs, H, D]
            P(None, None, axis, None),  # v_cache
            P(None, None),  # block_tables (replicated)
            P(None, None),  # q_positions (replicated)
        ),
        out_specs=P(None, None, axis, None),
        check_rep=False,
    )(q, k_cache, v_cache, block_tables, q_positions)


def sharded_paged_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    mesh,
    axis: str = "model",
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    kv_splits: Optional[int] = None,
) -> jax.Array:
    """One-token (decode) form of :func:`sharded_paged_append_attention`
    (q [B, H, D], H sharded on ``axis``)."""
    if kv_splits is None:
        kv_splits = default_kv_splits(q.shape[0], block_tables.shape[1])
    out = sharded_paged_append_attention(
        q[:, None],
        k_cache,
        v_cache,
        block_tables,
        context_lens[:, None] - 1,
        mesh,
        axis=axis,
        scale=scale,
        interpret=interpret,
        kv_splits=kv_splits,
    )
    return out[:, 0]


def supports_decode_shapes(num_heads: int, head_dim: int, block_size: int) -> bool:
    """Shapes the TPU kernel handles without falling back: lane-multiple
    head_dim and a sublane-multiple block size."""
    return head_dim in (64, 128, 256) and block_size % 8 == 0 and num_heads >= 1


def supports_append_shapes(
    num_heads: int, head_dim: int, block_size: int, window: int
) -> bool:
    """Append-window shapes the TPU kernel handles without falling back:
    the decode constraints plus a bounded window (the per-query scratch
    is [H, W, D] in VMEM; tiny speculative windows always fit)."""
    return (
        supports_decode_shapes(num_heads, head_dim, block_size) and 1 <= window <= 32
    )
