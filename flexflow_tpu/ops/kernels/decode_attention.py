"""Paged decode attention: one query token per sequence attending over a
block-structured KV cache.

The generation engine's decode step calls this once per layer: ``q`` is
[B, H, D] (the token being decoded, one per batch slot), and the cached
K/V live in the block-structured cache (generation/cache.py) as
[num_blocks, block_size, H, D] per layer, indexed per sequence through a
block table. Position masking keeps only cache positions
``< context_len`` in the softmax, so incremental decode reproduces the
full-context causal logits exactly.

Two lowerings:

* :func:`reference_paged_attention` — gather the table'd blocks and run
  a masked softmax in plain XLA. This is the CPU/test path and the
  parity oracle.
* :func:`paged_decode_attention` — a Pallas TPU kernel gridded over
  (batch, cache blocks) with the block tables scalar-prefetched
  (``pltpu.PrefetchScalarGridSpec``), so each grid step DMAs exactly
  one cache block into VMEM (the PagedAttention access pattern) and
  accumulates online-softmax state in scratch across the sequential
  grid. Out-of-range table entries point at the scratch block 0 and are
  masked, never read out of bounds.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # the XLA reference path below must work without pallas at all
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas-less jax build
    pl = pltpu = None

NEG_INF = -1e30


def on_tpu() -> bool:
    """True on real TPU backends (incl. the tunneled 'axon' platform)."""
    return jax.default_backend() in ("tpu", "axon")


def reference_paged_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
) -> jax.Array:
    """Masked attention over gathered cache blocks, in plain XLA.

    q: [B, H, D]; k_cache/v_cache: [num_blocks, block_size, H, D];
    block_tables: [B, max_blocks] int32; context_lens: [B] int32
    (number of valid cache positions, INCLUDING the current token's
    already-written K/V). Returns [B, H, D]. Sequences with
    context_len == 0 (inactive slots) produce zeros, not NaN.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bs = k_cache.shape[1]
    b, max_blocks = block_tables.shape
    # [B, max_blocks, bs, H, D] -> [B, S_max, H, D]
    k = k_cache[block_tables].reshape(b, max_blocks * bs, *k_cache.shape[2:])
    v = v_cache[block_tables].reshape(b, max_blocks * bs, *v_cache.shape[2:])
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, :]
    valid = pos < context_lens[:, None, None]
    s = jnp.where(valid, s, NEG_INF)
    # max over an all-masked row is NEG_INF; subtracting keeps exp at 1
    # on masked lanes, so zero the probabilities explicitly instead of
    # relying on exp(-inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhk,bkhd->bhd", p / l, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _decode_kernel(
    bt_ref,  # scalar-prefetch: [B, max_blocks] block tables
    len_ref,  # scalar-prefetch: [B] context lens
    q_ref,  # [H, D] this sequence's query
    k_ref,  # [block_size, H, D] the grid step's cache block
    v_ref,  # [block_size, H, D]
    o_ref,  # [H, D]
    m_ref,  # scratch [H, 1] running max
    l_ref,  # scratch [H, 1] running denominator
    acc_ref,  # scratch [H, D] running numerator
    *,
    scale,
    block_size,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nblocks = pl.num_programs(1)
    ctx = len_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # whole block past the context: nothing to accumulate (its DMA read
    # the scratch block; the data is ignored)
    @pl.when(j * block_size < ctx)
    def _accum():
        q = q_ref[:].astype(jnp.float32) * scale  # [H, D]
        k = k_ref[:].astype(jnp.float32)  # [bs, H, D]
        v = v_ref[:].astype(jnp.float32)
        # s[h, t] = sum_d q[h, d] * k[t, h, d] — batch over H on the MXU
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, bs]
        pos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ctx, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(pos < ctx, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        # acc[h, d] += sum_t p[h, t] * v[t, h, d]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, D]
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(j == nblocks - 1)
    def _finish():
        l = jnp.maximum(l_ref[:], 1e-30)
        # an inactive slot (ctx == 0) accumulated nothing: emit zeros
        out = jnp.where(ctx > 0, acc_ref[:] / l, 0.0)
        o_ref[:] = out.astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Pallas paged decode attention (shapes as in
    :func:`reference_paged_attention`). ``interpret=None`` auto-selects
    interpret mode off-TPU so the kernel path is testable on CPU."""
    if pl is None or pltpu is None:
        return reference_paged_attention(q, k_cache, v_cache, block_tables, context_lens, scale)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not on_tpu()
    b, h, d = q.shape
    _, block_size, _, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda i, j, bt, ln: (i, 0, 0)),
            pl.BlockSpec((None, block_size, h, d), lambda i, j, bt, ln: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((None, block_size, h, d), lambda i, j, bt, ln: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda i, j, bt, ln: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=float(scale), block_size=block_size)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32), q, k_cache, v_cache)


def supports_decode_shapes(num_heads: int, head_dim: int, block_size: int) -> bool:
    """Shapes the TPU kernel handles without falling back: lane-multiple
    head_dim and a sublane-multiple block size."""
    return head_dim in (64, 128, 256) and block_size % 8 == 0 and num_heads >= 1
