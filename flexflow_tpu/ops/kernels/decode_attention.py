"""Paged decode/append attention: a window of query tokens per sequence
attending over a block-structured KV cache.

The generation engine's decode step calls this once per layer with a
one-token window (``q`` [B, H, D]); the speculative-verification step
calls the generalized *chunked-append* form with a W = k+1 token window
(``q`` [B, W, H, D]) — the W drafted-window tokens are scored against
the cache in ONE forward instead of W sequential decode steps. Each
window query has its own cache position; masking keeps only cache
positions ``<= q_position`` in its softmax (causal within the window,
full history before it), so chunked verification reproduces the
sequential decode logits exactly. ``q_position < 0`` marks a padding
query (fixed-shape windows with fewer real draft tokens): it attends to
nothing and emits zeros.

Two lowerings:

* :func:`reference_paged_append_attention` — gather the table'd blocks
  and run a masked softmax in plain XLA. This is the CPU/test path and
  the parity oracle. :func:`reference_paged_attention` is its W = 1
  wrapper (the original decode form).
* :func:`paged_append_attention` — a Pallas TPU kernel gridded over
  (batch, cache blocks) with the block tables AND per-query positions
  scalar-prefetched (``pltpu.PrefetchScalarGridSpec``), so each grid
  step DMAs exactly one cache block into VMEM (the PagedAttention
  access pattern) and accumulates per-query online-softmax state in
  scratch across the sequential grid. Out-of-range table entries point
  at the scratch block 0 and are masked, never read out of bounds.
  :func:`paged_decode_attention` is its W = 1 wrapper.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # the XLA reference path below must work without pallas at all
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas-less jax build
    pl = pltpu = None

NEG_INF = -1e30


def on_tpu() -> bool:
    """True on real TPU backends (incl. the tunneled 'axon' platform)."""
    return jax.default_backend() in ("tpu", "axon")


def reference_paged_append_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    scale: Optional[float] = None,
) -> jax.Array:
    """Masked window attention over gathered cache blocks, in plain XLA.

    q: [B, W, H, D] (a W-token append window per sequence, K/V already
    written into the cache); k_cache/v_cache: [num_blocks, block_size,
    H, D]; block_tables: [B, max_blocks] int32; q_positions: [B, W]
    int32 — each window query's cache position. Query (b, w) attends to
    cache positions ``<= q_positions[b, w]`` (its own history including
    itself); ``q_positions[b, w] < 0`` marks a padding query, which
    produces zeros, not NaN. Returns [B, W, H, D].
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bs = k_cache.shape[1]
    b, max_blocks = block_tables.shape
    # [B, max_blocks, bs, H, D] -> [B, S_max, H, D]
    k = k_cache[block_tables].reshape(b, max_blocks * bs, *k_cache.shape[2:])
    v = v_cache[block_tables].reshape(b, max_blocks * bs, *v_cache.shape[2:])
    s = jnp.einsum("bwhd,bkhd->bhwk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]  # key positions
    valid = pos <= q_positions[:, None, :, None]  # [B, 1, W, S_max]
    s = jnp.where(valid, s, NEG_INF)
    # max over an all-masked row is NEG_INF; subtracting keeps exp at 1
    # on masked lanes, so zero the probabilities explicitly instead of
    # relying on exp(-inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhwk,bkhd->bwhd", p / l, v.astype(jnp.float32))
    return out.astype(q.dtype)


def reference_paged_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
) -> jax.Array:
    """One-token (decode) form: q [B, H, D], context_lens [B] int32 (the
    number of valid cache positions INCLUDING the current token's
    already-written K/V; 0 marks an inactive slot). The W = 1 special
    case of :func:`reference_paged_append_attention`."""
    out = reference_paged_append_attention(
        q[:, None], k_cache, v_cache, block_tables, context_lens[:, None] - 1, scale
    )
    return out[:, 0]


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _append_kernel(
    bt_ref,  # scalar-prefetch: [B, max_blocks] block tables
    qpos_ref,  # scalar-prefetch: [B, W] per-query cache positions (-1 = pad)
    q_ref,  # [W, H, D] this sequence's query window
    k_ref,  # [block_size, H, D] the grid step's cache block
    v_ref,  # [block_size, H, D]
    o_ref,  # [W, H, D]
    m_ref,  # scratch [H, W] running max per query
    l_ref,  # scratch [H, W] running denominator per query
    acc_ref,  # scratch [H, W, D] running numerator per query
    *,
    scale,
    block_size,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nblocks = pl.num_programs(1)
    qp = qpos_ref[b, :]  # [W] each query's own cache position
    max_qp = jnp.max(qp)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # whole block past every query's position: nothing to accumulate
    # (its DMA read the scratch block; the data is ignored)
    @pl.when(j * block_size <= max_qp)
    def _accum():
        q = jnp.swapaxes(q_ref[:].astype(jnp.float32), 0, 1) * scale  # [H, W, D]
        k = k_ref[:].astype(jnp.float32)  # [bs, H, D]
        v = v_ref[:].astype(jnp.float32)
        # s[h, w, t] = sum_d q[h, w, d] * k[t, h, d] — batch over H on the MXU
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, W, bs]
        pos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        valid = pos <= qp[None, :, None]  # causal-within-window + history
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]  # [H, W]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.where(valid, jnp.exp(s - m_new[:, :, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1)
        # acc[h, w, d] += sum_t p[h, w, t] * v[t, h, d]
        pv = jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [H, W, D]
        acc_ref[:] = acc_ref[:] * corr[:, :, None] + pv

    @pl.when(j == nblocks - 1)
    def _finish():
        l = jnp.maximum(l_ref[:], 1e-30)  # [H, W]
        # a padding query (qp < 0) accumulated nothing: emit zeros
        out = jnp.where(qp[None, :, None] >= 0, acc_ref[:] / l[:, :, None], 0.0)
        o_ref[:] = jnp.swapaxes(out, 0, 1).astype(o_ref.dtype)


def paged_append_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Pallas paged chunked-append attention (shapes as in
    :func:`reference_paged_append_attention`). ``interpret=None``
    auto-selects interpret mode off-TPU so the kernel path is testable
    on CPU."""
    if pl is None or pltpu is None:
        return reference_paged_append_attention(
            q, k_cache, v_cache, block_tables, q_positions, scale
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not on_tpu()
    b, w, h, d = q.shape
    _, block_size, _, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=[
            pl.BlockSpec((None, w, h, d), lambda i, j, bt, qp: (i, 0, 0, 0)),
            pl.BlockSpec((None, block_size, h, d), lambda i, j, bt, qp: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((None, block_size, h, d), lambda i, j, bt, qp: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, w, h, d), lambda i, j, bt, qp: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, w), jnp.float32),
            pltpu.VMEM((h, w), jnp.float32),
            pltpu.VMEM((h, w, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_append_kernel, scale=float(scale), block_size=block_size)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, w, h, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), q_positions.astype(jnp.int32), q, k_cache, v_cache)


def paged_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """One-token (decode) form of :func:`paged_append_attention`
    (shapes as in :func:`reference_paged_attention`)."""
    out = paged_append_attention(
        q[:, None],
        k_cache,
        v_cache,
        block_tables,
        context_lens[:, None] - 1,
        scale=scale,
        interpret=interpret,
    )
    return out[:, 0]


def supports_decode_shapes(num_heads: int, head_dim: int, block_size: int) -> bool:
    """Shapes the TPU kernel handles without falling back: lane-multiple
    head_dim and a sublane-multiple block size."""
    return head_dim in (64, 128, 256) and block_size % 8 == 0 and num_heads >= 1


def supports_append_shapes(
    num_heads: int, head_dim: int, block_size: int, window: int
) -> bool:
    """Append-window shapes the TPU kernel handles without falling back:
    the decode constraints plus a bounded window (the per-query scratch
    is [H, W, D] in VMEM; tiny speculative windows always fit)."""
    return (
        supports_decode_shapes(num_heads, head_dim, block_size) and 1 <= window <= 32
    )
