"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Green-field capability (SURVEY §2.2 / §5: the reference has NO sequence
parallelism — only seq_length iteration plumbing, config.h:165-170). Two
TPU-native schemes over the ICI torus:

  * **Ring attention**: Q stays put; K/V chunks rotate around the "seq"
    mesh axis via ``jax.lax.ppermute`` (neighbor hops on the ICI ring),
    merging per-chunk partial attention with the online-softmax rule.
    HBM footprint per chip is O(S/n); comm overlaps compute on the torus.
  * **Ulysses**: all-to-all swaps sequence sharding for head sharding,
    runs full-sequence attention on 1/n of the heads locally, and swaps
    back. One all-to-all each way; good when heads >= mesh axis size.

Both are pure-JAX (differentiable through scan/ppermute); the per-chunk
core uses the same blockwise algebra as the Pallas flash kernel.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax>=0.8 promotes shard_map out of experimental (check_rep -> check_vma)
    from jax import shard_map as _shard_map  # type: ignore

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_rep)

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

NEG_INF = -1e30


def _chunk_attend(q, k, v, scale, mask):
    """Blockwise partial attention: returns (m, l, o_unnormalized).

    q: [B, Sq, H, D]; k, v: [B, Sc, H, D]; mask: [Sq, Sc] bool or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: make their contribution exactly zero
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)  # [B,H,Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over an SPMD axis (call inside shard_map).

    q, k, v: local shards [B, S_local, H, D]; every device holds one
    sequence chunk. K/V rotate ``n`` times around the ring.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    sk_local = k.shape[1]  # may differ from s_local for cross-attention
    qf = q.astype(jnp.float32)
    q_pos = my * s_local + jnp.arange(s_local)  # global positions of local q

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(m, l, acc, kc, vc, t):
        src_chunk = (my - t) % n  # which global chunk we currently hold
        if causal:
            k_pos = src_chunk * sk_local + jnp.arange(sk_local)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        mc, lc, oc = _chunk_attend(qf, kc.astype(jnp.float32), vc, scale, mask)
        m_new = jnp.maximum(m, mc)
        # guard -inf - -inf when a row has seen nothing yet
        c_old = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        c_new = jnp.where(mc <= NEG_INF / 2, 0.0, jnp.exp(mc - m_new))
        l_out = l * c_old + lc * c_new
        acc_out = acc * jnp.swapaxes(c_old, 1, 2)[..., None] + oc * jnp.swapaxes(c_new, 1, 2)[..., None]
        return m_new, l_out, acc_out

    def step(carry, t):
        kc, vc, m, l, acc = carry
        m, l, acc = attend(m, l, acc, kc, vc, t)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, m, l, acc), None

    # derive the scan inits from q (x*0 keeps the value exact for finite
    # x) so they inherit q's varying manual axes — fresh zeros would be
    # invarying and reject the scan carry under nested shard_map vma
    # tracking (the pp x cp composition runs this inside the pipeline's
    # shard_map)
    zero_bhs = jnp.swapaxes(qf, 1, 2)[..., 0] * 0.0
    m0 = zero_bhs + NEG_INF
    l0 = zero_bhs
    acc0 = qf * 0.0
    # n-1 rotating steps, then attend to the last-held chunk without the
    # final ppermute pair (whose result would be discarded)
    (kc, vc, m, l, acc), _ = jax.lax.scan(step, (k, v, m0, l0, acc0), jnp.arange(n - 1))
    m, l, acc = attend(m, l, acc, kc, vc, n - 1)
    l = jnp.maximum(l, 1e-30)
    out = acc / jnp.swapaxes(l, 1, 2)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    batch_axis: Optional[str] = "data",
    causal: bool = False,
    scale: Optional[float] = None,
    head_axis: Optional[str] = None,
) -> jax.Array:
    """shard_map wrapper: [B, S, H, D] globally, S sharded on ``seq_axis``.

    ``head_axis``: keep the head dim sharded through the kernel (cp x tp
    composition — head-sharded projections from Megatron weights would
    otherwise be all-gathered at this boundary)."""
    ba = batch_axis if batch_axis in mesh.axis_names else None
    ha = head_axis if head_axis in mesh.axis_names else None
    spec = P(ba, seq_axis, ha, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn=None,
) -> jax.Array:
    """Ulysses (all-to-all) sequence parallelism (call inside shard_map).

    Local shards [B, S/n, H, D] -> all_to_all -> [B, S, H/n, D] -> local
    full-sequence attention -> all_to_all back. ``attn_fn(q, k, v)`` runs
    the local attention (defaults to the blockwise core; on TPU the Pallas
    flash kernel slots in).
    """
    n = jax.lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn_fn is None:
        from ..attention import reference_attention

        attn_fn = functools.partial(reference_attention, causal=causal, scale=scale)
    out = attn_fn(qh, kh, vh)
    return heads_to_seq(out)


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    batch_axis: Optional[str] = "data",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    ba = batch_axis if batch_axis in mesh.axis_names else None
    spec = P(ba, seq_axis, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=seq_axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)
