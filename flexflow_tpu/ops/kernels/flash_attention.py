"""Pallas TPU flash attention (forward + backward).

The reference's attention is a monolithic cuDNN call
(src/ops/attention.cu:35 cudnnMultiHeadAttnForward) with no long-context
story (SURVEY §2.2: no ring/blockwise attention anywhere). This kernel is
the TPU-native replacement for the attention core: online-softmax
blockwise attention that never materializes the [Sq, Sk] score matrix in
HBM, keeping the working set in VMEM and the matmuls on the MXU.

Layout: [B, H, S, D] inside the kernels (batch*heads on the grid's first
axes, sequence blocked on the last); the public API takes [B, S, H, D] to
match ops/attention.py.

Backward follows the FlashAttention-2 decomposition: residuals are the
output O and the per-row logsumexp L; dQ is computed by a kernel gridded
over Q blocks, dK/dV by a kernel gridded over KV blocks.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def on_tpu() -> bool:
    """True on real TPU backends (incl. the tunneled 'axon' platform)."""
    return jax.default_backend() in ("tpu", "axon")

# Sequence block sizes. 128 matches the MXU systolic dimension, but the
# round-5 on-chip sweep (BENCH_TPU_evidence_r5.json, seq 512) measured
# 256x256 blocks 1.49x faster than 128x128 (fewer grid invocations and
# online-softmax rescale passes per output row), while 512x512 never
# finished compiling inside a 20-minute child budget. Default policy:
# the largest block in _BLOCK_CANDIDATES that divides the sequence, so
# long sequences get the measured winner and seq 128 keeps 128.
# Env-overridable (FF_FLASH_BLOCK_Q/K) for sweeps across clean child
# processes; read once at import; malformed values fall back to the
# adaptive policy rather than breaking every import of the package.
import os as _os

_BLOCK_CANDIDATES = (256, 128)


def _env_block(name: str) -> Optional[int]:
    raw = _os.environ.get(name)
    if raw is None:
        return None
    try:
        v = int(raw)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


ENV_BLOCK_Q = _env_block("FF_FLASH_BLOCK_Q")
ENV_BLOCK_K = _env_block("FF_FLASH_BLOCK_K")


def pick_block(seq: int, env: Optional[int]) -> int:
    """Effective block for a sequence length: the env override clamped
    to the sequence, else the largest default candidate dividing it,
    else the largest power-of-two divisor (a non-dividing block would
    leave sq // bq grid steps covering only a prefix of the rows)."""
    if env is not None:
        return min(env, seq)
    for b in _BLOCK_CANDIDATES + (64, 32, 16, 8):
        if seq >= b and seq % b == 0:
            return b
    return seq


def effective_blocks(sq: int, sk: int) -> Tuple[int, int]:
    return pick_block(sq, ENV_BLOCK_Q), pick_block(sk, ENV_BLOCK_K)


def supports_shapes(q_shape: Tuple[int, ...], k_shape: Tuple[int, ...]) -> bool:
    """Shapes the kernel handles without falling back: head_dim a lane
    multiple and sequence lengths divisible by the block size."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    _, sq, _, d = q_shape
    _, sk, _, _ = k_shape
    if d not in (64, 128, 256):
        return False
    bq, bk = effective_blocks(sq, sk)
    # sequence lengths must tile into blocks and respect the (8, 128)
    # sublane/lane tiling of the TPU vector memory
    return sq % bq == 0 and sk % bk == 0 and sq % 8 == 0 and sk % 8 == 0 and sq >= 8 and sk >= 8


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k, sk):
    # q_ref: [bq, d]; k_ref/v_ref: [sk, d] (whole key sequence for this head)
    bq, d = q_ref.shape
    iq = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32) * scale
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    nk = sk // block_k

    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    if causal:
        # skip key blocks entirely above the diagonal
        nk_eff = jnp.minimum(nk, (iq + 1) * bq // block_k + 1)
    else:
        nk_eff = nk
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m, l, acc))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)  # [bq, 1]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    # q,k,v: [B, H, S, D]
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq or sk % bk:
        # a non-dividing block would silently compute only the first
        # (sq // bq) * bq query rows — fail loudly instead
        raise ValueError(
            f"sequence lengths ({sq}, {sk}) not divisible by blocks ({bq}, {bk})"
        )
    grid = (b, h, sq // bq)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal, block_k=bk, sk=sk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((None, None, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            pl.BlockSpec((None, None, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((None, None, bq, 1), lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, causal, block_k, sk):
    bq, d = q_ref.shape
    iq = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]  # [bq, 1]
    delta = delta_ref[:]
    dq = jnp.zeros((bq, d), jnp.float32)
    nk = sk // block_k
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        nk_eff = jnp.minimum(nk, (iq + 1) * bq // block_k + 1)
    else:
        nk_eff = nk
    dq = jax.lax.fori_loop(0, nk_eff, body, dq)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, causal, block_q, sq):
    bk, d = k_ref.shape
    jk = pl.program_id(2)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    nq = sq // block_q
    k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :]  # [bq, 1]
        delta = delta_ref[pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # query blocks strictly below this key block see nothing
        start = jk * bk // block_q
    else:
        start = 0
    dk, dv = jax.lax.fori_loop(start, nq, body, (dk, dv))
    # q entered the loop pre-scaled, so dk = scale * dS^T Q already
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, block_q, block_k, interpret):
    q, k, v, o, lse = res
    do = g
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True)  # [B,H,Sq,1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, block_k=bk, sk=sk),
        grid=(b, h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, None, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((None, None, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            pl.BlockSpec((None, None, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            pl.BlockSpec((None, None, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((None, None, bq, 1), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((None, None, bq, 1), lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, block_q=bq, sq=sq),
        grid=(b, h, sk // bk),
        in_specs=[
            pl.BlockSpec((None, None, sq, d), lambda ib, ih, jk: (ib, ih, 0, 0)),
            pl.BlockSpec((None, None, bk, d), lambda ib, ih, jk: (ib, ih, jk, 0)),
            pl.BlockSpec((None, None, bk, d), lambda ib, ih, jk: (ib, ih, jk, 0)),
            pl.BlockSpec((None, None, sq, d), lambda ib, ih, jk: (ib, ih, 0, 0)),
            pl.BlockSpec((None, None, sq, 1), lambda ib, ih, jk: (ib, ih, 0, 0)),
            pl.BlockSpec((None, None, sq, 1), lambda ib, ih, jk: (ib, ih, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, bk, d), lambda ib, ih, jk: (ib, ih, jk, 0)),
            pl.BlockSpec((None, None, bk, d), lambda ib, ih, jk: (ib, ih, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return o


def _flash_bhsd_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bhsd_bwd(scale, causal, block_q, block_k, interpret, res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k, interpret)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [B, S, H, D] tensors (differentiable).

    ``block_q``/``block_k`` default to the adaptive policy (env override
    or the largest candidate dividing the sequence). ``interpret=None``
    auto-selects Pallas interpret mode off-TPU so the same code path is
    testable on the CPU mesh.
    """
    if block_q is None:
        block_q = pick_block(q.shape[1], ENV_BLOCK_Q)
    if block_k is None:
        block_k = pick_block(k.shape[1], ENV_BLOCK_K)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not on_tpu()
    # [B, S, H, D] -> [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash_bhsd(qt, kt, vt, float(scale), bool(causal), int(block_q), int(block_k), bool(interpret))
    return jnp.swapaxes(o, 1, 2)
