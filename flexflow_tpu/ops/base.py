"""Operator definition registry.

TPU-native analog of the reference's Op class hierarchy
(reference: include/flexflow/operator.h:51-277, src/ops/*). Where the
reference gives each op Legion launchers + CUDA kernels + a
``measure_operator_cost`` hook, here each op provides:

  * a frozen params record (the reference's ``<op>_params.h``),
  * shape inference (``infer_output_specs``),
  * weight specs + initializer choice,
  * a JAX lowering (the kernel — XLA/Pallas instead of cuDNN/cuBLAS),
  * an analytic cost estimate (flops / bytes) feeding the simulator, in
    place of on-device CUDA-event measurement (simulator.cc:588-628);
    measured calibration happens at the cost-model layer.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from ..core.tensor import TensorSpec
from ..core.types import DataType, OpType


@dataclasses.dataclass(frozen=True)
class WeightSpec:
    """A learnable parameter of an op + its initializer."""

    name: str
    spec: TensorSpec
    initializer: str = "glorot_uniform"  # name into runtime/initializers.py
    trainable: bool = True


@dataclasses.dataclass
class OpCost:
    """Analytic per-op cost (reference: CostMetrics simulator.h:54-88)."""

    flops: float = 0.0
    bytes_accessed: float = 0.0  # HBM traffic: inputs + outputs + weights
    memory_bytes: float = 0.0  # resident memory: weights + activations

    def __add__(self, other: "OpCost") -> "OpCost":
        return OpCost(
            self.flops + other.flops,
            self.bytes_accessed + other.bytes_accessed,
            self.memory_bytes + other.memory_bytes,
        )


@dataclasses.dataclass
class LowerCtx:
    """Context threaded through op lowering."""

    training: bool = True
    rng: Optional[jax.Array] = None  # base PRNG key; fold_in node guid per op
    node_guid: int = 0
    backend: str = "tpu"  # "tpu" enables pallas kernels; "cpu" falls back to XLA
    mesh: Optional[Any] = None  # jax.sharding.Mesh when lowering a sharded strategy
    seq_length: Optional[int] = None  # iteration-level seq truncation (FFIterationConfig)
    # functional state written by ops (e.g. batchnorm running stats),
    # keyed (node_guid, weight_name); merged by the executor after the step
    state_updates: Dict = dataclasses.field(default_factory=dict)
    # auxiliary losses appended by ops (e.g. MoE load-balancing, aggregate.cc
    # lambda_bal); summed into the total loss by the executor
    aux_losses: List = dataclasses.field(default_factory=list)
    # manual tensor parallelism (inside shard_map, where GSPMD can't see):
    # the mesh axis the current node's weights are sharded on, plus the
    # per-weight SpecTuples from the strategy. Megatron-style ops consult
    # weight_sharded_dim() to decide whether their local matmul contracts
    # a sharded dim (row parallel -> psum over tp_axis).
    tp_axis: Optional[str] = None
    weight_specs: Optional[Dict] = None
    # manual context parallelism (inside shard_map — pipeline stages with
    # the sequence dim sharded on "seq"): attention lowers to ring
    # attention over this axis instead of local dense attention
    cp_axis: Optional[str] = None
    # manual data parallelism axis inside shard_map (pipeline stages with
    # the batch dim sharded on "data"): stochastic ops fold the shard
    # index into their key via shard_rng()
    dp_axis: Optional[str] = None
    # pp x cp: True when the current node's K/V input (input 1) is
    # FULL-LENGTH on every cp shard (a shared cross-attention memory
    # whose seq dim didn't divide cp) — attention must go dense on the
    # local complete K/V, not ring over cp identical copies
    kv_seq_replicated: bool = False

    def node_rng(self) -> jax.Array:
        if self.rng is None:
            raise ValueError("op requires an RNG but none was provided")
        return jax.random.fold_in(self.rng, self.node_guid)

    def shard_rng(self) -> jax.Array:
        """node_rng decorrelated per shard: inside a manual shard_map
        every shard traces the same key, so a stochastic op sampling at
        its LOCAL shape would repeat the identical pattern on every
        shard (every S/cp positions under cp; across batch shards under
        dp). Fold in the index along each manual axis that is set."""
        key = self.node_rng()
        for ax in (self.dp_axis, self.cp_axis):
            if ax is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(ax))
        return key

    def weight_sharded_dim(self, wname: str) -> Optional[int]:
        """Index of the dim of weight ``wname`` sharded on tp_axis, or
        None (replicated / no manual tp active)."""
        if self.tp_axis is None or not self.weight_specs:
            return None
        spec = self.weight_specs.get(wname)
        if not spec:
            return None
        for i, axes in enumerate(spec):
            if axes and self.tp_axis in axes:
                return i
        return None


class OpDef:
    """Base operator definition; subclasses register per OpType."""

    op_type: OpType = None  # type: ignore
    params_cls: type = None  # type: ignore

    # --- shape inference -------------------------------------------------
    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]) -> List[TensorSpec]:
        raise NotImplementedError

    # --- weights ---------------------------------------------------------
    @staticmethod
    def weight_specs(params, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        return []

    # --- lowering --------------------------------------------------------
    @staticmethod
    def lower(params, inputs: List[jax.Array], weights: Dict[str, jax.Array], ctx: LowerCtx) -> List[jax.Array]:
        raise NotImplementedError

    # --- cost ------------------------------------------------------------
    @staticmethod
    def cost(params, input_specs: List[TensorSpec], output_specs: List[TensorSpec]) -> OpCost:
        io_bytes = sum(s.size_bytes for s in input_specs) + sum(s.size_bytes for s in output_specs)
        return OpCost(flops=0.0, bytes_accessed=io_bytes, memory_bytes=sum(s.size_bytes for s in output_specs))


_REGISTRY: Dict[OpType, type] = {}


def register_op(cls: type) -> type:
    if cls.op_type is None:
        raise ValueError(f"{cls} missing op_type")
    _REGISTRY[cls.op_type] = cls
    return cls


def get_op_def(op_type: OpType) -> type:
    if op_type not in _REGISTRY:
        raise KeyError(f"no OpDef registered for {op_type}")
    return _REGISTRY[op_type]


def registered_ops() -> Dict[OpType, type]:
    return dict(_REGISTRY)


def io_cost(input_specs: Sequence[TensorSpec], output_specs: Sequence[TensorSpec], flops: float = 0.0, extra_mem: float = 0.0) -> OpCost:
    io = sum(s.size_bytes for s in input_specs) + sum(s.size_bytes for s in output_specs)
    out_mem = sum(s.size_bytes for s in output_specs)
    return OpCost(flops=flops, bytes_accessed=io, memory_bytes=out_mem + extra_mem)
