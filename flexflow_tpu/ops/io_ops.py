"""Graph source/sink ops: Input, Weight, NoOp.

Reference: src/ops/noop.cc (NoOp carries input_tensor_guid mapping,
model.cc:2862-2875); input/weight nodes are how the PCG roots tensors.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax

from ..core.tensor import TensorSpec
from ..core.types import DataType, OpType
from .base import LowerCtx, OpCost, OpDef, WeightSpec, register_op


@dataclasses.dataclass(frozen=True)
class InputParams:
    shape: Tuple[int, ...]
    dtype: DataType = DataType.FLOAT
    input_index: int = 0  # position in the user's batch tuple


@register_op
class InputOp(OpDef):
    op_type = OpType.INPUT
    params_cls = InputParams

    @staticmethod
    def infer_output_specs(params: InputParams, input_specs: List[TensorSpec]) -> List[TensorSpec]:
        return [TensorSpec(params.shape, params.dtype)]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        raise RuntimeError("Input nodes are bound by the executor, not lowered")

    @staticmethod
    def cost(params, input_specs, output_specs) -> OpCost:
        return OpCost()


@dataclasses.dataclass(frozen=True)
class WeightParams:
    shape: Tuple[int, ...]
    dtype: DataType = DataType.FLOAT
    initializer: str = "glorot_uniform"


@register_op
class WeightOp(OpDef):
    op_type = OpType.WEIGHT
    params_cls = WeightParams

    @staticmethod
    def infer_output_specs(params: WeightParams, input_specs: List[TensorSpec]) -> List[TensorSpec]:
        return [TensorSpec(params.shape, params.dtype)]

    @staticmethod
    def weight_specs(params: WeightParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        return [WeightSpec("weight", TensorSpec(params.shape, params.dtype), params.initializer)]

    @staticmethod
    def lower(params, inputs, weights: Dict[str, jax.Array], ctx: LowerCtx):
        return [weights["weight"]]

    @staticmethod
    def cost(params, input_specs, output_specs) -> OpCost:
        return OpCost()


@dataclasses.dataclass(frozen=True)
class NoOpParams:
    pass


@register_op
class NoOp(OpDef):
    op_type = OpType.NOOP
    params_cls = NoOpParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]) -> List[TensorSpec]:
        return list(input_specs)

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return list(inputs)

    @staticmethod
    def cost(params, input_specs, output_specs) -> OpCost:
        return OpCost()
