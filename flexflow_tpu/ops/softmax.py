"""Softmax and Dropout operators.

Reference: src/ops/softmax.cc (418 LoC, cudnnSoftmaxForward) and
src/ops/dropout.cc (362 LoC, cudnnDropout with per-op RNG state).
TPU-native: jax.nn.softmax; dropout uses a per-node folded PRNG key
(deterministic given the step key — replaces cuDNN dropout descriptors).
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import OpType
from .base import LowerCtx, OpDef, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class SoftmaxParams:
    axis: int = -1


@register_op
class SoftmaxOp(OpDef):
    op_type = OpType.SOFTMAX
    params_cls = SoftmaxParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def lower(params: SoftmaxParams, inputs, weights, ctx):
        return [jax.nn.softmax(inputs[0], axis=params.axis)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=5.0 * output_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class DropoutParams:
    rate: float
    seed: int = 0


@register_op
class DropoutOp(OpDef):
    op_type = OpType.DROPOUT
    params_cls = DropoutParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def lower(params: DropoutParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        if not ctx.training or params.rate <= 0.0:
            return [x]
        key = ctx.shard_rng()
        keep = 1.0 - params.rate
        mask = jax.random.bernoulli(key, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=2.0 * output_specs[0].num_elements)
