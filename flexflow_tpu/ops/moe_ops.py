"""Mixture-of-Experts operator family: TopK, GroupBy, Aggregate,
AggregateSpec, Cache.

Reference: src/ops/topk.cc (437), group_by.cc (534), aggregate.cc (569,
with the lambda_bal load-balancing gradient), aggregate_spec.cc (519),
cache.cc (291, score-triggered recompile). The reference moves tokens
with CUDA scatter kernels into per-expert buffers of capacity
``alpha * k * B / n``. TPU-native: identical static-capacity semantics,
implemented with one-hot matmuls, cumsum position assignment and
scatter — all static shapes so XLA can compile them; expert parallelism
lays experts on a mesh axis and XLA's all_to_all moves the tokens.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import DataType, OpType
from .base import LowerCtx, OpCost, OpDef, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class TopKParams:
    k: int
    sorted: bool = True


@register_op
class TopKOp(OpDef):
    op_type = OpType.TOPK
    params_cls = TopKParams

    @staticmethod
    def infer_output_specs(params: TopKParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        shape = x.shape[:-1] + (params.k,)
        return [TensorSpec(shape, x.dtype), TensorSpec(shape, DataType.INT32)]

    @staticmethod
    def lower(params: TopKParams, inputs, weights, ctx):
        values, indices = jax.lax.top_k(inputs[0], params.k)
        return [values, indices.astype(jnp.int32)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        n = input_specs[0].num_elements
        return io_cost(input_specs, output_specs, flops=float(n) * math.log2(max(2, input_specs[0].shape[-1])))


def expert_capacity(batch: int, k: int, n_experts: int, alpha: float) -> int:
    """Per-expert token capacity (reference: group_by.cc capacity calc)."""
    return max(1, int(math.ceil(alpha * k * batch / n_experts)))


@dataclasses.dataclass(frozen=True)
class GroupByParams:
    n_experts: int
    alpha: float = 1.0  # capacity factor


@register_op
class GroupByOp(OpDef):
    """Scatter tokens into per-expert buffers.

    Inputs: data [B, D], assignments [B, K] (int expert ids).
    Outputs: n_experts tensors [capacity, D]; overflowing tokens are
    dropped (same drop semantics as the reference's fixed-size buffers).
    """

    op_type = OpType.GROUP_BY
    params_cls = GroupByParams

    @staticmethod
    def infer_output_specs(params: GroupByParams, input_specs: List[TensorSpec]):
        data, assign = input_specs
        b, d = data.shape
        cap = expert_capacity(b, assign.shape[-1], params.n_experts, params.alpha)
        return [TensorSpec((cap, d), data.dtype) for _ in range(params.n_experts)]

    @staticmethod
    def lower(params: GroupByParams, inputs, weights, ctx: LowerCtx):
        data, assign = inputs
        b, d = data.shape
        k = assign.shape[-1]
        n = params.n_experts
        cap = expert_capacity(b, k, n, params.alpha)
        flat_assign = assign.reshape(-1).astype(jnp.int32)  # [B*K]
        # position of each (token, slot) within its expert, via masked cumsum
        onehot = jax.nn.one_hot(flat_assign, n, dtype=jnp.int32)  # [B*K, n]
        pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based position per expert
        pos_in_expert = jnp.sum(pos, axis=-1) - 1  # [B*K]
        token_idx = jnp.repeat(jnp.arange(b), k)
        outs = []
        for e in range(n):
            sel = (flat_assign == e) & (pos_in_expert < cap)
            dst = jnp.where(sel, pos_in_expert, cap)  # row `cap` = dropped/overflow
            buf = jnp.zeros((cap + 1, d), data.dtype).at[dst].set(data[token_idx])[:cap]
            outs.append(buf)
        return outs

    @staticmethod
    def cost(params: GroupByParams, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=2.0 * input_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class AggregateParams:
    n_experts: int
    lambda_bal: float = 0.0  # load-balance aux loss weight (aggregate.cc)
    alpha: float = 1.0


@register_op
class AggregateOp(OpDef):
    """Gather expert outputs back to token order, weighted by gate scores.

    Inputs: gate_preds [B, K], gate_assign [B, K], then n_experts tensors
    [capacity, D] (reference aggregate.cc input layout, minus the
    backward-only full_gate_grads which autodiff makes unnecessary).
    Output: [B, D].
    """

    op_type = OpType.AGGREGATE
    params_cls = AggregateParams

    @staticmethod
    def infer_output_specs(params: AggregateParams, input_specs: List[TensorSpec]):
        gate = input_specs[0]
        d = input_specs[2].shape[-1]
        return [TensorSpec((gate.shape[0], d), input_specs[2].dtype)]

    @staticmethod
    def lower(params: AggregateParams, inputs, weights, ctx: LowerCtx):
        gate_preds, gate_assign = inputs[0], inputs[1]
        experts = inputs[2:]
        b, k = gate_preds.shape
        n = params.n_experts
        cap = experts[0].shape[0]
        d = experts[0].shape[1]
        flat_assign = gate_assign.reshape(-1).astype(jnp.int32)
        onehot = jax.nn.one_hot(flat_assign, n, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot
        pos_in_expert = jnp.sum(pos, axis=-1) - 1  # [B*K]
        valid = pos_in_expert < cap
        stacked = jnp.stack(experts)  # [n, cap, D]
        rows = stacked[flat_assign, jnp.clip(pos_in_expert, 0, cap - 1)]  # [B*K, D]
        rows = jnp.where(valid[:, None], rows, 0.0)
        w = gate_preds.reshape(-1)[:, None].astype(rows.dtype)
        out = jnp.sum((rows * w).reshape(b, k, d), axis=1)
        if params.lambda_bal > 0.0:
            # load-balance aux loss (reference: aggregate.cc lambda_bal):
            # penalize squared per-expert token fractions (Shazeer-style)
            frac = jnp.mean(jax.nn.one_hot(flat_assign, n, dtype=jnp.float32), axis=0)
            imp = jnp.mean(
                jax.nn.one_hot(flat_assign, n, dtype=jnp.float32)
                * gate_preds.reshape(-1, 1).astype(jnp.float32),
                axis=0,
            )
            ctx.aux_losses.append(params.lambda_bal * n * jnp.sum(frac * imp))
        return [out]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=3.0 * output_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class AggregateSpecParams:
    n_experts: int
    lambda_bal: float = 0.0
    alpha: float = 1.0


@register_op
class AggregateSpecOp(AggregateOp):
    """Speculative-assignment variant (reference: aggregate_spec.cc) —
    combines expert outputs under the *true* assignment while gradients
    flow to the speculative gate scores; forward math matches Aggregate."""

    op_type = OpType.AGGREGATE_SPEC
    params_cls = AggregateSpecParams


@dataclasses.dataclass(frozen=True)
class CacheParams:
    num_batches: int = 1
    trigger_threshold: float = 0.0


@register_op
class CacheOp(OpDef):
    """Input-caching op (reference: cache.cc): stores recent batches and
    scores drift to trigger recompilation. Forward is identity; the
    scoring/trigger logic lives in runtime/recompile.py on host."""

    op_type = OpType.CACHE
    params_cls = CacheParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return [inputs[0]]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return OpCost()
