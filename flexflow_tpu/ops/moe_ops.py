"""Mixture-of-Experts operator family: TopK, GroupBy, Aggregate,
AggregateSpec, Cache.

Reference: src/ops/topk.cc (437), group_by.cc (534), aggregate.cc (569,
with the lambda_bal load-balancing gradient), aggregate_spec.cc (519),
cache.cc (291, score-triggered recompile). The reference moves tokens
with CUDA scatter kernels into per-expert buffers of capacity
``alpha * k * B / n``. TPU-native: identical static-capacity semantics,
implemented with one-hot matmuls, cumsum position assignment and
scatter — all static shapes so XLA can compile them; expert parallelism
lays experts on a mesh axis and XLA's all_to_all moves the tokens.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import ActiMode, DataType, OpType
from .base import LowerCtx, OpCost, OpDef, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class TopKParams:
    k: int
    sorted: bool = True


@register_op
class TopKOp(OpDef):
    op_type = OpType.TOPK
    params_cls = TopKParams

    @staticmethod
    def infer_output_specs(params: TopKParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        shape = x.shape[:-1] + (params.k,)
        return [TensorSpec(shape, x.dtype), TensorSpec(shape, DataType.INT32)]

    @staticmethod
    def lower(params: TopKParams, inputs, weights, ctx):
        values, indices = jax.lax.top_k(inputs[0], params.k)
        return [values, indices.astype(jnp.int32)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        n = input_specs[0].num_elements
        return io_cost(input_specs, output_specs, flops=float(n) * math.log2(max(2, input_specs[0].shape[-1])))


def expert_capacity(batch: int, k: int, n_experts: int, alpha: float) -> int:
    """Per-expert token capacity (reference: group_by.cc capacity calc)."""
    return max(1, int(math.ceil(alpha * k * batch / n_experts)))


def _dispatch_positions(assign: jax.Array, n: int):
    """(flat_assign [B*K], pos_in_expert [B*K]): each (token, slot)'s
    0-based position within its expert's buffer, via masked cumsum."""
    flat_assign = assign.reshape(-1).astype(jnp.int32)
    onehot = jax.nn.one_hot(flat_assign, n, dtype=jnp.int32)  # [B*K, n]
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based position per expert
    return flat_assign, jnp.sum(pos, axis=-1) - 1


def _dispatch_stacked(data, assign, n: int, cap: int) -> jax.Array:
    """ONE dense-capacity scatter of tokens into [n, cap, D] (round-2 fix:
    the per-expert Python scatter loop was O(n_experts) HLO for the
    reference's 64-expert configs, examples/cpp/mixture_of_experts)."""
    b, d = data.shape
    k = assign.shape[-1]
    flat_assign, pos_in_expert = _dispatch_positions(assign, n)
    token_idx = jnp.repeat(jnp.arange(b), k)
    valid = pos_in_expert < cap
    dst = jnp.where(valid, flat_assign * cap + pos_in_expert, n * cap)  # row n*cap = dropped
    buf = jnp.zeros((n * cap + 1, d), data.dtype).at[dst].set(data[token_idx])
    return buf[: n * cap].reshape(n, cap, d)


@dataclasses.dataclass(frozen=True)
class GroupByParams:
    n_experts: int
    alpha: float = 1.0  # capacity factor
    stacked: bool = False  # True -> single [n, cap, D] output (feeds ExpertsOp)


@register_op
class GroupByOp(OpDef):
    """Scatter tokens into per-expert buffers.

    Inputs: data [B, D], assignments [B, K] (int expert ids).
    Outputs: n_experts tensors [capacity, D] — or, with stacked=True, ONE
    [n_experts, capacity, D] tensor whose leading dim shards over the
    expert mesh axis (token routing becomes a GSPMD all_to_all).
    Overflowing tokens are dropped (same drop semantics as the
    reference's fixed-size buffers, group_by.cc).
    """

    op_type = OpType.GROUP_BY
    params_cls = GroupByParams

    @staticmethod
    def infer_output_specs(params: GroupByParams, input_specs: List[TensorSpec]):
        data, assign = input_specs
        b, d = data.shape
        cap = expert_capacity(b, assign.shape[-1], params.n_experts, params.alpha)
        if params.stacked:
            return [TensorSpec((params.n_experts, cap, d), data.dtype)]
        return [TensorSpec((cap, d), data.dtype) for _ in range(params.n_experts)]

    @staticmethod
    def lower(params: GroupByParams, inputs, weights, ctx: LowerCtx):
        data, assign = inputs
        b, d = data.shape
        n = params.n_experts
        cap = expert_capacity(b, assign.shape[-1], n, params.alpha)
        buf = _dispatch_stacked(data, assign, n, cap)
        if params.stacked:
            return [buf]
        return [buf[e] for e in range(n)]

    @staticmethod
    def cost(params: GroupByParams, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=2.0 * input_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class ExpertsParams:
    """Batched two-layer expert FFN (reference: the n per-expert Dense
    pairs of FFModel::moe, src/ops/moe.cc:20; here ONE op whose weights
    carry a leading expert dim, so expert parallelism is just sharding
    that dim over the mesh's expert/model axis)."""

    n_experts: int
    hidden_size: int
    out_dim: int
    activation: ActiMode = ActiMode.RELU
    dtype: DataType = DataType.FLOAT


@register_op
class ExpertsOp(OpDef):
    """[n, cap, D] -> [n, cap, out_dim] batched expert MLP.

    When the mesh has an expert-bearing axis ("expert", else "model")
    that divides n_experts, compute runs under shard_map with each device
    applying only its local experts — weights never move; tokens ride the
    GSPMD all_to_all at the shard_map boundary (the TPU-native form of
    the reference's per-expert machine views, moe.cc:180-204).
    """

    op_type = OpType.EXPERTS
    params_cls = ExpertsParams

    @staticmethod
    def infer_output_specs(params: ExpertsParams, input_specs: List[TensorSpec]):
        x = input_specs[0]
        return [TensorSpec((x.shape[0], x.shape[1], params.out_dim), params.dtype)]

    @staticmethod
    def weight_specs(params: ExpertsParams, input_specs: List[TensorSpec]):
        from .base import WeightSpec

        d = input_specs[0].shape[-1]
        n, h, o = params.n_experts, params.hidden_size, params.out_dim
        dt = params.dtype
        return [
            WeightSpec("w1", TensorSpec((n, d, h), dt), "glorot_uniform"),
            WeightSpec("b1", TensorSpec((n, h), dt), "zeros"),
            WeightSpec("w2", TensorSpec((n, h, o), dt), "glorot_uniform"),
            WeightSpec("b2", TensorSpec((n, o), dt), "zeros"),
        ]

    @staticmethod
    def _apply(x, w1, b1, w2, b2, activation):
        from .elementwise import apply_activation

        h = jnp.einsum("ncd,ndh->nch", x, w1) + b1[:, None, :]
        h = apply_activation(activation, h)
        return jnp.einsum("nch,nho->nco", h, w2) + b2[:, None, :]

    @staticmethod
    def lower(params: ExpertsParams, inputs, weights, ctx: LowerCtx):
        x = inputs[0]
        w1, b1, w2, b2 = weights["w1"], weights["b1"], weights["w2"], weights["b2"]
        mesh = getattr(ctx, "mesh", None)
        axis = None
        if mesh is not None:
            for cand in ("expert", "model"):
                if cand in mesh.axis_names and mesh.shape[cand] > 1 and params.n_experts % mesh.shape[cand] == 0:
                    axis = cand
                    break
        if axis is not None:
            try:
                from jax import shard_map
            except ImportError:
                from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def local(x, w1, b1, w2, b2):
                # each device: only its n/E experts; tokens arrived via
                # the boundary all_to_all
                return ExpertsOp._apply(x, w1, b1, w2, b2, params.activation)

            ep = P(axis, None, None)
            e2 = P(axis, None)
            y = shard_map(
                local,
                mesh=mesh,
                in_specs=(ep, ep, e2, ep, e2),
                out_specs=ep,
            )(x, w1, b1, w2, b2)
        else:
            y = ExpertsOp._apply(x, w1, b1, w2, b2, params.activation)
        return [y.astype(params.dtype.jnp)]

    @staticmethod
    def cost(params: ExpertsParams, input_specs, output_specs):
        n, cap, d = input_specs[0].shape
        flops = 2.0 * n * cap * d * params.hidden_size + 2.0 * n * cap * params.hidden_size * params.out_dim
        w_bytes = (n * d * params.hidden_size + n * params.hidden_size * params.out_dim) * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=flops, extra_mem=w_bytes)
        c.bytes_accessed += w_bytes
        return c


@dataclasses.dataclass(frozen=True)
class AggregateParams:
    n_experts: int
    lambda_bal: float = 0.0  # load-balance aux loss weight (aggregate.cc)
    alpha: float = 1.0


@register_op
class AggregateOp(OpDef):
    """Gather expert outputs back to token order, weighted by gate scores.

    Inputs: gate_preds [B, K], gate_assign [B, K], then n_experts tensors
    [capacity, D] (reference aggregate.cc input layout, minus the
    backward-only full_gate_grads which autodiff makes unnecessary).
    Output: [B, D].
    """

    op_type = OpType.AGGREGATE
    params_cls = AggregateParams

    @staticmethod
    def infer_output_specs(params: AggregateParams, input_specs: List[TensorSpec]):
        gate = input_specs[0]
        d = input_specs[2].shape[-1]
        return [TensorSpec((gate.shape[0], d), input_specs[2].dtype)]

    @staticmethod
    def _gather_rows(gate_assign, experts, n: int):
        """Expert rows in (token, slot) order: [B*K, D]. ``experts`` is
        either a single stacked [n, cap, D] tensor or n [cap, D] tensors."""
        stacked = experts[0] if len(experts) == 1 and experts[0].ndim == 3 else jnp.stack(experts)
        cap = stacked.shape[1]
        flat_assign, pos_in_expert = _dispatch_positions(gate_assign, n)
        valid = pos_in_expert < cap
        rows = stacked[flat_assign, jnp.clip(pos_in_expert, 0, cap - 1)]  # [B*K, D]
        return jnp.where(valid[:, None], rows, 0.0), flat_assign

    @staticmethod
    def lower(params: AggregateParams, inputs, weights, ctx: LowerCtx):
        gate_preds, gate_assign = inputs[0], inputs[1]
        b, k = gate_preds.shape
        n = params.n_experts
        rows, flat_assign = AggregateOp._gather_rows(gate_assign, inputs[2:], n)
        d = rows.shape[-1]
        w = gate_preds.reshape(-1)[:, None].astype(rows.dtype)
        out = jnp.sum((rows * w).reshape(b, k, d), axis=1)
        if params.lambda_bal > 0.0:
            # load-balance aux loss (reference: aggregate.cc lambda_bal):
            # penalize squared per-expert token fractions (Shazeer-style)
            frac = jnp.mean(jax.nn.one_hot(flat_assign, n, dtype=jnp.float32), axis=0)
            imp = jnp.mean(
                jax.nn.one_hot(flat_assign, n, dtype=jnp.float32)
                * gate_preds.reshape(-1, 1).astype(jnp.float32),
                axis=0,
            )
            ctx.aux_losses.append(params.lambda_bal * n * jnp.sum(frac * imp))
        return [out]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=3.0 * output_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class AggregateSpecParams:
    n_experts: int
    lambda_bal: float = 0.0
    alpha: float = 1.0


@register_op
class AggregateSpecOp(OpDef):
    """Speculative-assignment variant (reference: aggregate_spec.cc/.cu).

    Forward (aggspec_forward_kernel, aggregate_spec.cu:21-63): outputs
    every chosen expert's prediction SEPARATELY, [B*K, D] — NOT the
    gate-weighted sum — so the loss evaluates each speculative routing.
    Backward to the gate (aggspec_backward_kernel_gate, :64-127) is a
    hand-crafted rule, not the forward's transpose: each selected gate
    score's gradient is its normalized share of the squared output error
    minus (1 - gate_pred), plus the lambda_bal balance term, mean-centered
    across experts. Implemented with jax.custom_vjp; expert gradients use
    the standard scatter transpose.
    """

    op_type = OpType.AGGREGATE_SPEC
    params_cls = AggregateSpecParams

    @staticmethod
    def infer_output_specs(params: AggregateSpecParams, input_specs: List[TensorSpec]):
        gate = input_specs[0]
        d = input_specs[2].shape[-1]
        return [TensorSpec((gate.shape[0] * gate.shape[1], d), input_specs[2].dtype)]

    @staticmethod
    def lower(params: AggregateSpecParams, inputs, weights, ctx: LowerCtx):
        gate_preds, gate_assign = inputs[0], inputs[1]
        experts = tuple(inputs[2:])
        n = params.n_experts
        lambda_bal = params.lambda_bal

        @jax.custom_vjp
        def agg_spec(gate_preds, experts):
            rows, _ = AggregateOp._gather_rows(gate_assign, experts, n)
            return rows  # [B*K, D]

        def fwd(gate_preds, experts):
            rows, flat_assign = AggregateOp._gather_rows(gate_assign, experts, n)
            return rows, (gate_preds, experts, flat_assign)

        def bwd(res, g):
            gate_preds, experts, flat_assign = res
            b, k = gate_preds.shape
            # expert grads: standard transpose of the gather (linear part)
            def gather_only(experts):
                rows, _ = AggregateOp._gather_rows(gate_assign, experts, n)
                return rows

            _, exp_vjp = jax.vjp(gather_only, experts)
            (experts_grad,) = exp_vjp(g)
            # gate grads: reference rule (aggregate_spec.cu:87-126)
            err = jnp.sum(jnp.square(g.astype(jnp.float32)), axis=-1) * b  # [B*K]
            full = jnp.zeros((b, n), jnp.float32)
            bi = jnp.repeat(jnp.arange(b), k)
            full = full.at[bi, flat_assign].add(err)
            err_sum = jnp.sum(err.reshape(b, k), axis=-1, keepdims=False)  # [B]
            full = full / jnp.maximum(err_sum, 1e-20)[:, None]
            # -(1 - gate_pred) on each selected entry
            full = full.at[bi, flat_assign].add(-(1.0 - gate_preds.reshape(-1).astype(jnp.float32)))
            if lambda_bal > 0.0:
                counts = jnp.sum(jax.nn.one_hot(flat_assign, n, dtype=jnp.float32), axis=0)
                full = full + lambda_bal * counts[None, :]
            full = full - jnp.mean(full, axis=-1, keepdims=True)  # zero-mean over experts
            gate_grad = full[bi, flat_assign].reshape(b, k).astype(gate_preds.dtype)
            return gate_grad, experts_grad

        agg_spec.defvjp(fwd, bwd)
        return [agg_spec(gate_preds, experts)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=3.0 * output_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class CacheParams:
    num_batches: int = 1
    trigger_threshold: float = 0.0


@register_op
class CacheOp(OpDef):
    """Input-caching op (reference: cache.cc): stores recent batches and
    scores drift to trigger recompilation. Forward is identity; the
    scoring/trigger logic lives in runtime/recompile.py on host."""

    op_type = OpType.CACHE
    params_cls = CacheParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return [inputs[0]]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return OpCost()
