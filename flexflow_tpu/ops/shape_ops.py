"""Shape-manipulation operators: Reshape, Transpose, Reverse, Flat, Concat,
Split, Cast.

Reference: src/ops/{reshape,transpose,reverse,flat,concat,split,cast}.cc
with their CUDA copy kernels. TPU-native: all are pure layout/metadata
ops in XLA (free or fused); costs model the HBM copy the reference pays.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import DataType, OpType
from .base import OpDef, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class ReshapeParams:
    shape: Tuple[int, ...]


@register_op
class ReshapeOp(OpDef):
    op_type = OpType.RESHAPE
    params_cls = ReshapeParams

    @staticmethod
    def infer_output_specs(params: ReshapeParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        shape = list(params.shape)
        if -1 in shape:
            i = shape.index(-1)
            rest = math.prod(s for s in shape if s != -1)
            shape[i] = x.num_elements // rest
        if math.prod(shape) != x.num_elements:
            raise ValueError(f"cannot reshape {x.shape} to {params.shape}")
        return [TensorSpec(tuple(shape), x.dtype)]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        out_shape = ReshapeOp.infer_output_specs(params, [TensorSpec(inputs[0].shape, DataType.from_jnp(inputs[0].dtype))])[0].shape
        return [jnp.reshape(inputs[0], out_shape)]


@dataclasses.dataclass(frozen=True)
class TransposeParams:
    perm: Tuple[int, ...]


@register_op
class TransposeOp(OpDef):
    op_type = OpType.TRANSPOSE
    params_cls = TransposeParams

    @staticmethod
    def infer_output_specs(params: TransposeParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        return [TensorSpec(tuple(x.shape[p] for p in params.perm), x.dtype)]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return [jnp.transpose(inputs[0], params.perm)]


@dataclasses.dataclass(frozen=True)
class ReverseParams:
    axis: int


@register_op
class ReverseOp(OpDef):
    op_type = OpType.REVERSE
    params_cls = ReverseParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return [jnp.flip(inputs[0], params.axis)]


@dataclasses.dataclass(frozen=True)
class FlatParams:
    pass


@register_op
class FlatOp(OpDef):
    """Flatten all non-batch dims (reference: src/ops/flat.cc)."""

    op_type = OpType.FLAT
    params_cls = FlatParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        (x,) = input_specs
        return [TensorSpec((x.shape[0], math.prod(x.shape[1:])), x.dtype)]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        x = inputs[0]
        return [jnp.reshape(x, (x.shape[0], -1))]


@dataclasses.dataclass(frozen=True)
class ConcatParams:
    axis: int
    n_inputs: int


@register_op
class ConcatOp(OpDef):
    op_type = OpType.CONCAT
    params_cls = ConcatParams

    @staticmethod
    def infer_output_specs(params: ConcatParams, input_specs: List[TensorSpec]):
        ax = params.axis
        base = list(input_specs[0].shape)
        base[ax] = sum(s.shape[ax] for s in input_specs)
        return [TensorSpec(tuple(base), input_specs[0].dtype)]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return [jnp.concatenate(inputs, axis=params.axis)]


@dataclasses.dataclass(frozen=True)
class SplitParams:
    sizes: Tuple[int, ...]
    axis: int


@register_op
class SplitOp(OpDef):
    op_type = OpType.SPLIT
    params_cls = SplitParams

    @staticmethod
    def infer_output_specs(params: SplitParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        out = []
        for sz in params.sizes:
            shape = list(x.shape)
            shape[params.axis] = sz
            out.append(TensorSpec(tuple(shape), x.dtype))
        return out

    @staticmethod
    def lower(params, inputs, weights, ctx):
        splits = []
        off = 0
        for sz in params.sizes[:-1]:
            off += sz
            splits.append(off)
        return list(jnp.split(inputs[0], splits, axis=params.axis))


@dataclasses.dataclass(frozen=True)
class CastParams:
    dtype: DataType


@register_op
class CastOp(OpDef):
    op_type = OpType.CAST
    params_cls = CastParams

    @staticmethod
    def infer_output_specs(params: CastParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        return [TensorSpec(x.shape, params.dtype)]

    @staticmethod
    def lower(params, inputs, weights, ctx):
        return [inputs[0].astype(params.dtype.jnp)]
