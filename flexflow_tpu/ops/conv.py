"""Convolution family: Conv2D, Pool2D.

Reference: src/ops/conv_2d.cc (1198 LoC, cudnnConvolution with fwd-algo
selection + groups) and src/ops/pool_2d.cc (688 LoC, cudnnPooling).
TPU-native: lax.conv_general_dilated / lax.reduce_window — XLA lowers
these onto the MXU (convs become implicit GEMMs) with its own algorithm
selection; the reference's cudnnFindConvolutionForwardAlgorithm has no
analog because XLA autotunes. Layout is logical NCHW for API parity with
the reference; XLA relayouts internally for the TPU.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import TensorSpec
from ..core.types import ActiMode, DataType, OpType, PoolType
from .base import LowerCtx, OpCost, OpDef, WeightSpec, io_cost, register_op
from .elementwise import apply_activation


def _pad2(p):
    """Padding entry: int (symmetric) or (before, after) pair."""
    return (p, p) if isinstance(p, int) else tuple(p)


def _out_dim(size, kernel, stride, pad):
    lo, hi = _pad2(pad)
    out = (size + lo + hi - kernel) // stride + 1
    if out <= 0:
        # fail AT GRAPH BUILD with the geometry in hand — a 0-dim tensor
        # otherwise flows silently until a ZeroDivisionError deep in the
        # search cost model (found via AlexNet's 224-geometry stack fed
        # 32x32 CIFAR images; the reference upscales CIFAR to 229 first)
        raise ValueError(
            f"conv/pool output dim collapsed to {out}: input {size}, "
            f"kernel {kernel}, stride {stride}, padding {pad}"
        )
    return out


@dataclasses.dataclass(frozen=True)
class Conv2DParams:
    out_channels: int
    kernel: tuple  # (kh, kw)
    stride: tuple  # (sh, sw)
    padding: tuple  # (ph, pw)
    groups: int = 1
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE
    dtype: DataType = DataType.FLOAT
    kernel_initializer: str = "glorot_uniform"


@register_op
class Conv2DOp(OpDef):
    op_type = OpType.CONV2D
    params_cls = Conv2DParams

    @staticmethod
    def infer_output_specs(params: Conv2DParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        n, c, h, w = x.shape
        oh = _out_dim(h, params.kernel[0], params.stride[0], params.padding[0])
        ow = _out_dim(w, params.kernel[1], params.stride[1], params.padding[1])
        return [TensorSpec((n, params.out_channels, oh, ow), params.dtype)]

    @staticmethod
    def weight_specs(params: Conv2DParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        (x,) = input_specs
        cin = x.shape[1]
        ws = [
            WeightSpec(
                "kernel",
                TensorSpec((params.out_channels, cin // params.groups) + tuple(params.kernel), params.dtype),
                params.kernel_initializer,
            )
        ]
        if params.use_bias:
            ws.append(WeightSpec("bias", TensorSpec((params.out_channels,), params.dtype), "zeros"))
        return ws

    @staticmethod
    def lower(params: Conv2DParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        y = lax.conv_general_dilated(
            x,
            weights["kernel"],
            window_strides=params.stride,
            padding=[_pad2(params.padding[0]), _pad2(params.padding[1])],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=params.groups,
            preferred_element_type=jnp.float32,
        ).astype(params.dtype.jnp)
        if params.use_bias:
            y = y + weights["bias"].reshape(1, -1, 1, 1)
        return [apply_activation(params.activation, y)]

    @staticmethod
    def cost(params: Conv2DParams, input_specs, output_specs) -> OpCost:
        (x,) = input_specs
        (y,) = output_specs
        cin = x.shape[1]
        flops = 2.0 * y.num_elements * (cin // params.groups) * params.kernel[0] * params.kernel[1]
        w_bytes = params.out_channels * (cin // params.groups) * params.kernel[0] * params.kernel[1] * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=flops, extra_mem=w_bytes)
        c.bytes_accessed += w_bytes
        return c


@dataclasses.dataclass(frozen=True)
class Pool2DParams:
    kernel: tuple
    stride: tuple
    padding: tuple
    pool_type: PoolType = PoolType.MAX
    activation: ActiMode = ActiMode.NONE


@register_op
class Pool2DOp(OpDef):
    op_type = OpType.POOL2D
    params_cls = Pool2DParams

    @staticmethod
    def infer_output_specs(params: Pool2DParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        n, c, h, w = x.shape
        oh = _out_dim(h, params.kernel[0], params.stride[0], params.padding[0])
        ow = _out_dim(w, params.kernel[1], params.stride[1], params.padding[1])
        return [TensorSpec((n, c, oh, ow), x.dtype)]

    @staticmethod
    def lower(params: Pool2DParams, inputs, weights, ctx):
        (x,) = inputs
        pads = ((0, 0), (0, 0), _pad2(params.padding[0]), _pad2(params.padding[1]))
        dims = (1, 1) + tuple(params.kernel)
        strides = (1, 1) + tuple(params.stride)
        if params.pool_type == PoolType.MAX:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init, lax.max, dims, strides, pads)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            # divide by true window size (count_include_pad=False à la cuDNN default)
            ones = jnp.ones(x.shape[2:], x.dtype)
            cnt = lax.reduce_window(ones, 0.0, lax.add, tuple(params.kernel), tuple(params.stride), pads[2:])
            y = s / cnt[None, None]
        return [apply_activation(params.activation, y)]

    @staticmethod
    def cost(params: Pool2DParams, input_specs, output_specs):
        k = params.kernel[0] * params.kernel[1]
        return io_cost(input_specs, output_specs, flops=float(k) * output_specs[0].num_elements)
