"""Embedding operator.

Reference: src/ops/embedding.cc (1205 LoC) + kernels/embedding_kernels.cu.
Supports SUM/AVG aggregation over a bag of indices per sample
(reference AggrMode) and plain per-token lookup when aggr=NONE.
TPU-native: jnp.take — XLA lowers gathers efficiently on TPU; for
attribute-parallel (vocab-sharded) embeddings the strategy layer shards
the table's vocab dim and XLA inserts the needed collectives.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import AggrMode, DataType, OpType
from .base import LowerCtx, OpCost, OpDef, WeightSpec, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class EmbeddingParams:
    num_entries: int
    out_dim: int
    aggr: AggrMode = AggrMode.NONE
    dtype: DataType = DataType.FLOAT
    initializer: str = "glorot_uniform"


@register_op
class EmbeddingOp(OpDef):
    op_type = OpType.EMBEDDING
    params_cls = EmbeddingParams

    @staticmethod
    def infer_output_specs(params: EmbeddingParams, input_specs: List[TensorSpec]):
        (idx,) = input_specs
        if params.aggr == AggrMode.NONE:
            # per-token lookup: [..., ] -> [..., out_dim]
            return [TensorSpec(idx.shape + (params.out_dim,), params.dtype)]
        # bag aggregation over the last dim: [B, bag] -> [B, out_dim]
        return [TensorSpec(idx.shape[:-1] + (params.out_dim,), params.dtype)]

    @staticmethod
    def weight_specs(params: EmbeddingParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        return [
            WeightSpec(
                "embedding",
                TensorSpec((params.num_entries, params.out_dim), params.dtype),
                params.initializer,
            )
        ]

    @staticmethod
    def lower(params: EmbeddingParams, inputs, weights, ctx: LowerCtx):
        (idx,) = inputs
        table = weights["embedding"]
        vecs = jnp.take(table, idx.astype(jnp.int32), axis=0)
        if params.aggr == AggrMode.SUM:
            vecs = jnp.sum(vecs, axis=-2)
        elif params.aggr == AggrMode.AVG:
            vecs = jnp.mean(vecs, axis=-2)
        return [vecs]

    @staticmethod
    def cost(params: EmbeddingParams, input_specs, output_specs) -> OpCost:
        (idx,) = input_specs
        gathered = idx.num_elements * params.out_dim * params.dtype.size_bytes
        table_bytes = params.num_entries * params.out_dim * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=float(idx.num_elements * params.out_dim), extra_mem=table_bytes)
        c.bytes_accessed += gathered
        return c
