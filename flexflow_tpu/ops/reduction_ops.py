"""Reduction-style operators: Gather, ReduceSum, Mean.

Reference: src/ops/gather.cc (424), src/ops/reduce.cc (411, keepdims),
src/ops/mean.cc (114).
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import OpType
from .base import OpDef, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class GatherParams:
    axis: int


@register_op
class GatherOp(OpDef):
    """torch.gather semantics: index tensor same rank as input
    (reference: gather.cc — index/input shapes match except on `axis`)."""

    op_type = OpType.GATHER
    params_cls = GatherParams

    @staticmethod
    def infer_output_specs(params: GatherParams, input_specs: List[TensorSpec]):
        data, index = input_specs
        return [TensorSpec(index.shape, data.dtype)]

    @staticmethod
    def lower(params: GatherParams, inputs, weights, ctx):
        data, index = inputs
        return [jnp.take_along_axis(data, index.astype(jnp.int32), axis=params.axis)]


@dataclasses.dataclass(frozen=True)
class ReduceSumParams:
    axes: Tuple[int, ...]
    keepdims: bool = False


@register_op
class ReduceSumOp(OpDef):
    op_type = OpType.REDUCE_SUM
    params_cls = ReduceSumParams

    @staticmethod
    def infer_output_specs(params: ReduceSumParams, input_specs: List[TensorSpec]):
        (x,) = input_specs
        axes = {a % x.ndim for a in params.axes}
        if params.keepdims:
            shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape))
        else:
            shape = tuple(s for i, s in enumerate(x.shape) if i not in axes)
        return [TensorSpec(shape, x.dtype)]

    @staticmethod
    def lower(params: ReduceSumParams, inputs, weights, ctx):
        return [jnp.sum(inputs[0], axis=params.axes, keepdims=params.keepdims)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=float(input_specs[0].num_elements))


@dataclasses.dataclass(frozen=True)
class MeanParams:
    axes: Tuple[int, ...]
    keepdims: bool = False


@register_op
class MeanOp(OpDef):
    op_type = OpType.MEAN
    params_cls = MeanParams

    @staticmethod
    def infer_output_specs(params: MeanParams, input_specs: List[TensorSpec]):
        return ReduceSumOp.infer_output_specs(
            ReduceSumParams(params.axes, params.keepdims), input_specs
        )

    @staticmethod
    def lower(params: MeanParams, inputs, weights, ctx):
        return [jnp.mean(inputs[0], axis=params.axes, keepdims=params.keepdims)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=float(input_specs[0].num_elements))
