"""BatchMatmul operator.

Reference: src/ops/batch_matmul.cc (711 LoC) + kernels/batch_matmul.cu
(cublasGemmStridedBatchedEx). Carries the reference's per-input
seq-length-dim early-truncation feature (model.h:483-487): at trace time
a ``seq_length`` in the iteration config slices the marked dims.
Computes C[b] = A[b] @ B[b] over leading batch dims.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import OpType
from .base import LowerCtx, OpCost, OpDef, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class BatchMatmulParams:
    a_seq_length_dim: int = -1
    b_seq_length_dim: int = -1


@register_op
class BatchMatmulOp(OpDef):
    op_type = OpType.BATCH_MATMUL
    params_cls = BatchMatmulParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        a, b = input_specs
        if a.shape[:-2] != b.shape[:-2]:
            raise ValueError(f"batch dims mismatch: {a.shape} vs {b.shape}")
        if a.shape[-1] != b.shape[-2]:
            raise ValueError(f"contraction mismatch: {a.shape} @ {b.shape}")
        return [TensorSpec(a.shape[:-1] + (b.shape[-1],), a.dtype)]

    @staticmethod
    def lower(params: BatchMatmulParams, inputs, weights, ctx: LowerCtx):
        a, b = inputs
        seq = getattr(ctx, "seq_length", None)
        if seq is not None:
            if params.a_seq_length_dim >= 0:
                a = jnp.take(a, jnp.arange(seq), axis=params.a_seq_length_dim)
            if params.b_seq_length_dim >= 0:
                b = jnp.take(b, jnp.arange(seq), axis=params.b_seq_length_dim)
        return [jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)]

    @staticmethod
    def cost(params, input_specs, output_specs) -> OpCost:
        a, b = input_specs
        k = a.shape[-1]
        flops = 2.0 * output_specs[0].num_elements * k
        return io_cost(input_specs, output_specs, flops=flops)
