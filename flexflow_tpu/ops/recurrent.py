"""Recurrent operators: vanilla RNN and LSTM.

Reference: nmt/ (3980 LoC) — the legacy standalone LSTM/RNN NMT app
predating FFModel (nmt/rnn.h, nmt/lstm.cc CUDA kernels via cudnnRNN).
TPU-native: lax.scan over time — XLA unrolls the recurrence into a
single compiled loop; the input projection for ALL timesteps is one
large matmul (good MXU utilization), only the hidden recurrence scans.

Layout: sequences are batch-first [B, T, D]; hidden states [B, H].
Both ops emit (sequence, final_h[, final_c]) so encoder final states can
initialize a decoder (optional inputs h0[, c0]).
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import ActiMode, DataType, OpType
from .base import LowerCtx, OpCost, OpDef, WeightSpec, io_cost, register_op
from .elementwise import apply_activation


@dataclasses.dataclass(frozen=True)
class RecurrentParams:
    hidden_size: int
    dtype: DataType = DataType.FLOAT
    activation: ActiMode = ActiMode.TANH  # RNN cell nonlinearity
    kernel_initializer: str = "glorot_uniform"


def _scan_time_major(step, init_carry, x_proj):
    """Scan over [T, B, G] input projections."""
    (carry, ys) = jax.lax.scan(step, init_carry, x_proj)
    return carry, ys


@register_op
class RNNOp(OpDef):
    """Elman RNN: h_t = act(x_t @ Wx + h_{t-1} @ Wh + b)."""

    op_type = OpType.RNN
    params_cls = RecurrentParams

    @staticmethod
    def infer_output_specs(params: RecurrentParams, input_specs: List[TensorSpec]):
        x = input_specs[0]
        b, t = x.shape[0], x.shape[1]
        h = params.hidden_size
        return [
            TensorSpec((b, t, h), params.dtype),  # sequence
            TensorSpec((b, h), params.dtype),  # final hidden
        ]

    @staticmethod
    def weight_specs(params: RecurrentParams, input_specs: List[TensorSpec]):
        x = input_specs[0]
        d, h = x.shape[-1], params.hidden_size
        init = params.kernel_initializer
        return [
            WeightSpec("wx", TensorSpec((d, h), params.dtype), init),
            WeightSpec("wh", TensorSpec((h, h), params.dtype), "orthogonal"),
            WeightSpec("bias", TensorSpec((h,), params.dtype), "zeros"),
        ]

    @staticmethod
    def lower(params: RecurrentParams, inputs, weights, ctx: LowerCtx):
        x = inputs[0]
        b = x.shape[0]
        h = params.hidden_size
        h0 = inputs[1] if len(inputs) > 1 else jnp.zeros((b, h), x.dtype)
        # one big [B*T, D] @ [D, H] matmul for every step's input part
        xp = jnp.einsum("btd,dh->tbh", x, weights["wx"]) + weights["bias"]

        def step(carry, xt):
            nxt = apply_activation(
                params.activation,
                xt + jnp.dot(carry, weights["wh"], preferred_element_type=jnp.float32).astype(xt.dtype),
            )
            return nxt, nxt

        hT, ys = _scan_time_major(step, h0, xp)
        return [jnp.swapaxes(ys, 0, 1), hT]

    @staticmethod
    def cost(params: RecurrentParams, input_specs, output_specs) -> OpCost:
        x = input_specs[0]
        b, t, d = x.shape[0], x.shape[1], x.shape[-1]
        h = params.hidden_size
        flops = 2.0 * b * t * (d * h + h * h)
        w_bytes = (d * h + h * h + h) * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=flops, extra_mem=w_bytes)
        c.bytes_accessed += w_bytes + t * b * h * params.dtype.size_bytes
        return c


@register_op
class LSTMOp(OpDef):
    """LSTM with fused gates (i, f, g, o), forget bias 1.0
    (reference: nmt/lstm.cc's cudnnRNN LSTM mode)."""

    op_type = OpType.LSTM
    params_cls = RecurrentParams

    @staticmethod
    def infer_output_specs(params: RecurrentParams, input_specs: List[TensorSpec]):
        x = input_specs[0]
        b, t = x.shape[0], x.shape[1]
        h = params.hidden_size
        return [
            TensorSpec((b, t, h), params.dtype),  # sequence
            TensorSpec((b, h), params.dtype),  # final hidden
            TensorSpec((b, h), params.dtype),  # final cell
        ]

    @staticmethod
    def weight_specs(params: RecurrentParams, input_specs: List[TensorSpec]):
        x = input_specs[0]
        d, h = x.shape[-1], params.hidden_size
        init = params.kernel_initializer
        return [
            WeightSpec("wx", TensorSpec((d, 4 * h), params.dtype), init),
            WeightSpec("wh", TensorSpec((h, 4 * h), params.dtype), "orthogonal"),
            WeightSpec("bias", TensorSpec((4 * h,), params.dtype), "zeros"),
        ]

    @staticmethod
    def lower(params: RecurrentParams, inputs, weights, ctx: LowerCtx):
        x = inputs[0]
        b = x.shape[0]
        h = params.hidden_size
        h0 = inputs[1] if len(inputs) > 1 else jnp.zeros((b, h), x.dtype)
        c0 = inputs[2] if len(inputs) > 2 else jnp.zeros((b, h), x.dtype)
        xp = jnp.einsum("btd,dg->tbg", x, weights["wx"]) + weights["bias"]

        def step(carry, xt):
            hp, cp = carry
            gates = xt + jnp.dot(hp, weights["wh"], preferred_element_type=jnp.float32).astype(xt.dtype)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f + 1.0)  # forget-gate bias 1.0
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c = f * cp + i * g
            hn = o * jnp.tanh(c)
            return (hn, c), hn

        (hT, cT), ys = _scan_time_major(step, (h0, c0), xp)
        return [jnp.swapaxes(ys, 0, 1), hT, cT]

    @staticmethod
    def cost(params: RecurrentParams, input_specs, output_specs) -> OpCost:
        x = input_specs[0]
        b, t, d = x.shape[0], x.shape[1], x.shape[-1]
        h = params.hidden_size
        flops = 2.0 * b * t * 4 * (d * h + h * h)
        w_bytes = (d * 4 * h + h * 4 * h + 4 * h) * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=flops, extra_mem=w_bytes)
        c.bytes_accessed += w_bytes + t * b * h * params.dtype.size_bytes
        return c
