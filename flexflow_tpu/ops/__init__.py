"""Operator library: registry population.

Importing this package registers every OpDef (analog of the reference's
``register_flexflow_internal_tasks``, src/runtime/model.cc:4201 — except
registration here is shape-inference + JAX lowering + cost, not Legion
task variants).
"""
from .base import (  # noqa: F401
    LowerCtx,
    OpCost,
    OpDef,
    WeightSpec,
    get_op_def,
    register_op,
    registered_ops,
)
from . import io_ops  # noqa: F401
from . import elementwise  # noqa: F401
from . import linear  # noqa: F401
from . import batch_matmul  # noqa: F401
from . import conv  # noqa: F401
from . import attention  # noqa: F401
from . import embedding  # noqa: F401
from . import norm  # noqa: F401
from . import softmax  # noqa: F401
from . import shape_ops  # noqa: F401
from . import reduction_ops  # noqa: F401
from . import moe_ops  # noqa: F401
from . import parallel_ops  # noqa: F401
from . import recurrent  # noqa: F401
