"""Parallel operators — sharding transitions in the PCG.

Reference: src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc + their CUDA kernels, which physically copy/reduce
data between differently-partitioned Legion regions. TPU-native, these
are *annotations*: each lowers to jax.lax.with_sharding_constraint and
GSPMD materializes the movement as XLA collectives on ICI —
  Repartition -> dynamic-slice / all-to-all   (partition.cc)
  Combine     -> all-gather                   (combine.cc:74)
  Replicate   -> broadcast                    (replicate.cc)
  Reduction   -> reduce-scatter / psum        (reduction.cc)
  AllReduce   -> psum
  FusedParallelOp -> one combined reshard     (fused_parallel_op.cc)
Logical shapes are unchanged; what changes is the ParallelTensorSpec
(dims' degree / mesh_axis), which the strategy layer tracks.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax

from ..core.tensor import TensorSpec
from ..core.types import OpType
from .base import LowerCtx, OpCost, OpDef, register_op


def _constrain(x: jax.Array, ctx: LowerCtx, partition_spec) -> jax.Array:
    """Apply a sharding constraint if we're lowering under a mesh."""
    mesh = getattr(ctx, "mesh", None)
    if mesh is None or partition_spec is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec(*partition_spec)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


class _ParallelOpBase(OpDef):
    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def cost(params, input_specs, output_specs) -> OpCost:
        # communication cost is modeled by the simulator per machine view,
        # not per-op flops (reference: estimate_xfer_cost simulator.cc:671)
        return OpCost()


@dataclasses.dataclass(frozen=True)
class RepartitionParams:
    dim: int  # tensor dim to shard
    degree: int
    mesh_axis: Optional[str] = None
    # full output partition spec (per logical dim, tuple of axis names or None)
    out_spec: Optional[Tuple] = None


@register_op
class RepartitionOp(_ParallelOpBase):
    op_type = OpType.REPARTITION
    params_cls = RepartitionParams

    @staticmethod
    def lower(params: RepartitionParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        spec = params.out_spec
        if spec is None and params.mesh_axis is not None:
            spec = tuple(params.mesh_axis if i == params.dim else None for i in range(x.ndim))
        return [_constrain(x, ctx, spec)]


@dataclasses.dataclass(frozen=True)
class CombineParams:
    dim: int  # dim being un-sharded (all-gathered)
    degree: int
    out_spec: Optional[Tuple] = None


@register_op
class CombineOp(_ParallelOpBase):
    op_type = OpType.COMBINE
    params_cls = CombineParams

    @staticmethod
    def lower(params: CombineParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        spec = params.out_spec if params.out_spec is not None else tuple(None for _ in range(x.ndim))
        return [_constrain(x, ctx, spec)]


@dataclasses.dataclass(frozen=True)
class ReplicateParams:
    degree: int
    out_spec: Optional[Tuple] = None


@register_op
class ReplicateOp(_ParallelOpBase):
    op_type = OpType.REPLICATE
    params_cls = ReplicateParams

    @staticmethod
    def lower(params: ReplicateParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        return [_constrain(x, ctx, params.out_spec or tuple(None for _ in range(x.ndim)))]


@dataclasses.dataclass(frozen=True)
class ReductionParams:
    degree: int  # replica-dim partial results being summed
    out_spec: Optional[Tuple] = None


@register_op
class ReductionOp(_ParallelOpBase):
    op_type = OpType.REDUCTION
    params_cls = ReductionParams

    @staticmethod
    def lower(params: ReductionParams, inputs, weights, ctx: LowerCtx):
        # Under GSPMD the partial-sum reduction is inserted by XLA where the
        # producing contraction was sharded; the node pins the output layout.
        (x,) = inputs
        return [_constrain(x, ctx, params.out_spec or tuple(None for _ in range(x.ndim)))]


@dataclasses.dataclass(frozen=True)
class AllReduceParams:
    degree: int
    out_spec: Optional[Tuple] = None


@register_op
class AllReduceOp(_ParallelOpBase):
    op_type = OpType.ALLREDUCE
    params_cls = AllReduceParams

    @staticmethod
    def lower(params: AllReduceParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        return [_constrain(x, ctx, params.out_spec or tuple(None for _ in range(x.ndim)))]


@dataclasses.dataclass(frozen=True)
class FusedParallelParams:
    # sequence of (kind, dim, degree) transitions fused into one reshard
    transitions: Tuple = ()
    out_spec: Optional[Tuple] = None


@register_op
class FusedParallelOp(_ParallelOpBase):
    op_type = OpType.FUSED_PARALLEL
    params_cls = FusedParallelParams

    @staticmethod
    def lower(params: FusedParallelParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        return [_constrain(x, ctx, params.out_spec)]
