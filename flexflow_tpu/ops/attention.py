"""MultiHeadAttention operator.

Reference: src/ops/attention.cc (926 LoC) lowering to a monolithic
``cudnnMultiHeadAttnForward`` (src/ops/attention.cu:35) with qkv+output
projection weights woven into one tensor. TPU-native: explicit q/k/v/o
projections (MXU matmuls) around a fused attention core — a Pallas
flash-attention kernel on TPU (ops/kernels/flash_attention.py), falling
back to the einsum/softmax composition under jit elsewhere. Unlike the
reference (no causal masking, no long-context support at all — SURVEY
§2.2), this op supports causal masks and, via the strategy layer,
sequence-parallel ring attention.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import DataType, OpType
from .base import LowerCtx, OpCost, OpDef, WeightSpec, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class MultiHeadAttentionParams:
    embed_dim: int
    num_heads: int
    kdim: int = 0  # 0 -> embed_dim // num_heads
    vdim: int = 0
    dropout: float = 0.0
    use_bias: bool = False  # reference: bias flag
    causal: bool = False  # new capability (absent in reference)
    dtype: DataType = DataType.FLOAT

    @property
    def head_dim(self) -> int:
        return self.kdim or self.embed_dim // self.num_heads

    @property
    def v_head_dim(self) -> int:
        return self.vdim or self.embed_dim // self.num_heads


@register_op
class MultiHeadAttentionOp(OpDef):
    op_type = OpType.MULTIHEAD_ATTENTION
    params_cls = MultiHeadAttentionParams

    @staticmethod
    def infer_output_specs(params: MultiHeadAttentionParams, input_specs: List[TensorSpec]):
        q = input_specs[0]
        return [TensorSpec(q.shape[:-1] + (params.embed_dim,), params.dtype)]

    @staticmethod
    def weight_specs(params: MultiHeadAttentionParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        q, k, v = input_specs
        h, dk, dv, e = params.num_heads, params.head_dim, params.v_head_dim, params.embed_dim
        dt = params.dtype
        ws = [
            WeightSpec("wq", TensorSpec((q.shape[-1], h, dk), dt), "glorot_uniform"),
            WeightSpec("wk", TensorSpec((k.shape[-1], h, dk), dt), "glorot_uniform"),
            WeightSpec("wv", TensorSpec((v.shape[-1], h, dv), dt), "glorot_uniform"),
            WeightSpec("wo", TensorSpec((h, dv, e), dt), "glorot_uniform"),
        ]
        if params.use_bias:
            ws += [
                WeightSpec("bq", TensorSpec((h, dk), dt), "zeros"),
                WeightSpec("bk", TensorSpec((h, dk), dt), "zeros"),
                WeightSpec("bv", TensorSpec((h, dv), dt), "zeros"),
                WeightSpec("bo", TensorSpec((e,), dt), "zeros"),
            ]
        return ws

    @staticmethod
    def lower(params: MultiHeadAttentionParams, inputs, weights, ctx: LowerCtx):
        q, k, v = inputs
        # projections: [B, S, E] x [E, H, D] -> [B, S, H, D]
        qh = jnp.einsum("bse,ehd->bshd", q, weights["wq"])
        kh = jnp.einsum("bse,ehd->bshd", k, weights["wk"])
        vh = jnp.einsum("bse,ehd->bshd", v, weights["wv"])
        if params.use_bias:
            qh = qh + weights["bq"]
            kh = kh + weights["bk"]
            vh = vh + weights["bv"]
        cp_axis = getattr(ctx, "cp_axis", None)
        mesh = getattr(ctx, "mesh", None)
        seq_cp = (
            mesh is not None
            and "seq" in mesh.axis_names
            and mesh.shape["seq"] > 1
            and qh.shape[1] % mesh.shape["seq"] == 0
            # cross-attention: K/V carry their OWN sequence length (the
            # encoder side), which must also divide or the kernel's
            # in_specs reject it at trace time — fall back to dense
            and kh.shape[1] % mesh.shape["seq"] == 0
        )
        if cp_axis is not None and getattr(ctx, "kv_seq_replicated", False):
            # pp x cp cross-attention whose shared K/V seq dim couldn't
            # shard: K/V are FULL-LENGTH on every cp shard, so dense
            # attention over the local complete memory gives the exact
            # result — a ring over cp identical copies computes the same
            # softmax at cp x the FLOPs plus cp-1 full-size ppermutes
            # (ADVICE r4)
            if params.causal:
                raise ValueError(
                    "pp x cp: causal attention over cp-replicated K/V has "
                    "no well-defined local mask; use a seq length divisible "
                    "by cp or drop cp"
                )
            ctx_out = attention_core(qh, kh, vh, causal=False, backend=ctx.backend)
        elif cp_axis is not None:
            # manual context parallelism (inside a pipeline stage's
            # shard_map): the sequence dim of q/k/v is sharded over
            # cp_axis — K/V ride the ring (pp x cp composition); shares
            # the projection/bias/dropout tail below
            from .kernels.ring_attention import ring_attention

            ctx_out = ring_attention(
                qh, kh, vh, axis_name=cp_axis, causal=params.causal
            )
        elif seq_cp:
            # context parallelism: sequence dim sharded on the "seq" axis,
            # K/V ride the ICI ring (new capability; reference has none).
            # cp x tp: Megatron-sharded projections keep their heads on
            # "model" through the kernel instead of re-gathering
            from .kernels.ring_attention import ring_attention_sharded

            head_axis = (
                "model"
                if (
                    "model" in mesh.axis_names
                    and mesh.shape["model"] > 1
                    and qh.shape[2] % mesh.shape["model"] == 0
                )
                else None
            )
            ctx_out = ring_attention_sharded(
                qh, kh, vh, mesh, seq_axis="seq", causal=params.causal,
                head_axis=head_axis,
            )
        else:
            ctx_out = attention_core(qh, kh, vh, causal=params.causal, backend=ctx.backend)
        out = jnp.einsum("bshd,hde->bse", ctx_out, weights["wo"])
        # manual tensor parallelism (inside shard_map — GPipe stages):
        # head-sharded wq/wk/wv make ctx_out carry H/tp local heads and
        # wo sharded on H is row-parallel — reduce the partial output
        # projections over the tp axis before the (replicated) bias
        if ctx.weight_sharded_dim("wo") == 0:
            out = jax.lax.psum(out, ctx.tp_axis)
        if params.use_bias:
            out = out + weights["bo"]
        if params.dropout > 0.0 and ctx.training:
            keep = 1.0 - params.dropout
            # per-shard key: every manual shard (seq and/or data) must
            # draw an INDEPENDENT mask — one shared key would repeat the
            # pattern every S/cp positions and across batch shards
            key = ctx.shard_rng()
            mask = jax.random.bernoulli(key, keep, out.shape)
            out = jnp.where(mask, out / keep, 0.0).astype(out.dtype)
        return [out.astype(params.dtype.jnp)]

    @staticmethod
    def cost(params: MultiHeadAttentionParams, input_specs, output_specs) -> OpCost:
        q, k, v = input_specs
        b, sq = q.shape[0], q.shape[1]
        sk = k.shape[1]
        h, dk, dv, e = params.num_heads, params.head_dim, params.v_head_dim, params.embed_dim
        proj = 2.0 * b * (sq * q.shape[-1] * h * dk + sk * k.shape[-1] * h * dk + sk * v.shape[-1] * h * dv + sq * h * dv * e)
        core = 2.0 * b * h * sq * sk * (dk + dv)
        w_elems = q.shape[-1] * h * dk + k.shape[-1] * h * dk + v.shape[-1] * h * dv + h * dv * e
        w_bytes = w_elems * params.dtype.size_bytes
        c = io_cost(input_specs, output_specs, flops=proj + core, extra_mem=w_bytes)
        c.bytes_accessed += w_bytes
        return c


def attention_core(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    backend: str = "tpu",
    scale: Optional[float] = None,
) -> jax.Array:
    """Scaled dot-product attention over [B, S, H, D] tensors.

    Dispatches to the Pallas flash-attention kernel on TPU backends and to
    the XLA einsum composition elsewhere (CPU test meshes, interpret mode).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if backend == "tpu":
        try:
            from .kernels.flash_attention import flash_attention, on_tpu, supports_shapes
        except ImportError:
            flash_attention = None
        if flash_attention is not None and on_tpu() and supports_shapes(q.shape, k.shape):
            return flash_attention(q, k, v, causal=causal, scale=scale)
    return reference_attention(q, k, v, causal=causal, scale=scale)


def decode_attention_core(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    backend: str = "tpu",
    scale: Optional[float] = None,
    mesh=None,
    head_axis: str = "model",
) -> jax.Array:
    """Decode-mode attention: one query token per sequence ([B, H, D])
    over a block-structured KV cache with position masking, so
    incremental decode reproduces full-context causal logits.

    Dispatches to the Pallas paged-attention kernel on TPU backends
    (kernels/decode_attention.py) and to the XLA gather + masked softmax
    composition elsewhere (paged_decode_attention itself falls back on
    pallas-less jax builds). ``mesh`` with a >1 ``head_axis`` selects
    the HEAD-SHARDED kernel path (ISSUE 15): each shard's kernel runs
    over its local KV heads via shard_map; the reference path needs no
    mesh plumb — GSPMD partitions the plain-XLA composition itself.
    """
    from .kernels.decode_attention import (
        on_tpu,
        paged_decode_attention,
        reference_paged_attention,
        sharded_paged_decode_attention,
        supports_decode_shapes,
    )

    tp = 1 if mesh is None else int(dict(mesh.shape).get(head_axis, 1))
    if (
        backend == "tpu"
        and on_tpu()
        and q.shape[1] % max(1, tp) == 0
        and supports_decode_shapes(
            q.shape[1] // max(1, tp), q.shape[2], k_cache.shape[1]
        )
    ):
        if tp > 1:
            return sharded_paged_decode_attention(
                q, k_cache, v_cache, block_tables, context_lens,
                mesh, axis=head_axis, scale=scale,
            )
        return paged_decode_attention(
            q, k_cache, v_cache, block_tables, context_lens, scale=scale
        )
    return reference_paged_attention(
        q, k_cache, v_cache, block_tables, context_lens, scale=scale
    )


def append_attention_core(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    backend: str = "tpu",
    scale: Optional[float] = None,
    mesh=None,
    head_axis: str = "model",
) -> jax.Array:
    """Chunked-append attention: a W-token window per sequence
    ([B, W, H, D], K/V already written) over the block-structured KV
    cache. Query (b, w) attends cache positions ``<= q_positions[b, w]``
    — causal within the window, full history before it — so verifying a
    k+1-token speculative window in one forward reproduces the k+1
    sequential decode steps' logits exactly. ``q_positions < 0`` marks
    fixed-shape padding queries (they emit zeros). Decode-mode attention
    is the W = 1 special case.

    Dispatches to the generalized Pallas paged kernel on TPU backends
    (kernels/decode_attention.py) and to the XLA gather + masked softmax
    composition elsewhere. ``mesh`` with a >1 ``head_axis`` selects the
    head-sharded shard_map kernel path (see
    :func:`decode_attention_core`).
    """
    from .kernels.decode_attention import (
        on_tpu,
        paged_append_attention,
        reference_paged_append_attention,
        sharded_paged_append_attention,
        supports_append_shapes,
    )

    tp = 1 if mesh is None else int(dict(mesh.shape).get(head_axis, 1))
    if (
        backend == "tpu"
        and on_tpu()
        and q.shape[2] % max(1, tp) == 0
        and supports_append_shapes(
            q.shape[2] // max(1, tp), q.shape[3], k_cache.shape[1], q.shape[1]
        )
    ):
        if tp > 1:
            return sharded_paged_append_attention(
                q, k_cache, v_cache, block_tables, q_positions,
                mesh, axis=head_axis, scale=scale,
            )
        return paged_append_attention(
            q, k_cache, v_cache, block_tables, q_positions, scale=scale
        )
    return reference_paged_append_attention(
        q, k_cache, v_cache, block_tables, q_positions, scale=scale
    )


def masked_attention(q, k, v, lengths, causal=True, scale=None):
    """Causal attention over [B, S, H, D] with a per-sequence valid
    length: key positions >= lengths[b] are masked. The prefill side of
    the decode split — bucketed (padded) prompts attend only over their
    real tokens, so prefill logits match the unpadded forward."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    sq, sk = logits.shape[-2], logits.shape[-1]
    mask = jnp.arange(sk)[None, :] < lengths[:, None]  # [B, Sk]
    mask = mask[:, None, None, :]
    if causal:
        mask = jnp.logical_and(
            mask, jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)[None, None]
        )
    logits = jnp.where(mask, logits, -jnp.inf)
    m = jnp.max(logits, axis=-1, keepdims=True)
    # fully-masked rows (padding queries) get uniform-zero probs, not NaN
    p = jnp.where(mask, jnp.exp(logits - jnp.maximum(m, -1e30)), 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return jnp.einsum("bhqk,bkhd->bqhd", (p / l).astype(v.dtype), v)


def reference_attention(q, k, v, causal=False, scale=None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
