"""Elementwise binary / unary / scalar operators.

Reference: src/ops/element_binary.cc (812 LoC, add/sub/mul/div/max/min with
broadcast + inplace) and src/ops/element_unary.cc (696 LoC,
relu/sigmoid/tanh/elu/gelu/identity/exp/sin/cos/rsqrt/pow + scalar ops).
TPU-native: plain jnp ops — XLA fuses entire elementwise chains into the
neighboring matmul/conv, so the reference's "inplace" optimization
(model.cc:2904-2938) is subsumed by the compiler.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import ActiMode, OpType
from .base import LowerCtx, OpCost, OpDef, io_cost, register_op


def apply_activation(mode: ActiMode, x: jax.Array) -> jax.Array:
    if mode == ActiMode.NONE:
        return x
    if mode == ActiMode.RELU:
        return jax.nn.relu(x)
    if mode == ActiMode.SIGMOID:
        return jax.nn.sigmoid(x)
    if mode == ActiMode.TANH:
        return jnp.tanh(x)
    if mode == ActiMode.GELU:
        # exact erf form: aligns with torch F.gelu default and the
        # reference's erf-based CUDA kernel (element_unary.cu)
        return jax.nn.gelu(x, approximate=False)
    raise ValueError(f"unknown activation {mode}")


def broadcast_shape(a, b):
    return jnp.broadcast_shapes(a, b)


_BINARY_FNS = {
    OpType.EW_ADD: jnp.add,
    OpType.EW_SUB: jnp.subtract,
    OpType.EW_MUL: jnp.multiply,
    OpType.EW_DIV: jnp.divide,
    OpType.EW_MAX: jnp.maximum,
    OpType.EW_MIN: jnp.minimum,
}

_UNARY_FNS = {
    OpType.RELU: jax.nn.relu,
    OpType.SIGMOID: jax.nn.sigmoid,
    OpType.TANH: jnp.tanh,
    OpType.ELU: jax.nn.elu,
    OpType.GELU: lambda x: jax.nn.gelu(x, approximate=False),
    OpType.IDENTITY: lambda x: x,
    OpType.EXP: jnp.exp,
    OpType.SIN: jnp.sin,
    OpType.COS: jnp.cos,
    OpType.RSQRT: jax.lax.rsqrt,
}


@dataclasses.dataclass(frozen=True)
class ElementBinaryParams:
    op: OpType  # one of _BINARY_FNS
    inplace_a: bool = False  # API parity; XLA handles buffer reuse


def _make_binary(op_type: OpType):
    class _Binary(OpDef):
        pass

    _Binary.op_type = op_type
    _Binary.params_cls = ElementBinaryParams
    _Binary.__name__ = f"ElementBinary_{op_type.value}"

    def infer_output_specs(params, input_specs: List[TensorSpec]):
        a, b = input_specs
        return [TensorSpec(broadcast_shape(a.shape, b.shape), a.dtype)]

    def lower(params, inputs, weights, ctx):
        a, b = inputs
        return [_BINARY_FNS[op_type](a, b)]

    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=output_specs[0].num_elements)

    _Binary.infer_output_specs = staticmethod(infer_output_specs)
    _Binary.lower = staticmethod(lower)
    _Binary.cost = staticmethod(cost)
    return register_op(_Binary)


for _t in _BINARY_FNS:
    _make_binary(_t)


@dataclasses.dataclass(frozen=True)
class ElementUnaryParams:
    op: OpType
    scalar: float = 0.0  # used by scalar_* and pow
    inplace: bool = False


def _make_unary(op_type: OpType):
    class _Unary(OpDef):
        pass

    _Unary.op_type = op_type
    _Unary.params_cls = ElementUnaryParams
    _Unary.__name__ = f"ElementUnary_{op_type.value}"

    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    def lower(params, inputs, weights, ctx):
        (x,) = inputs
        if op_type in _UNARY_FNS:
            return [_UNARY_FNS[op_type](x)]
        s = params.scalar
        if op_type == OpType.POW:
            return [jnp.power(x, s)]
        if op_type == OpType.SCALAR_ADD:
            return [x + s]
        if op_type == OpType.SCALAR_SUB:
            return [x - s]
        if op_type == OpType.SCALAR_MUL:
            return [x * s]
        if op_type == OpType.SCALAR_TRUE_DIV:
            return [x / s]
        raise ValueError(f"unknown unary {op_type}")

    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=output_specs[0].num_elements)

    _Unary.infer_output_specs = staticmethod(infer_output_specs)
    _Unary.lower = staticmethod(lower)
    _Unary.cost = staticmethod(cost)
    return register_op(_Unary)


for _t in list(_UNARY_FNS) + [
    OpType.POW,
    OpType.SCALAR_ADD,
    OpType.SCALAR_SUB,
    OpType.SCALAR_MUL,
    OpType.SCALAR_TRUE_DIV,
]:
    _make_unary(_t)
