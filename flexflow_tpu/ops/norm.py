"""Normalization operators: LayerNorm, BatchNorm.

Reference: src/ops/layer_norm.cc (601 LoC) + layer_norm.cu,
src/ops/batch_norm.cc (322 LoC) + batch_norm.cu (cudnnBatchNormalization,
optional fused relu). TPU-native: jnp reductions — XLA fuses the
mean/var/normalize chain into one pass. BatchNorm running statistics are
functional state threaded through LowerCtx.state_updates instead of
mutable cuDNN tensors.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..core.types import DataType, OpType
from .base import LowerCtx, OpDef, WeightSpec, io_cost, register_op


@dataclasses.dataclass(frozen=True)
class LayerNormParams:
    axes: tuple  # normalized axes (reference: reversed Legion order; here NumPy order)
    elementwise_affine: bool = True
    eps: float = 1e-5
    dtype: DataType = DataType.FLOAT


@register_op
class LayerNormOp(OpDef):
    op_type = OpType.LAYERNORM
    params_cls = LayerNormParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def weight_specs(params: LayerNormParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        if not params.elementwise_affine:
            return []
        (x,) = input_specs
        shape = tuple(x.shape[a] for a in params.axes)
        return [
            WeightSpec("scale", TensorSpec(shape, params.dtype), "ones"),
            WeightSpec("bias", TensorSpec(shape, params.dtype), "zeros"),
        ]

    @staticmethod
    def lower(params: LayerNormParams, inputs, weights, ctx):
        (x,) = inputs
        axes = tuple(a % x.ndim for a in params.axes)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + params.eps)
        if params.elementwise_affine:
            bshape = [x.shape[a] if a in axes else 1 for a in range(x.ndim)]
            y = y * weights["scale"].reshape(bshape) + weights["bias"].reshape(bshape)
        return [y.astype(x.dtype)]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=8.0 * output_specs[0].num_elements)


@dataclasses.dataclass(frozen=True)
class BatchNormParams:
    relu: bool = True  # reference batch_norm has fused-relu option
    eps: float = 1e-5
    momentum: float = 0.9
    dtype: DataType = DataType.FLOAT


@register_op
class BatchNormOp(OpDef):
    """BatchNorm over NCHW input, stats over (N,H,W) per channel."""

    op_type = OpType.BATCHNORM
    params_cls = BatchNormParams

    @staticmethod
    def infer_output_specs(params, input_specs: List[TensorSpec]):
        return [input_specs[0]]

    @staticmethod
    def weight_specs(params: BatchNormParams, input_specs: List[TensorSpec]) -> List[WeightSpec]:
        (x,) = input_specs
        c = (x.shape[1],)
        return [
            WeightSpec("scale", TensorSpec(c, params.dtype), "ones"),
            WeightSpec("bias", TensorSpec(c, params.dtype), "zeros"),
            WeightSpec("running_mean", TensorSpec(c, params.dtype), "zeros", trainable=False),
            WeightSpec("running_var", TensorSpec(c, params.dtype), "ones", trainable=False),
        ]

    @staticmethod
    def lower(params: BatchNormParams, inputs, weights, ctx: LowerCtx):
        (x,) = inputs
        axes = (0, 2, 3) if x.ndim == 4 else (0,)
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        xf = x.astype(jnp.float32)
        if ctx.training:
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            m = params.momentum
            ctx.state_updates[(ctx.node_guid, "running_mean")] = (
                m * weights["running_mean"] + (1 - m) * mean.astype(params.dtype.jnp)
            )
            ctx.state_updates[(ctx.node_guid, "running_var")] = (
                m * weights["running_var"] + (1 - m) * var.astype(params.dtype.jnp)
            )
        else:
            mean = weights["running_mean"].astype(jnp.float32)
            var = weights["running_var"].astype(jnp.float32)
        y = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + params.eps)
        y = y * weights["scale"].reshape(shape) + weights["bias"].reshape(shape)
        y = y.astype(x.dtype)
        if params.relu:
            y = jax.nn.relu(y)
        return [y]

    @staticmethod
    def cost(params, input_specs, output_specs):
        return io_cost(input_specs, output_specs, flops=10.0 * output_specs[0].num_elements)
