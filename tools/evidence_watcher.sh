#!/bin/bash
# Retry the on-chip evidence runner until the tunnel answers.
# rc=0: complete. rc=2: probe failed (tunnel down) -> retry.
# rc=3: tunnel died mid-run (results so far are durably appended) -> retry.
cd /root/repo
for i in $(seq 1 90); do
  echo "=== watcher attempt $i $(date -u +%H:%M:%S) ===" >> .evidence_r5.log
  python tools/tpu_evidence.py >> .evidence_r5.log 2>&1
  rc=$?
  echo "=== runner rc=$rc ===" >> .evidence_r5.log
  if [ $rc -eq 0 ]; then break; fi
  if [ $rc -ne 2 ] && [ $rc -ne 3 ]; then
    echo "=== unexpected rc=$rc: not a tunnel outage, stopping ===" >> .evidence_r5.log
    break
  fi
  sleep 300
done
