#!/usr/bin/env python
"""flexlint — repo-invariant static analysis for flexflow_tpu.

The CI gate for the four bug classes every recent PR's review caught by
hand: guarded state touched outside its lock, wall-clock reads in
injectable-clock code, retrace/host-sync risks inside jit-traced
programs, and stringly-typed fault-site / Prometheus-family names that
a typo silently disables.

Usage:
  python tools/flexlint.py                      # lint, exit 1 on findings
  python tools/flexlint.py --json report.json   # + machine-readable report
  python tools/flexlint.py --rules clock-discipline,lock-discipline
  python tools/flexlint.py --list-rules
  python tools/flexlint.py --emit-site-table    # regenerate README table
  python tools/flexlint.py --update-baseline    # grandfather current findings

Exit codes: 0 clean (suppressed/baselined findings allowed), 1 findings,
2 bad invocation.

Suppress one finding in place:  # flexlint: disable=<rule> — <reason>
Baseline: tools/flexlint_baseline.json (kept EMPTY by policy; inline
suppressions carry the reasons, the baseline exists for incremental
adoption of future rules).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import types
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def _load_analysis():
    """Import flexflow_tpu.analysis WITHOUT executing flexflow_tpu's
    package __init__ (which imports jax): the linter is stdlib-only and
    must run in seconds, before — and regardless of — whether the heavy
    deps import."""
    if "flexflow_tpu.analysis" in sys.modules:
        return sys.modules["flexflow_tpu.analysis"]
    if "flexflow_tpu" not in sys.modules:
        stub = types.ModuleType("flexflow_tpu")
        stub.__path__ = [str(ROOT / "flexflow_tpu")]
        sys.modules["flexflow_tpu"] = stub
    spec = importlib.util.spec_from_file_location(
        "flexflow_tpu.analysis",
        ROOT / "flexflow_tpu" / "analysis" / "__init__.py",
        submodule_search_locations=[str(ROOT / "flexflow_tpu" / "analysis")],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["flexflow_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="flexlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--json", metavar="FILE",
                    help="write the machine-readable report here")
    ap.add_argument("--rules", metavar="R1,R2",
                    help="run only these rules (comma-separated ids)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline file (default tools/flexlint_baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current findings into the baseline and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--emit-site-table", action="store_true",
                    help="print the README fault-site table generated from "
                         "runtime/faults.py::SITES and exit")
    ap.add_argument("--root", default=str(ROOT),
                    help="repo root (default: this checkout)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no summary line")
    args = ap.parse_args(argv)

    flex = _load_analysis()
    root = Path(args.root)

    if args.list_rules:
        for r in flex.ALL_RULES:
            print(f"{r.name:22s} {r.description}")
        return 0

    if args.emit_site_table:
        faults_path = root / flex.Context.FAULTS_PATH
        _, sites, err = flex.parse_registry(
            faults_path.read_text(encoding="utf-8")
        )
        if err:
            print(f"flexlint: {err}", file=sys.stderr)
            return 2
        print(flex.emit_site_table(sites))
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]
    baseline_path = Path(args.baseline) if args.baseline else (
        root / flex.DEFAULT_BASELINE
    )
    try:
        report = flex.analyze_repo(root, rule_names,
                                   baseline_path=baseline_path)
    except KeyError as e:
        print(f"flexlint: {e.args[0]}", file=sys.stderr)
        return 2

    if args.json:
        Path(args.json).write_text(
            json.dumps(report.to_json(), indent=2) + "\n", encoding="utf-8"
        )

    if args.update_baseline:
        # keep grandfathered findings that still fire (report.baselined)
        # alongside the new ones; entries for rules OUTSIDE this run's
        # --rules scope are preserved verbatim (they were never checked).
        # "parse" findings are emitted by EVERY run, so stale parse
        # entries age out instead of being preserved forever.
        ran = {r.name for r in flex.rules_by_name(rule_names)} | {"parse"}
        entries = {
            (f.rule, f.path, f.message): f.to_json()
            for f in report.baselined + report.findings
        }
        if baseline_path.is_file():
            old = json.loads(baseline_path.read_text(encoding="utf-8"))
            for e in old.get("findings", []):
                if e["rule"] not in ran:
                    entries.setdefault((e["rule"], e["path"], e["message"]), e)
        payload = sorted(entries.values(),
                         key=lambda e: (e["path"], e["rule"], e["message"]))
        baseline_path.write_text(json.dumps(
            {"findings": payload}, indent=2, sort_keys=True,
        ) + "\n", encoding="utf-8")
        print(f"flexlint: baselined {len(payload)} finding(s) "
              f"into {baseline_path}")
        return 0

    for f in report.findings:
        print(f.render())
    if not args.quiet:
        print(
            f"flexlint: {len(report.findings)} finding(s), "
            f"{len(report.suppressed)} suppressed, "
            f"{len(report.baselined)} baselined, "
            f"{report.files_scanned} files scanned"
        )
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
