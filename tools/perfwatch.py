#!/usr/bin/env python
"""perfwatch: noise-aware perf-regression sentry over the genbench
trajectory.

tools/genbench.py appends one line per run to ``BENCH_HISTORY.jsonl``
(timestamped, git-sha-stamped, keyed by mode + backend). This tool
compares the LATEST run of each (mode, backend) group against a rolling
baseline — the median of the previous ``--baseline-n`` runs — and exits
nonzero when any watched metric regresses past its noise floor. It is
the CI gate that turns the bench trajectory from an artifact pile into
an alarm.

Noise handling (wall clocks on shared CI hosts jitter):

  * the baseline is a MEDIAN, so one historically slow run cannot drag
    the reference;
  * each metric has a configured relative (or absolute) noise floor;
  * when >= 3 baseline samples exist, the floor widens to 3x the
    baseline's relative median-absolute-deviation — a metric that is
    historically noisy cannot false-fail, and a quiet one stays tight.

A metric regresses when it is WORSE than the baseline by more than the
effective floor in its bad direction (throughput down, latency/overhead
up). Improvements never fail, and missing metrics are skipped (an old
history format must not break the gate). With fewer than ``--min-prior``
prior runs (default 3 — the point where the spread widening has data)
for every group the gate passes with a note — there is nothing robust
to compare against yet; measured run-to-run tok/s noise on loaded CPU
hosts exceeds 30%, so gating off two samples would be a coin flip.

Usage:
  python tools/perfwatch.py [--history BENCH_HISTORY.jsonl]
      [--baseline-n 5] [--min-prior 3]

Stdlib only (no jax import): the sentry must be runnable anywhere the
history file is, including laptops triaging a CI failure.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

# metric -> (direction, floor kind, floor). "higher" metrics regress
# when they DROP below baseline * (1 - floor); "lower" metrics regress
# when they RISE past baseline * (1 + floor) ("abs": baseline + floor —
# for metrics that live near zero, where relative floors degenerate).
METRICS: Dict[str, Tuple[str, str, float]] = {
    "decode_tokens_per_s": ("higher", "rel", 0.12),
    "prefill_tokens_per_s": ("higher", "rel", 0.12),
    "tokens_per_step_speedup": ("higher", "rel", 0.10),
    "acceptance_rate": ("higher", "rel", 0.10),
    "ttft_p50_s": ("lower", "rel", 0.25),
    "mfu": ("higher", "rel", 0.25),
    "tracing_overhead": ("lower", "abs", 0.02),
    # request journeys (ISSUE 20): the journeys-on vs journeys-off arm
    # delta. Lives near zero like tracing_overhead, so it gets the same
    # absolute floor — a rise past baseline + 2 points means the
    # journey layer's per-hop cost crept onto the decode hot path.
    "journey_overhead_pct": ("lower", "abs", 0.02),
    # step anatomy (ISSUE 12): the tracing_overhead series now measures
    # the anatomy-on observability arm. The gated trajectory is the
    # UNCLAMPED hidden-host seconds per hot step — a RISE past the
    # floor means the decode hot path got more host-bound. (The bubble
    # ratio rides the history for humans but is NOT gated: it clamps to
    # [0, 1] and CPU CI hosts sit near 1.0, so a ratio gate could never
    # fire on the backend CI runs.) Wall-clock-derived -> the wide
    # relative floor wall clocks get.
    "host_s_per_hot_step": ("lower", "rel", 0.25),
    # overlap mode (ISSUE 13): the A/B tokens/s ratio is a ratio of
    # interleaved best-of-N runs (steadier than raw wall clocks); the
    # on-arm tok/s and hidden-host seconds are wall-clock-derived and
    # get the wide relative floor. A ratio drop past the floor means
    # the pipeline stopped winning; a host_s rise means hidden host
    # work crept back onto the decode critical path.
    "overlap_tokens_per_s_ratio": ("higher", "rel", 0.10),
    "overlap_decode_tokens_per_s": ("higher", "rel", 0.12),
    "overlap_host_s_per_hot_step": ("lower", "rel", 0.25),
    # shared-prefix mode (prefix caching): the improvement ratio and
    # reuse fraction are ratios of interleaved best-of-N runs, so they
    # are steadier than raw wall clocks; cached TTFT is a wall clock
    # and gets the same wide floor as ttft_p50_s
    "ttft_p50_improvement": ("higher", "rel", 0.15),
    "prefill_reuse_ratio": ("higher", "rel", 0.10),
    "ttft_p50_cached_s": ("lower", "rel", 0.25),
    # mesh mode (ISSUE 15): sharded-arm decode tokens/s and the
    # sharded/single ratio. On CPU CI the ratio sits well below 1
    # (collectives over host threads); the gate guards the TREND — a
    # drop past the floor means sharded execution got slower relative
    # to its own history, not that sharding must beat one device.
    # Wall-clock-derived -> the wide relative floors wall clocks get.
    "mesh_decode_tokens_per_s": ("higher", "rel", 0.25),
    "mesh_tokens_per_s_ratio": ("higher", "rel", 0.20),
    # disaggregated serving A/B (ISSUE 16): unified/disagg ratios of
    # interleaved best-of-N arms (steadier than raw wall clocks) — a
    # TTFT ratio drop past the floor means the prefill pool stopped
    # winning admissions, a TPOT ratio drop means the decode pool's
    # interference-free steps stopped paying for the handoff; the raw
    # disagg TTFT is a wall clock and gets the wide relative floor.
    "disagg_ttft_p95_ratio": ("higher", "rel", 0.15),
    "disagg_tpot_p50_ratio": ("higher", "rel", 0.12),
    "disagg_ttft_p95_s": ("lower", "rel", 0.25),
    # constrained decoding (ISSUE 18): the A/B ratio is a median of
    # per-pair interleaved runs (machine drift cancels within a pair),
    # so it gets a tight floor — a drop means the grammar mask's
    # per-step host cost grew. The constrained-arm tok/s is a raw wall
    # clock and gets the wide relative floor.
    "constrained_tokens_per_s_ratio": ("higher", "rel", 0.08),
    "constrained_decode_tokens_per_s": ("higher", "rel", 0.25),
    # durable serving (ISSUE 19): the WAL-on/WAL-off ratio is a median
    # of per-pair interleaved runs (machine drift cancels within a
    # pair), so it gets a tight floor — a drop means the group commit's
    # per-step host cost grew. fsync p50 is a physical disk latency:
    # noisy across CI boxes, wide relative floor — a rise past it means
    # commits started waiting on storage (or someone snuck extra fsyncs
    # into the step).
    "durable_tokens_per_s_ratio": ("higher", "rel", 0.08),
    "durable_fsync_p50_s": ("lower", "rel", 0.50),
}


def load_history(path: str) -> List[dict]:
    """Parse the JSONL trajectory, skipping malformed lines (a crashed
    bench writer must not take the sentry down with it). Runs the bench
    itself marked failed (``ok: false``) are kept — a failed latest run
    must still be gated and reported, not silently replaced by the
    previous good run — but ``check()`` excludes them from the rolling
    baseline."""
    entries: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if isinstance(e, dict) and isinstance(e.get("metrics"), dict):
                    entries.append(e)
    except OSError:
        pass
    return entries


def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def effective_floor(kind: str, floor: float, baseline: List[float]) -> float:
    """Configured floor, widened by observed spread when there is
    enough history to estimate it (3x relative MAD)."""
    if len(baseline) < 3:
        return floor
    med = _median(baseline)
    mad = _median([abs(v - med) for v in baseline])
    if kind == "abs":
        return max(floor, 3.0 * mad)
    if abs(med) < 1e-12:
        return floor
    return max(floor, 3.0 * mad / abs(med))


def check_metric(
    name: str, current: float, baseline: List[float]
) -> Tuple[bool, str]:
    """(regressed, human line) for one metric against its baseline."""
    direction, kind, floor = METRICS[name]
    base = _median(baseline)
    floor_eff = effective_floor(kind, floor, baseline)
    if kind == "abs":
        bound = base + floor_eff if direction == "lower" else base - floor_eff
        regressed = current > bound if direction == "lower" else current < bound
        floor_str = f"abs {floor_eff:g}"
    else:
        bound = (
            base * (1.0 + floor_eff) if direction == "lower"
            else base * (1.0 - floor_eff)
        )
        regressed = current > bound if direction == "lower" else current < bound
        floor_str = f"{floor_eff:.0%}"
    verdict = "REGRESSED" if regressed else "ok"
    line = (
        f"{name}: {current:g} vs baseline(median of {len(baseline)}) "
        f"{base:g}, floor {floor_str} -> {verdict}"
    )
    return regressed, line


def check(
    history: List[dict],
    baseline_n: int = 5,
    min_prior: int = 3,
) -> Tuple[bool, List[str], bool]:
    """Gate the latest run of every (mode, backend) group.

    Returns (ok, report lines, gated) — ``gated`` False when no group
    had enough prior history to compare at all."""
    groups: Dict[Tuple[str, str], List[dict]] = {}
    for e in history:
        groups.setdefault((e.get("mode", "?"), e.get("backend", "?")), []).append(e)
    ok, gated = True, False
    lines: List[str] = []
    for (mode, backend), runs in sorted(groups.items()):
        latest = runs[-1]
        # baseline: prior runs that PASSED their own bench gate — a
        # regressed run that failed must not median the regression into
        # the reference (the latest run is still gated even if ok=false)
        eligible = [r for r in runs[:-1] if r.get("ok") is not False]
        if len(eligible) < min_prior:
            lines.append(
                f"[{mode}/{backend}] {len(eligible)} eligible prior run(s) — "
                f"need {min_prior} to gate; skipping"
            )
            continue
        prior = eligible[-baseline_n:]
        flag = " (bench gate FAILED)" if latest.get("ok") is False else ""
        header = (
            f"[{mode}/{backend}] latest {latest.get('ts', '?')} "
            f"@{latest.get('git_sha', '?')}{flag} vs {len(prior)} prior run(s)"
        )
        lines.append(header)
        for name in METRICS:
            cur = latest["metrics"].get(name)
            if not isinstance(cur, (int, float)) or isinstance(cur, bool):
                continue  # missing or non-numeric: skip, never crash the gate
            base_vals = [
                r["metrics"][name] for r in prior
                if isinstance(r["metrics"].get(name), (int, float))
            ]
            if not base_vals:
                continue
            gated = True
            regressed, line = check_metric(name, float(cur), base_vals)
            lines.append("    " + line)
            if regressed:
                ok = False
    return ok, lines, gated


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("--history", default="BENCH_HISTORY.jsonl",
                    help="genbench trajectory (JSONL, one run per line)")
    ap.add_argument("--baseline-n", type=int, default=5,
                    help="rolling-baseline window (median of the last N prior runs)")
    ap.add_argument("--min-prior", type=int, default=3,
                    help="prior runs required before a group gates")
    args = ap.parse_args()

    history = load_history(args.history)
    if not history:
        print(f"perfwatch: no readable history at {args.history}; nothing to gate")
        return 0
    ok, lines, gated = check(history, args.baseline_n, args.min_prior)
    for line in lines:
        print(line)
    if not gated:
        print("perfwatch: insufficient history to gate any metric; passing")
        return 0
    if not ok:
        print("perfwatch: FAIL — regression past the noise floor (see above)",
              file=sys.stderr)
        return 1
    print("perfwatch: OK — no metric regressed past its noise floor")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
