#!/usr/bin/env python
"""obsreport: the observability CLI for a running (or in-process)
serving stack.

Against a live server (serving/server.py):

  python tools/obsreport.py --url http://host:8000
      Summary: per-model request counters, latency / queue-time / TTFT /
      TPOT percentiles, recovery counters.

  python tools/obsreport.py --url ... --request 17
      One request's postmortem: the trace waterfall (accept -> queue ->
      admit -> first token -> progress -> finish) with per-hop deltas —
      the "debug a slow request" view.

  python tools/obsreport.py --url ... --timeline-out timeline.json
      Dump the engine flight recorder as chrome://tracing JSON (open in
      chrome://tracing or https://ui.perfetto.dev).

  python tools/obsreport.py --url ... cache
      Capacity view (GET /v2/debug/cache): per-request block residency
      table, fragmentation, free-block watermarks, pressure time, and
      admission-wait blame — the "why are requests queueing?" answer.

  python tools/obsreport.py --url ... slo
      SLO view (GET /v2/slo): per-objective fast/slow burn rates and
      breach state.

  python tools/obsreport.py --url ... predict
      Cost-model truth view (GET /v2/debug/predictions): per-program
      (predicted, measured) pairs with relative-error distributions,
      and the calibration-drift alarms with blame — the "is the
      simulator lying?" answer.

  python tools/obsreport.py --url ... predict --export ledger.json
      Dump the same ledger snapshot as a flexflow-ledger-export-v1
      document (per-model entries + counters, tagged with each model's
      device kind from its metadata) — the calibration artifact
      `flexflow_tpu.sim.SimCosts.from_ledger_export` consumes. The
      loader refuses cross-device loads, the apply_recalibration rule.

  python tools/obsreport.py --url ... overload
      Overload-control view (GET /v2/overload): adaptive-limiter state,
      degrade-ladder level + transition history, the per-reason /
      per-priority shed table, and the fleet autoscale signal — the
      "why is load being refused?" answer.

  python tools/obsreport.py --url ... disagg
      Disaggregated-serving view (GET /v2/fleet): per-pool replica
      states and load, in-flight KV handoffs with deadlines, the
      transfer outcome table (ok/corrupt/error/stalled), delivered
      bytes, replay fallbacks, and handoff latency percentiles — the
      "is the prefill->decode handoff healthy?" answer.

  python tools/obsreport.py --url ... anatomy [--capture K]
      [--anatomy-out anatomy.json]
      Step-anatomy view (GET /v2/debug/anatomy): per-kind phase
      breakdown (p50/mean per schedule/admit/prefix_plan/draft/sample/
      dispatch/block/readback/bookkeep span), the device-bubble ratio
      with host/device-bound classification, and the overlap-headroom
      projection (tokens/s if host phases were hidden behind device
      work) — the "is decode host-bound, and what would overlap buy?"
      answer. --capture K arms a K-step two-lane capture (scrape again
      once the engine has stepped); --anatomy-out dumps the captured
      chrome://tracing timeline.

CI self-check (no server needed; used by .github/workflows/tpu-ci.yml):

  python tools/obsreport.py --selfcheck
      Serves a tiny model in-process over real HTTP, generates, and
      asserts the whole observability chain: TTFT/TPOT histograms are
      non-empty, GET /metrics parses as Prometheus exposition text,
      traces carry queue-time/TTFT/TPOT, a forced quarantine AND a
      forced engine restart each capture a flight-recorder snapshot
      containing the failing step, and the error response embeds the
      postmortem. PR 7: additionally asserts the truth ledger holds
      (predicted, measured) pairs for prefill/decode/verify plus an
      executor program after real runs, and that a deliberately scaled
      calibration entry trips the calibration-drift alarm with the
      correct op-level blame string. PR 20: additionally drives request
      journeys end to end — a client traceparent joined at ingress and
      returned on the response, GET /v2/debug/journey/{id} stitching a
      complete parent-linked hop chain, tail-latency exemplars linking
      to stitchable ids, a forced replica failover whose journey
      crosses lanes gap-free with span count == attempted hops, and a
      warm restart whose pre-crash spans stitch from the on-disk spool
      alone. Exit 1 on any miss.

  python tools/obsreport.py --url ... journey [<id>] [--slow p99]
      [--timeline-out journey.json]
      Fleet-wide request journeys (GET /v2/debug/journey[/{id}]): one
      journey's cross-replica hop table with per-hop deltas and
      handoff/failover/restart annotations (--timeline-out dumps the
      chrome://tracing lanes view), or the stitchable-id listing
      (--slow p99 narrows to tail-latency exemplar journeys).

  python tools/obsreport.py --url ... slow
      Tail-latency exemplar table (GET /v2/debug/slow): each latency
      window's worst-decile samples with their journey ids.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request

sys.path.insert(0, ".")


def _get(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _get_json(url: str, timeout: float = 30.0):
    return json.loads(_get(url, timeout))


# --------------------------------------------------------------- summaries
def _pct_line(name: str, snap: dict) -> str:
    return (
        f"    {name:<14} n={snap['count']:<6} p50={snap['p50_s'] * 1e3:8.2f}ms "
        f"p95={snap['p95_s'] * 1e3:8.2f}ms p99={snap['p99_s'] * 1e3:8.2f}ms "
        f"max={snap['max_s'] * 1e3:8.2f}ms"
    )


def summarize(base: str) -> int:
    stats = _get_json(f"{base}/v2/stats")
    for section in ("models", "generation"):
        for name, snap in sorted(stats.get(section, {}).items()):
            print(f"model {name!r} ({section}):")
            counts = {
                k: snap[k]
                for k in ("admitted", "rejected", "expired", "completed",
                          "failed", "cancelled")
                if k in snap
            }
            print("    " + "  ".join(f"{k}={v}" for k, v in counts.items()))
            if isinstance(snap.get("latency"), dict):
                print(_pct_line("latency", snap["latency"]))
            for w in ("queue_time", "ttft", "tpot"):
                if isinstance(snap.get(w), dict):
                    print(_pct_line(w, snap[w]))
            rec = {
                k: snap[k]
                for k in ("recoveries", "quarantined", "watchdog_trips",
                          "step_retries", "engine_failures", "replayed_tokens")
                if snap.get(k) is not None
            }
            if rec:
                print("    recovery: " + "  ".join(f"{k}={v}" for k, v in rec.items()))
            if section == "generation":
                # serving layout (ISSUE 15): mesh geometry + the
                # search-chosen (or pinned) tensor-parallel degree
                try:
                    meta = _get_json(f"{base}/v2/models/{name}")
                except Exception:
                    meta = {}
                ss = meta.get("serving_strategy") or {}
                if ss:
                    line = (
                        f"    serving: mesh_devices={ss.get('mesh_devices')}"
                        f"  tp_degree={ss.get('tp_degree')}"
                    )
                    search = ss.get("search") or {}
                    if search:
                        line += (
                            f"  layout={'pinned' if search.get('pinned') else 'searched'}"
                            f"  candidates="
                            f"{[c['tp_degree'] for c in search.get('candidates', [])]}"
                        )
                    chip = (meta.get("compute") or {}).get("chip")
                    if chip:
                        line += f"  chip={chip}"
                    print(line)
    return 0


def show_request(base: str, request_id: int) -> int:
    payload = _get_json(f"{base}/v2/debug/traces?id={request_id}")
    traces = payload.get("traces", [])
    if not traces:
        print(f"no trace retained for request {request_id} "
              f"(ring evicted, or never finished)", file=sys.stderr)
        return 1
    for tr in traces:
        print(f"request {tr['request_id']} model={tr['model']} "
              f"transport={tr.get('transport')} outcome={tr['outcome']}")
        for k in ("queue_time_s", "ttft_s", "tpot_s", "total_s"):
            v = tr.get(k)
            print(f"    {k:<13} {v * 1e3:9.3f}ms" if v is not None else f"    {k:<13} -")
        print(f"    prompt_len={tr['prompt_len']} n_generated={tr['n_generated']} "
              f"preemptions={tr['preemptions']} replays={tr['replays']}")
        events = tr.get("events", [])
        t0 = events[0]["t"] if events else 0.0
        prev = t0
        print("    waterfall:")
        for ev in events:
            extra = {k: v for k, v in ev.items() if k not in ("t", "event")}
            print(f"      +{(ev['t'] - t0) * 1e3:9.3f}ms (Δ{(ev['t'] - prev) * 1e3:8.3f}ms) "
                  f"{ev['event']:<12} {extra if extra else ''}")
            prev = ev["t"]
        if tr.get("error"):
            print(f"    error: {tr['error']}")
    return 0


def show_cache(base: str) -> int:
    """Block-residency table + capacity counters per model."""
    payload = _get_json(f"{base}/v2/debug/cache")
    for name, rep in sorted(payload.get("models", {}).items()):
        blocks = rep["blocks"]
        print(f"model {name!r}: blocks used={blocks['used']}/{blocks['total']} "
              f"free={blocks['free']} (low_water={blocks['low_water']} "
              f"high_water={blocks['high_water']})")
        print(f"    fragmentation={rep['fragmentation_slots']} slot(s)  "
              f"occupancy={rep['occupancy']:.2f}  queue_depth={rep['queue_depth']}")
        p = rep["pressure"]
        print(f"    pressure: under={p['under_pressure']} "
              f"time_at_pressure={p['time_at_pressure_s'] * 1e3:.1f}ms "
              f"(threshold {p['threshold']:.0%} free)")
        c = rep["counters"]
        print(f"    reclaims: preempt={c['preempt_reclaimed_blocks']} blocks "
              f"({c['preempt_reclaims']}x)  trim={c['trimmed_blocks']} blocks "
              f"({c['trims']}x)")
        print(f"    admission waits: {c['admission_waits']} "
              f"({c['admission_wait_s'] * 1e3:.1f}ms total)"
              + (f"  last: {c['last_wait_blame']}" if c.get("last_wait_blame") else ""))
        pc = rep.get("prefix_cache") or {}
        if pc.get("enabled"):
            print(f"    prefix cache: hits={pc['hits']}/{pc['lookups']} "
                  f"(ratio {pc['hit_ratio']:.2f})  "
                  f"reused={pc['tokens_reused_total']} tokens / "
                  f"{pc['blocks_reused_total']} blocks  "
                  f"cow={pc['cow_copies_total']}")
            print(f"    tiers: device={pc['resident_blocks']} block(s) "
                  f"({pc['shared_blocks']} shared)  "
                  f"host={pc['offloaded_blocks']} block(s) "
                  f"({pc['host_bytes']}B of {pc['host_budget_bytes']}B)  "
                  f"swaps in/out={pc['swaps_in_total']}/{pc['swaps_out_total']}  "
                  f"fallbacks={pc['recompute_fallbacks']}")
        rows = rep.get("residency", [])
        if rows:
            print("    residency:")
            print("      req       slot  blocks  shared  alloc_slots  live_tokens  frag")
            for r in rows:
                print(f"      {r['request_id']:<9} {r['slot']:<5} {r['blocks']:<7} "
                      f"{r.get('shared_blocks', 0):<7} "
                      f"{r['allocated_slots']:<12} {r['live_tokens']:<12} "
                      f"{r['frag_slots']}")
        else:
            print("    residency: (no running requests)")
    return 0


def show_slo(base: str) -> int:
    """Burn-rate summary per objective."""
    payload = _get_json(f"{base}/v2/slo")
    for name, rep in sorted(payload.get("models", {}).items()):
        state = "HEALTHY" if rep["healthy"] else f"BREACHING: {rep['breaching']}"
        print(f"model {name!r}: {state} ({rep['observed']} requests observed)")
        for obj in rep["objectives"]:
            thr = f" <= {obj['threshold_s']}s" if obj["threshold_s"] is not None else ""
            fast, slow = obj["fast"], obj["slow"]
            flag = "  << BREACHING" if obj["breaching"] else ""
            print(f"    {obj['name']:<16} {obj['metric']}{thr} target={obj['target']}")
            print(f"        fast {fast['window_s']:.0f}s: burn={fast['burn_rate']:.2f} "
                  f"({fast['bad']}/{fast['events']} bad)   "
                  f"slow {slow['window_s']:.0f}s: burn={slow['burn_rate']:.2f} "
                  f"({slow['bad']}/{slow['events']} bad){flag}")
    return 0


def _predict_rows(rep: dict, indent: str = "    ") -> None:
    entries = [e for e in rep.get("entries", []) if e["pairs"] > 0]
    if not entries:
        print(indent + "(no joined pairs)")
    else:
        print(indent + "key                        pairs  predicted   meas_p50    err_p50  ewma     alarm")
        for e in entries:
            pred = e["predicted_s"]
            p50 = e["measured_p50_s"]
            print(
                f"{indent}{e['key'][:26]:<26} {e['pairs']:<6} "
                f"{pred * 1e3:9.3f}ms {p50 * 1e3:9.3f}ms "
                f"{(e['rel_err_p50'] or 0):+8.0%} {(e['rel_err_ewma'] or 0):+8.0%} "
                f"{'<<' if e['alarming'] else ''}"
            )
    unpred = rep.get("unpredicted", {})
    if unpred:
        total = rep.get("counters", {}).get("unpredicted_total", sum(unpred.values()))
        print(f"{indent}unpredicted measurements: {total} across {len(unpred)} key(s)")
    for a in rep.get("alarms", []):
        print(f"{indent}DRIFT: {a['blame']}")


def show_predictions(base: str) -> int:
    """Predicted-vs-measured table + drift alarms, per model and for
    the process-wide ledger (cost model / calibration / executor)."""
    payload = _get_json(f"{base}/v2/debug/predictions")
    for name, rep in sorted(payload.get("models", {}).items()):
        c = rep["counters"]
        print(f"model {name!r}: {c['pairs_total']} pairs, "
              f"{c['drift_alarms_total']} drift alarm(s)")
        _predict_rows(rep)
    g = payload.get("global")
    if g is not None:
        c = g["counters"]
        print(f"global ledger (cost model / calibration / executor): "
              f"{c['pairs_total']} pairs, {c['drift_alarms_total']} drift alarm(s)")
        _predict_rows(g)
    return 0


LEDGER_EXPORT_SCHEMA = "flexflow-ledger-export-v1"


def export_predictions(base: str, out: str) -> int:
    """Write the ledger snapshot as a ``flexflow-ledger-export-v1``
    document: per-model entries + counters, each model tagged with the
    device kind its engine reported (metadata ``compute.chip``). This
    is the calibration artifact the fleet digital twin loads
    (``SimCosts.from_ledger_export``); the device tag is what lets the
    loader refuse cross-device loads."""
    payload = _get_json(f"{base}/v2/debug/predictions")
    models = {}
    for name, rep in sorted(payload.get("models", {}).items()):
        try:
            meta = _get_json(f"{base}/v2/models/{name}")
            device = meta.get("compute", {}).get("chip") or "unknown"
        except Exception:
            device = "unknown"
        models[name] = {
            "device_kind": device,
            "entries": rep.get("entries", []),
            "counters": rep.get("counters", {}),
        }
    doc = {
        "schema": LEDGER_EXPORT_SCHEMA,
        "exported_from": base,
        "models": models,
        "global": payload.get("global"),
    }
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    pairs = sum(
        m["counters"].get("pairs_total", 0) for m in models.values()
    )
    print(f"exported {len(models)} model ledger(s) ({pairs} pairs) -> {out}")
    return 0


def show_anatomy(base: str, capture=None, out: str = "") -> int:
    """Phase breakdown + bubble/headroom per generation unit."""
    url = f"{base}/v2/debug/anatomy"
    if capture:
        url += f"?capture={int(capture)}"
    payload = _get_json(url)
    for name, unit in sorted(payload.get("models", {}).items()):
        rep = unit["report"]
        if not rep.get("enabled", False):
            print(f"model {name!r}: anatomy disabled (observability off)")
            continue
        print(f"model {name!r}: {rep['steps_observed']} step(s) observed, "
              f"classification={rep['classification']}")
        if unit.get("armed") is not None:
            print(f"    armed a {unit['armed']}-step capture "
                  f"(scrape again after the engine steps)")
        bubble = rep.get("device_bubble_ratio")
        if bubble is not None:
            print(f"    device_bubble_ratio={bubble:.1%} "
                  f"(device idle while the host works, rolling window)")
        for kind, phases in sorted(rep.get("phases", {}).items()):
            print(f"    {kind}:")
            print("        phase         count     mean        p50")
            for phase, p in sorted(phases.items()):
                print(f"        {phase:<12} {p['count']:<7} "
                      f"{p['mean_s'] * 1e3:8.3f}ms {p['p50_s'] * 1e3:8.3f}ms")
        hr = rep.get("headroom", {})
        if hr.get("measured_tokens_per_s") is not None:
            print(f"    overlap headroom ({hr['steps']} hot step(s)): "
                  f"{hr['measured_tokens_per_s']:.1f} -> "
                  f"{hr['projected_tokens_per_s']:.1f} tok/s "
                  f"({hr['projected_speedup']:.2f}x) if host phases were "
                  f"hidden behind device work")
        cap = rep.get("capture", {})
        print(f"    capture: {cap.get('captured', 0)} step(s) retained, "
              f"{cap.get('remaining', 0)} armed")
    if out:
        with open(out, "w") as f:
            json.dump(payload, f, indent=2)
        print(f"wrote anatomy report + two-lane timeline(s) to {out} "
              f"— open a 'trace' block in chrome://tracing")
    return 0


def show_overload(base: str) -> int:
    """Overload-control view (GET /v2/overload): limiter state, ladder
    level + transition history, and the per-reason / per-priority shed
    table — the "why is load being refused?" answer."""
    payload = _get_json(f"{base}/v2/overload")
    for name, rep in sorted(payload.get("models", {}).items()):
        lim = rep["limiter"]
        lad = rep["ladder"]
        print(f"model {name!r}: degrade_level={lad['level']} "
              f"(max seen {lad['max_level_seen']}, "
              f"{lad['transitions_total']} transition(s))  "
              f"pressure={rep['pressure']:.2f}")
        print(f"    limiter: limit={lim['limit']:.0f} "
              f"[{lim['min_limit']:.0f}..{lim['max_limit']:.0f}] "
              f"inflight={lim['inflight']} "
              f"util={lim['utilization']:.2f} last={lim['last_decision']}")
        print(f"    counters: throttled={lim['throttled_total']} "
              f"cuts={lim['cuts_total']} raises={lim['raises_total']}  "
              f"retry_after={rep['retry_after_s']:.1f}s")
        rej = rep.get("rejections", {})
        by_r, by_p = rej.get("by_reason", {}), rej.get("by_priority", {})
        if by_r or by_p:
            print("    refused: "
                  + "  ".join(f"{k}={v}" for k, v in sorted(by_r.items()))
                  + "   by class: "
                  + "  ".join(f"{k}={v}" for k, v in sorted(by_p.items())))
        else:
            print("    refused: (none)")
        hist = lad.get("history", [])
        if hist:
            print("    ladder history:")
            for h in hist[-8:]:
                print(f"      t={h['t']:.2f}s  {h['from']} -> {h['to']} "
                      f"(pressure {h['pressure']:.2f})")
    auto = _get_json(f"{base}/v2/fleet/autoscale").get("models", {})
    for name, rep in sorted(auto.items()):
        print(f"fleet {name!r}: autoscale signal={rep['signal']:+d} "
              f"want_replicas={rep['want_replicas']} "
              f"(current {rep['current_replicas']}, "
              f"sustained {rep['sustained_s']:.1f}s, "
              f"fleet_sheds={rep.get('fleet_sheds', 0)})")
    return 0


def show_disagg(base: str) -> int:
    """Disaggregated-serving view (GET /v2/fleet): pool states + the
    KV handoff protocol counters — the "is the prefill->decode handoff
    healthy?" answer."""
    payload = _get_json(f"{base}/v2/fleet")
    shown = 0
    for name, rep in sorted(payload.get("models", {}).items()):
        if not rep.get("disaggregated"):
            continue
        shown += 1
        print(f"fleet {name!r} (disaggregated):")
        for pool in ("prefill", "decode"):
            prep = rep["pools"][pool]
            states = "  ".join(
                f"{r['id']}={r['state']}(q={r['queue_depth']} "
                f"run={r['running']})"
                for r in prep.get("replicas", [])
            )
            print(f"    {pool:<8} pending={prep.get('pending', 0)}  {states}")
        ho = rep.get("handoffs", {})
        t = ho.get("transfers", {})
        print(f"    handoffs: ok={t.get('ok', 0)} corrupt={t.get('corrupt', 0)} "
              f"error={t.get('error', 0)} stalled={t.get('stalled', 0)}  "
              f"retries={ho.get('retries_total', 0)}  "
              f"replay_fallbacks={ho.get('replay_fallbacks_total', 0)}  "
              f"bytes={ho.get('bytes_total', 0)}")
        lat = ho.get("latency") or {}
        if lat.get("count"):
            mean = lat["sum"] / lat["count"]
            print(f"    handoff latency: n={lat['count']} "
                  f"mean={mean * 1e3:.2f}ms total={lat['sum'] * 1e3:.1f}ms")
        inflight = ho.get("in_flight", [])
        if inflight:
            print("    in flight:")
            for h in inflight:
                dl = h.get("deadline_in_s")
                print(f"      handoff {h['id']} req={h['request_id']} "
                      f"from={h['source']} attempts={h['attempts']} "
                      f"age={h['age_s']:.2f}s "
                      f"deadline_in={'-' if dl is None else f'{dl:.2f}s'} "
                      f"bytes={h['bytes']}")
        else:
            print("    in flight: (none)")
    if not shown:
        print("no disaggregated fleets registered")
    return 0


def show_constrained(base: str) -> int:
    """Constrained-decoding view (GET /v2/stats + model metadata): the
    grammar-cache hit economics, how many masked rows the engine
    stepped, and the dead-end quarantine count — the "are response_format
    requests healthy and cheap?" answer."""
    stats = _get_json(f"{base}/v2/stats")
    shown = 0
    for name, snap in sorted(stats.get("generation", {}).items()):
        hits = snap.get("constrained_grammar_cache_hits_total")
        if hits is None:
            continue
        shown += 1
        misses = snap.get("constrained_grammar_cache_misses_total", 0)
        total = hits + misses
        ratio = (hits / total) if total else 0.0
        print(f"model {name!r} (constrained):")
        print(f"    grammar cache: hits={hits} misses={misses} "
              f"hit_ratio={ratio:.2f} "
              f"compile_s={snap.get('constrained_grammar_compile_seconds_total', 0.0):.3f}")
        print(f"    masked_steps={snap.get('constrained_masked_steps_total', 0)}  "
              f"dead_end_failures={snap.get('constrained_dead_end_failures_total', 0)}")
        try:
            meta = _get_json(f"{base}/v2/models/{name}")
        except Exception:
            meta = {}
        con = meta.get("constrained") or {}
        if con:
            print(f"    cache entries={con.get('grammar_cache_entries')}  "
                  f"vocabulary_tokens={con.get('vocabulary_tokens')}  "
                  f"formats={','.join(con.get('formats', []))}")
    if not shown:
        print("no generation models expose constrained counters")
    return 0


def _print_durable_report(rep: dict, indent: str = "    ") -> None:
    wm = rep.get("watermark", {})
    wal = rep.get("wal", {})
    counts = rep.get("counters", {})
    ri = rep.get("resume_index", {})
    print(f"{indent}wal: dir={rep.get('wal_dir')!r} fsync={rep.get('fsync')} "
          f"segments={rep.get('segments', 0)}")
    print(f"{indent}watermark: segment={wm.get('segment')} "
          f"bytes={wm.get('segment_bytes')} appends={wm.get('appends')} "
          f"unflushed={wm.get('unflushed')} commit_lag={wm.get('commit_lag')} "
          f"open_streams={wm.get('open_streams')}")
    print(f"{indent}writes: appends={wal.get('appends', 0)} "
          f"bytes={wal.get('bytes', 0)} fsyncs={wal.get('fsyncs', 0)} "
          f"fsync_failures={wal.get('fsync_failures', 0)} "
          f"fsync_p50={wal.get('fsync_p50_s', 0.0) * 1e3:.2f}ms "
          f"reaped_segments={wal.get('reaped_segments', 0)}")
    print(f"{indent}replay: streams={counts.get('replayed_streams', 0)} "
          f"tokens={counts.get('replayed_tokens', 0)} "
          f"torn_records={counts.get('torn_records', 0)} "
          f"rolling_restarts={counts.get('rolling_restarts', 0)}")
    print(f"{indent}degraded_streams={rep.get('degraded_streams', 0)}  "
          f"resume_index: live={ri.get('live', 0)} "
          f"terminal={ri.get('terminal', 0)}")


def show_durable(base: str) -> int:
    """Durable-serving view (GET /v2/durable): WAL watermark + write
    counters, warm-restart replay totals, degraded streams, and the
    resume index — the "would a crash right now lose anything, and did
    the last restart replay cleanly?" answer."""
    payload = _get_json(f"{base}/v2/durable")
    shown = 0
    for name, rep in sorted(payload.get("models", {}).items()):
        shown += 1
        if "replicas" in rep:  # fleet: per-replica durability
            print(f"model {name!r} (durable fleet, root={rep.get('root')!r}):")
            for rid, rrep in sorted(rep.get("replicas", {}).items()):
                print(f"  replica {rid}:")
                _print_durable_report(rrep, indent="      ")
        else:
            print(f"model {name!r} (durable):")
            _print_durable_report(rep)
    if not shown:
        print("no models have durability attached")
    return 0


# hop names that mark a journey crossing a process/replica boundary —
# the annotations the hop table calls out loudly
_JOURNEY_ANNOTATIONS = {
    "kv_handoff_pack": "<< HANDOFF (KV packed for the decode pool)",
    "kv_handoff": "<< HANDOFF (KV delivered cross-pool)",
    "kv_handoff_replay": "<< HANDOFF FALLBACK (journal replay)",
    "failover": "<< FAILOVER (replica died mid-stream)",
    "warm_restart": "<< WARM RESTART (WAL replay after process death)",
    "sse_resume": "<< RESUME (client re-attached)",
    "replay": "<< REPLAY (engine restart)",
}


def show_slow(base: str, model=None) -> int:
    """Tail-latency exemplar table (GET /v2/debug/slow): each latency
    window's worst-decile samples with the journey ids they retained —
    a bad percentile links straight to a stitchable journey."""
    url = f"{base}/v2/debug/slow"
    if model:
        url += f"?model={model}"
    payload = _get_json(url)
    shown = 0
    for label, windows in sorted(payload.get("models", {}).items()):
        print(f"model {label!r}:")
        for window, rows in sorted(windows.items()):
            print(f"    {window} worst-decile exemplars:")
            for r in rows:
                shown += 1
                print(f"        {r['seconds'] * 1e3:9.3f}ms  "
                      f"journey {r['journey_id']}")
    if not shown:
        print("no slow exemplars retained (journeys off, or no traffic)")
    return 0


def _exemplar_windows(base: str, journey_id: str) -> list:
    """Which (model, window) latency exemplars retained this journey."""
    try:
        payload = _get_json(f"{base}/v2/debug/slow")
    except Exception:
        return []
    return sorted(
        f"{label}:{window}"
        for label, windows in payload.get("models", {}).items()
        for window, rows in windows.items()
        if any(r.get("journey_id") == journey_id for r in rows)
    )


def show_journey(base: str, journey_id=None, slow=None,
                 timeline_out: str = "") -> int:
    """One journey's cross-replica hop table (or, without an id, the
    listing of stitchable journeys — ``--slow p99`` narrows to the
    tail-latency exemplars)."""
    if not journey_id:
        url = f"{base}/v2/debug/journey"
        if slow:
            url += f"?slow={slow}"
        payload = _get_json(url)
        ids = payload.get("journeys", [])
        if not ids:
            print("no journeys retained" + (" as slow exemplars" if slow else ""))
            return 1
        label = "slow-exemplar journeys" if slow else "journeys (newest first)"
        print(f"{len(ids)} {label}:")
        for jid in ids:
            print(f"    {jid}")
        print(f"inspect one: obsreport.py --url {base} journey <id>")
        return 0
    try:
        payload = _get_json(f"{base}/v2/debug/journey/{journey_id}")
    except urllib.error.HTTPError as e:
        if e.code == 404:
            print(f"unknown journey {journey_id} (spool evicted, or never "
                  f"minted)", file=sys.stderr)
            return 1
        raise
    j = payload["journey"]
    spans = j["spans"]
    verdict = "complete" if j["complete"] else (
        f"INCOMPLETE ({j['n_roots']} root(s); orphaned spans present)"
    )
    print(f"journey {j['journey_id']}: {j['n_spans']} hop(s) across "
          f"lanes {', '.join(j['lanes'])} — {verdict}")
    for w in _exemplar_windows(base, journey_id):
        print(f"    # EXEMPLAR: retained as a worst-decile {w} sample")
    t0 = spans[0]["t0"] if spans else 0.0
    prev = t0
    print("    hop table (causal order):")
    for s in spans:
        extra = {k: v for k, v in (s.get("attrs") or {}).items()}
        note = _JOURNEY_ANNOTATIONS.get(s["name"], "")
        print(f"      +{(s['t0'] - t0) * 1e3:9.3f}ms "
              f"(Δ{(s['t0'] - prev) * 1e3:8.3f}ms) "
              f"[{s['lane']:<10}] {s['name']:<16} "
              f"{extra if extra else ''}{'  ' + note if note else ''}")
        prev = s["t0"]
    if timeline_out:
        with open(timeline_out, "w") as f:
            json.dump(payload["chrome_trace"], f)
        print(f"wrote {len(payload['chrome_trace'].get('traceEvents', []))} "
              f"trace events to {timeline_out} — open in chrome://tracing")
    return 0 if j["complete"] else 1


def dump_timeline(base: str, out: str) -> int:
    payload = _get_json(f"{base}/v2/debug/timeline")
    with open(out, "w") as f:
        json.dump(payload, f)
    print(f"wrote {len(payload.get('traceEvents', []))} trace events "
          f"({len(payload.get('incidents', []))} incidents) to {out} "
          f"— open in chrome://tracing")
    return 0


# --------------------------------------------------------------- selfcheck
def selfcheck() -> int:
    """End-to-end observability proof on a tiny in-process model."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np

    from flexflow_tpu.generation import (
        GenerationEngine,
        SamplingParams,
        init_decoder_params,
    )
    from flexflow_tpu.models.transformer import TransformerConfig
    from flexflow_tpu.obs import validate_exposition
    from flexflow_tpu.runtime.faults import FaultPlan
    from flexflow_tpu.serving import InferenceServer
    from flexflow_tpu.serving.generation import GenerationModel

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)
            print(f"FAIL: {msg}", file=sys.stderr)

    cfg = TransformerConfig(
        num_layers=2, hidden_size=32, num_heads=4, ff_size=64,
        seq_length=64, vocab_size=50, causal=True,
    )
    params = init_decoder_params(jax.random.key(0), cfg)
    eng = GenerationEngine(params, cfg, max_batch_slots=3, block_size=8)
    eng.generate([[1] * 8], SamplingParams(max_new_tokens=2))  # warm the jits
    model = GenerationModel(eng, name="lm")
    srv = InferenceServer(port=0)
    srv.register_generation(model)
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"

    def post(path, payload, headers=None, return_headers=False):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                out = r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as e:
            out = e.code, json.loads(e.read()), dict(e.headers)
        return out if return_headers else out[:2]

    import urllib.error

    try:
        # ------------------------------------------ healthy generations
        for prompt in ([1, 2, 3], [4, 5, 6, 7], [9, 8, 7]):
            code, resp = post("/v2/models/lm/generate",
                              {"prompt": prompt, "max_new_tokens": 8})
            check(code == 200 and len(resp["tokens"]) == 8,
                  f"generate failed: {code} {resp}")

        # ---------------------------------------------- /metrics parses
        metrics = _get(f"{base}/metrics")
        bad = validate_exposition(metrics)
        check(not bad, f"/metrics has malformed lines: {bad[:3]}")

        def hist_count(name):
            for line in metrics.splitlines():
                if line.startswith(f"flexflow_serving_{name}_seconds_count"):
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        check(hist_count("ttft") >= 3, "TTFT histogram is empty")
        check(hist_count("tpot") >= 3, "TPOT histogram is empty")
        check(hist_count("queue_time") >= 3, "queue-time histogram is empty")

        # ------------------------------------------------ trace complete
        traces = _get_json(f"{base}/v2/debug/traces")["traces"]
        check(len(traces) >= 3, f"expected >=3 traces, got {len(traces)}")
        tr = traces[0]
        for k in ("queue_time_s", "ttft_s", "tpot_s"):
            check(tr.get(k) is not None, f"trace missing {k}: {tr}")
        names = [e["event"] for e in tr["events"]]
        for needed in ("accept", "transport", "admit", "first_token", "finish"):
            check(needed in names, f"trace missing {needed} event: {names}")

        # --------------------------------------------- timeline is sane
        tl = _get_json(f"{base}/v2/debug/timeline")
        kinds = {e["name"] for e in tl["traceEvents"]}
        check("decode" in kinds and "prefill" in kinds,
              f"timeline missing step kinds: {sorted(kinds)[:10]}")

        # ------------------------------------- forced quarantine (NaN)
        # one request alone in the batch; poison its decode bias -> the
        # blame vector quarantines it and the incident snapshot must
        # hold the failing step
        plan = FaultPlan(seed=0)
        plan.on("generation.decode_step", mode="nan", nth=(0,),
                select=lambda v: np.ones_like(np.asarray(v[1]), bool))
        with plan.active():
            code, resp = post("/v2/models/lm/generate",
                              {"prompt": [3, 1, 4, 1, 5], "max_new_tokens": 8})
        check(code == 500, f"poisoned request returned {code}")
        check(resp.get("type") == "PoisonedRequestError",
              f"expected PoisonedRequestError, got {resp.get('type')}: {resp.get('error')}")
        check(resp.get("trace", {}).get("outcome") == "PoisonedRequestError",
              "error response did not embed the request trace")
        flight = resp.get("flight") or {}
        check(flight.get("kind") == "quarantine" and flight.get("records"),
              "quarantine did not capture a flight-recorder snapshot")
        check(any(r.get("kind") == "decode" for r in flight.get("records", [])),
              "quarantine snapshot does not contain the failing decode step")

        # ------------------------------------ forced restart (crash x2)
        plan = FaultPlan(seed=0)
        plan.on("generation.decode_step", mode="error",
                error=RuntimeError("injected device crash"), nth=(0, 1))
        with plan.active():
            code, resp = post("/v2/models/lm/generate",
                              {"prompt": [2, 7, 1, 8], "max_new_tokens": 8})
        check(code == 200 and len(resp.get("tokens", [])) == 8,
              f"restart did not replay the stream: {code} {resp}")
        incidents = model.flight.incident_snapshots()
        restart = [i for i in incidents if i["kind"] == "restart"]
        check(restart, f"no restart incident recorded: {[i['kind'] for i in incidents]}")
        check(any(r.get("kind") == "step_failed" for r in restart[-1]["records"]),
              "restart snapshot does not contain the failing step")
        check(model.recovery_stats.recoveries >= 1, "recovery counter not bumped")

        # fault-site counters surfaced the chaos on the LIVE plan only;
        # after plan removal /metrics must still parse
        metrics = _get(f"{base}/metrics")
        check(not validate_exposition(metrics), "/metrics broke after chaos")

        # -------------------------- capacity: cache telemetry is honest
        cache = _get_json(f"{base}/v2/debug/cache")["models"]["lm"]
        blocks = cache["blocks"]
        # real conservation, not the tautological used+free==total (used
        # is computed as total-free): every block ever handed out is
        # accounted as freed, reclaimed by reset, or still resident
        check(blocks["allocated_total"] == blocks["freed_total"]
              + blocks["reset_reclaimed_total"] + blocks["used"],
              f"cache conservation broken: {blocks}")
        # tier conservation under prefix caching: per-request PRIVATE
        # blocks + the radix index's resident blocks == used (shared
        # blocks count once however many streams reference them), and
        # host-tier bytes match its block count
        pc = cache["prefix_cache"]
        private = sum(r["blocks"] - r["shared_blocks"]
                      for r in cache["residency"])
        check(private + pc["resident_blocks"] == blocks["used"],
              f"residency+prefix does not sum to used: "
              f"{cache['residency']} {pc} vs {blocks}")
        check(pc["offloaded_blocks"] * cache["config"]["bytes_per_block"]
              == pc["host_bytes"],
              f"host-tier bytes disagree with offloaded blocks: {pc}")
        check(blocks["low_water"] < blocks["total"],
              "low-water mark never moved despite served requests")
        for series in ("cache_occupancy", "mfu", "goodput_ratio",
                       "slo_breaching_total", "prefix_cache_hit_ratio",
                       "prefix_cache_host_bytes"):
            check(f"flexflow_serving_{series}{{" in metrics,
                  f"/metrics missing {series}")

        # ---------------- prefix caching: reuse is real and byte-exact
        # the same templated prompt twice: the second admission must hit
        # the radix index and reuse its cached full block, with
        # identical tokens
        tpl = [5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16]  # > 1 block of 8
        code, first = post("/v2/models/lm/generate",
                           {"prompt": tpl, "max_new_tokens": 6})
        check(code == 200, f"templated generate failed: {code}")
        reused_before = eng.prefix_cache.tokens_reused_total
        code, second = post("/v2/models/lm/generate",
                            {"prompt": tpl, "max_new_tokens": 6})
        check(code == 200 and second["tokens"] == first["tokens"],
              "prefix-cached repeat stream differs from first run")
        check(eng.prefix_cache.tokens_reused_total > reused_before,
              "repeat admission did not reuse cached prefix blocks")

        # -------------------- serving-strategy metadata (ISSUE 15)
        meta = _get_json(f"{base}/v2/models/lm")
        ss = meta.get("serving_strategy") or {}
        check(ss.get("tp_degree") == 1 and ss.get("mesh_devices") == 1,
              f"single-device serving_strategy block wrong: {ss}")

        # -------------------- program registry: non-empty, blame works
        progs = _get_json(f"{base}/v2/debug/programs")
        entries = progs["models"]["lm"]["programs"]
        names = {p["name"] for p in entries}
        check("decode" in names and any(n.startswith("prefill[") for n in names),
              f"program registry missing engine programs: {sorted(names)}")
        check(all(p["compile_s"] is not None for p in entries
                  if p["name"] == "decode"),
              "decode program has no compile wall time")
        # force a retrace (batch widened by one) and require a correct,
        # human-readable blame string on the registry
        import jax.numpy as jnp
        b = eng.max_batch_slots + 1
        eng._decode_jit(
            eng.params, jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
            eng.cache.k, eng.cache.v,
            jnp.zeros((b, eng.max_blocks_per_seq), jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.float32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.float32),
            jnp.zeros((b,), jnp.uint32), jnp.zeros((b,), jnp.int32),
            jnp.zeros((b, eng.cfg.vocab_size), jnp.float32),
        )
        retraces = _get_json(f"{base}/v2/debug/programs")["models"]["lm"]["retraces"]
        check(retraces, "forced retrace produced no registry record")
        blame = retraces[-1]["blame"] if retraces else ""
        check("decode retraced" in blame
              and f"int32[{eng.max_batch_slots}] -> int32[{b}]" in blame,
              f"retrace blame string wrong: {blame!r}")

        # -------------------- step anatomy: report + forced capture
        # (ISSUE 12) the profiler must have folded the healthy steps
        # above into a non-empty report with a finite bubble ratio, and
        # an armed capture must retain real two-lane spans
        import math as _math

        anat = _get_json(f"{base}/v2/debug/anatomy?capture=6")
        check(anat["models"]["lm"].get("armed") == 6,
              f"anatomy capture did not arm: {anat['models']['lm'].get('armed')}")
        code, resp = post("/v2/models/lm/generate",
                          {"prompt": [2, 4, 6, 8], "max_new_tokens": 6})
        check(code == 200, f"anatomy-capture generate failed: {code}")
        anat = _get_json(f"{base}/v2/debug/anatomy")["models"]["lm"]
        rep = anat["report"]
        check(rep["steps_observed"] >= 3,
              f"anatomy observed too few steps: {rep['steps_observed']}")
        bubble = rep.get("device_bubble_ratio")
        check(bubble is not None and _math.isfinite(bubble) and 0.0 <= bubble <= 1.0,
              f"device_bubble_ratio not finite in [0,1]: {bubble}")
        hr = rep.get("headroom", {})
        check(hr.get("projected_tokens_per_s") is not None
              and hr.get("projected_speedup") is not None
              and _math.isfinite(hr["projected_speedup"]),
              f"overlap-headroom projection missing: {hr}")
        decode_phases = rep.get("phases", {}).get("decode", {})
        for phase in ("dispatch", "execute", "readback", "bookkeep"):
            check(decode_phases.get(phase, {}).get("count", 0) >= 1,
                  f"decode anatomy missing the {phase} phase: "
                  f"{sorted(decode_phases)}")
        check(rep["capture"]["captured"] >= 1,
              f"forced capture retained no steps: {rep['capture']}")
        lanes = {e.get("tid") for e in anat["trace"]["traceEvents"]
                 if e.get("ph") == "X"}
        check({1, 2} <= lanes,
              f"capture timeline is not two-lane (host+device): {lanes}")
        check("flexflow_serving_step_phase_seconds_bucket" in _get(f"{base}/metrics"),
              "/metrics missing the step_phase_seconds histogram")

        # ------------------------------- SLO + readiness rationale sane
        slo = _get_json(f"{base}/v2/slo")["models"]["lm"]
        check(slo["observed"] >= 3 and slo["objectives"],
              f"SLO monitor saw no requests: {slo['observed']}")
        ready = _get_json(f"{base}/v2/health/ready")
        rationale = ready.get("models", {}).get("lm", {})
        check(rationale.get("breaker") == "closed"
              and "slo_breaching" in rationale,
              f"readiness rationale incomplete: {rationale}")

        # --------------------- cost-model truth: ledger joins all paths
        # a speculative request so the verify program pairs too (its
        # first call is a compile and rightly excluded)
        for _ in range(2):
            code, resp = post("/v2/models/lm/generate",
                              {"prompt": [7, 8, 9] * 4, "max_new_tokens": 12,
                               "speculation": {"enabled": True, "k": 2}})
            check(code == 200, f"speculative generate failed: {code} {resp}")
        preds = _get_json(f"{base}/v2/debug/predictions")
        lm = preds["models"]["lm"]
        entries = {e["key"]: e for e in lm["entries"]}
        for k in ("decode", "verify"):
            check(entries.get(k, {}).get("pairs", 0) >= 1,
                  f"no (predicted, measured) pair for {k}: {sorted(entries)}")
        check(any(k.startswith("prefill[") and e["pairs"] >= 1
                  for k, e in entries.items()),
              f"no prefill pair in the ledger: {sorted(entries)}")
        check(all(e["predicted_s"] > 0 for e in entries.values()),
              "ledger entry with non-positive prediction")

        # executor program: a tiny compiled model's train window must
        # join the strategy simulator's compile-time prediction in the
        # process-wide ledger
        from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                                  SGDOptimizer)
        from flexflow_tpu.obs.truth import GLOBAL_LEDGER

        mdl = FFModel(FFConfig(batch_size=8))
        t = mdl.create_tensor((8, 8))
        t = mdl.dense(t, 8, ActiMode.RELU)
        t = mdl.dense(t, 4)
        t = mdl.softmax(t)
        mdl.compile(optimizer=SGDOptimizer(lr=0.1),
                    loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
        import jax.numpy as jnp
        xs = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
        ys = jnp.zeros((8,), jnp.int32)
        rng = jax.random.key(0)
        mdl.executor.train_batch_repeated([xs], ys, rng, num_steps=2)  # compile
        mdl.executor.train_batch_repeated([xs], ys, rng, num_steps=2)  # measured
        ex_key = f"{mdl.executor._prog_ns}.train_step"
        ex_entry = next((e for e in GLOBAL_LEDGER.report()["entries"]
                         if e["key"] == ex_key), None)
        check(ex_entry is not None and ex_entry["pairs"] >= 1
              and ex_entry["predicted_s"] > 0,
              f"executor program {ex_key} has no (predicted, measured) pair")

        # forced miscalibration: a calibration entry deliberately scaled
        # to 1/4 of the measured op time must trip the drift alarm with
        # op-level blame naming the calibration table
        from flexflow_tpu.core.tensor import TensorSpec
        from flexflow_tpu.core.types import DataType, OpType
        from flexflow_tpu.obs.truth import PredictionLedger
        from flexflow_tpu.ops.base import get_op_def
        from flexflow_tpu.ops.linear import LinearParams
        from flexflow_tpu.search.calibration import (Calibration, cost_key,
                                                     measure_lowered_op,
                                                     op_ledger_key)
        from flexflow_tpu.search.cost_model import CostModel

        led = PredictionLedger()
        drift = []
        led.on_alarm = drift.append
        lp = LinearParams(out_dim=64, use_bias=True, dtype=DataType.FLOAT)
        lspecs = [TensorSpec((128, 64), DataType.FLOAT)]
        lkey = cost_key(OpType.LINEAR, lp, lspecs, 1)
        measured = measure_lowered_op(OpType.LINEAR, lp, lspecs, inner=8)
        if measured is None:
            # below the host's jitter floor: the alarm-path check still
            # runs against a nominal measured value
            measured = 1e-4
        cal = Calibration(device_kind="cpu", entries={lkey: measured / 4.0})
        cal.source = "calibration_data/opcosts_cpu.json (selfcheck: entry scaled /4)"
        cm = CostModel(calibration=cal, ledger=led)
        out_specs = get_op_def(OpType.LINEAR).infer_output_specs(lp, list(lspecs))
        cmets = cm.op_cost_metrics(OpType.LINEAR, lp, lspecs, out_specs, 1)
        check(cmets.prediction_id is not None,
              "CostMetrics not tagged with a prediction id")
        for _ in range(4):
            led.measure(op_ledger_key("cpu", OpType.LINEAR, lp, lspecs, 1),
                        measured)
        blame = drift[-1]["blame"] if drift else ""
        check(drift, "scaled calibration entry did not trip the drift alarm")
        check("LINEAR" in blame and "+300%" in blame
              and "calibration table entry" in blame
              and "opcosts_cpu.json" in blame,
              f"drift blame wrong: {blame!r}")

        # -------------- journeys: ingress joins traceparent, stitches
        # (ISSUE 20) a W3C traceparent sent at ingress must come back as
        # the stream's journey id, and GET /v2/debug/journey/{id} must
        # stitch a complete, single-root, parent-linked hop chain
        client_trace = "0af7651916cd43dd8448eb211c80319c"
        code, resp, hdrs = post(
            "/v2/models/lm/generate",
            {"prompt": [6, 5, 4, 3], "max_new_tokens": 6},
            headers={"traceparent": f"00-{client_trace}-b7ad6b7169203331-01"},
            return_headers=True,
        )
        check(code == 200 and resp.get("journey_id") == client_trace,
              f"ingress did not join the client traceparent: "
              f"{resp.get('journey_id')}")
        check(client_trace in (hdrs.get("traceparent") or ""),
              f"response traceparent missing the journey id: {hdrs}")
        jpayload = _get_json(f"{base}/v2/debug/journey/{client_trace}")
        j = jpayload["journey"]
        names = [s["name"] for s in j["spans"]]
        check(j["complete"] and j["n_roots"] == 1,
              f"HTTP journey did not stitch complete: {j['n_roots']} "
              f"root(s), {names}")
        for needed in ("ingress", "submit", "admit", "prefill", "finish"):
            check(needed in names, f"journey missing the {needed} hop: {names}")
        check({"http", "local"} <= set(j["lanes"]),
              f"journey lanes missing ingress or replica: {j['lanes']}")
        check(jpayload["chrome_trace"]["traceEvents"]
              and jpayload["otlp"]["resourceSpans"],
              "journey renderings empty")
        # tail exemplars: the latency windows must have retained journey
        # ids, and ?slow= must list only retained ids
        slow_tbl = _get_json(f"{base}/v2/debug/slow")["models"]
        check(any(rows for rows in slow_tbl.values()),
              "latency windows retained no journey exemplars")
        slow_ids = _get_json(f"{base}/v2/debug/journey?slow=p99")["journeys"]
        check(slow_ids, "?slow=p99 listed no exemplar journeys")
        check("flexflow_serving_journey_spans_total"
              in _get(f"{base}/metrics"),
              "/metrics missing the journey span counter")

        # ------------- journeys: forced failover stitches cross-replica
        # a two-replica fleet, r0 murdered mid-flight: every migrated
        # stream's journey must stitch complete WITH the failover hop,
        # crossing from the r0 lane into the survivor's — and span count
        # must equal the context's attempted-hop count (a dropped span
        # is a gap, not a diagnostic judgment call)
        from flexflow_tpu.generation import RecoveryPolicy
        from flexflow_tpu.obs import JourneyIndex
        from flexflow_tpu.runtime.faults import replica_kill
        from flexflow_tpu.serving.fleet import Fleet

        tiny = TransformerConfig(
            num_layers=1, hidden_size=16, num_heads=2, ff_size=32,
            seq_length=64, vocab_size=40, causal=True,
        )
        tiny_params = init_decoder_params(jax.random.key(1), tiny)

        def factory():
            return GenerationEngine(
                tiny_params, tiny, max_batch_slots=3, block_size=8,
            )

        fleet = Fleet(
            factory, 2,
            scheduler_kwargs={
                "recovery": RecoveryPolicy(max_restarts=1,
                                           sleep=lambda _s: None),
            },
        )
        plan = FaultPlan(seed=0)
        replica_kill(plan, "r0", every=1)
        with plan.active():
            fprompts = [[1, 2, 3], [4, 5, 6, 7], [9, 8, 7, 6], [1, 2, 3, 4]]
            fhandles = [
                fleet.submit(p, SamplingParams(max_new_tokens=8))
                for p in fprompts
            ]
            for _ in range(500):
                if all(h.done() for h in fhandles):
                    break
                fleet.step()
        check(all(h.done() for h in fhandles),
              "fleet failover leg did not finish")
        check(fleet.fleet_stats.snapshot()["failovers"] >= 1,
              "replica murder produced no failover")
        idx = JourneyIndex()
        for rec in fleet.journey_recorders():
            idx.add(rec)
        migrated = [h._request for h in fhandles
                    if h._request.journey.hops and any(
                        s.name == "failover" for rec in
                        fleet.journey_recorders() for s in
                        rec.spans(h._request.journey.journey_id))]
        check(migrated, "no journey recorded a failover hop")
        for req in migrated:
            fj = idx.get(req.journey.journey_id)
            check(fj is not None and fj["complete"],
                  f"failover journey did not stitch gap-free: "
                  f"{fj and fj['n_roots']}")
            check(fj["n_spans"] == req.journey.hops,
                  f"failover journey dropped spans: {fj['n_spans']} "
                  f"stitched vs {req.journey.hops} attempted")
            fnames = [s["name"] for s in fj["spans"]]
            check("failover" in fnames and "adopt" in fnames,
                  f"failover journey missing the handover hops: {fnames}")
            check(len(set(s["lane"] for s in fj["spans"])) >= 2,
                  f"failover journey never crossed lanes: {fnames}")
        # parent links are REAL: every non-root span's parent is another
        # span of the same journey (not just "some id present")
        for req in migrated:
            fj = idx.get(req.journey.journey_id)
            ids = {s["span_id"] for s in fj["spans"]}
            dangling = [s for s in fj["spans"]
                        if s["parent_id"] and s["parent_id"] not in ids]
            check(not dangling, f"dangling parent links: {dangling}")

        # ---------------- durable serving: kill + warm restart replays
        # in-process "process death": journal a stream mid-decode, then
        # abandon the scheduler without ENDing it — exactly the journal
        # a SIGKILL leaves behind (minus the torn tail, which chaoscheck
        # --durable covers with a real kill). A fresh attachment on the
        # same WAL directory must warm-restart with a NON-EMPTY replay
        # report and count it on the durable gauges. The abandoned
        # scheduler's blocks leak by design (its owner is "dead"); this
        # is the last leg, the engine is torn down right after.
        import shutil
        import tempfile

        from flexflow_tpu.generation import ContinuousBatchingScheduler
        from flexflow_tpu.serving.durable import Durability, DurabilityConfig

        wal_root = tempfile.mkdtemp(prefix="obsreport-durable-")
        try:
            dead = ContinuousBatchingScheduler(eng)
            Durability(dead, DurabilityConfig(wal_dir=wal_root))
            dead.submit([2, 7, 1, 8, 2, 8], SamplingParams(max_new_tokens=10))
            for _ in range(4):
                dead.step()
            sched2 = ContinuousBatchingScheduler(eng)
            dur2 = Durability(sched2, DurabilityConfig(wal_dir=wal_root))
            replay = dur2.warm_restart()
            check(replay["replayed_streams"] >= 1
                  and replay["replayed_tokens"] >= 1,
                  f"warm restart replayed nothing: {replay}")
            adopted = [e.req for e in sched2.journal.entries()]
            for _ in range(200):
                if all(r.handle.done() for r in adopted):
                    break
                if not sched2.step():
                    break
            check(adopted and all(r.handle.done() for r in adopted),
                  "adopted stream did not finish after the warm restart")
            rep = dur2.report()
            check(rep["counters"]["replayed_streams"] >= 1,
                  f"durable report did not count the replay: {rep['counters']}")
            # journeys survive process death: stitch ONLY from the new
            # scheduler's ring + the shared on-disk spool (the dead
            # scheduler's ring is intentionally NOT consulted — exactly
            # what a real SIGKILL leaves behind). The pre-crash spans
            # must join the post-restart chain gap-free, with the
            # warm_restart hop bridging them.
            jreq = adopted[0]
            check(jreq.journey.journey_id is not None,
                  "warm-restarted stream lost its journey identity")
            jidx = JourneyIndex().add(sched2.journeys)
            jidx.add_spool(dur2.journey_spool)
            wj = jidx.get(jreq.journey.journey_id)
            check(wj is not None and wj["complete"]
                  and wj["n_roots"] == 1,
                  f"warm-restart journey did not stitch gap-free: "
                  f"{wj and (wj['n_roots'], [s['name'] for s in wj['spans']])}")
            wnames = [s["name"] for s in wj["spans"]]
            check("submit" in wnames and "adopt" in wnames
                  and "warm_restart" in wnames,
                  f"warm-restart journey missing pre-crash or bridge "
                  f"hops: {wnames}")
            wids = {s["span_id"] for s in wj["spans"]}
            check(not [s for s in wj["spans"]
                       if s["parent_id"] and s["parent_id"] not in wids],
                  "warm-restart journey has dangling parent links")
            dur2.close()
        finally:
            shutil.rmtree(wal_root, ignore_errors=True)
    finally:
        srv.stop()

    if failures:
        print(f"SELFCHECK FAILED: {len(failures)} check(s)", file=sys.stderr)
        return 1
    print("OK: obsreport selfcheck — traces complete (queue/TTFT/TPOT), "
          "/metrics parses with non-empty histograms, quarantine + restart "
          "each captured a flight-recorder postmortem, cache telemetry "
          "conserves blocks, program registry populated and a forced "
          "retrace produced a correct blame string, SLO + readiness "
          "rationale live, truth ledger joined prefill/decode/verify + an "
          "executor program, a scaled calibration entry tripped the "
          "drift alarm with correct blame, the step-anatomy profiler "
          "reported a finite bubble ratio + overlap headroom with a "
          "successful forced two-lane capture, an abandoned durable "
          "journal warm-restarted with a non-empty replay report, and "
          "request journeys joined the client traceparent, stitched "
          "gap-free through a forced failover AND a warm restart "
          "(pre-crash spans recovered from the on-disk spool alone), "
          "with tail-latency exemplars linking to stitchable ids")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("command", nargs="?", default="summary",
                    choices=("summary", "cache", "slo", "predict", "anatomy",
                             "overload", "disagg", "constrained", "durable",
                             "journey", "slow"),
                    help="view: summary (default), cache (block "
                         "residency), slo (burn rates), predict "
                         "(cost-model truth: error table + drift alarms), "
                         "anatomy (step phases, device bubble, overlap "
                         "headroom), overload (limiter state, ladder "
                         "history, shed table, autoscale signal), disagg "
                         "(pool states, KV handoff outcomes + latency, "
                         "in-flight transfers), constrained (grammar-cache "
                         "economics, masked steps, dead-end quarantines), "
                         "durable (WAL watermark, replay totals, resume "
                         "index), journey [<id>] (one request's "
                         "cross-replica hop table, or the stitchable-id "
                         "listing; --slow p99 narrows to tail exemplars), "
                         "slow (tail-latency exemplar table)")
    ap.add_argument("ident", nargs="?", default=None,
                    help="with `journey`: the journey id to stitch")
    ap.add_argument("--url", default="", help="base URL of a running server")
    ap.add_argument("--request", type=int, default=None,
                    help="print one request's trace waterfall")
    ap.add_argument("--timeline-out", default="",
                    help="dump the flight recorder as chrome://tracing JSON")
    ap.add_argument("--capture", type=int, default=None,
                    help="with `anatomy`: arm a K-step detailed capture")
    ap.add_argument("--anatomy-out", default="",
                    help="with `anatomy`: dump the report + two-lane "
                         "capture timeline JSON to this file")
    ap.add_argument("--export", default="",
                    help="with `predict`: write the ledger snapshot as "
                         "a flexflow-ledger-export-v1 JSON document "
                         "(the sim cost-table calibration artifact)")
    ap.add_argument("--slow", default="",
                    help="with `journey` (no id): list only the "
                         "tail-latency exemplar journeys, e.g. --slow p99")
    ap.add_argument("--selfcheck", action="store_true",
                    help="in-process end-to-end observability check (CI)")
    args = ap.parse_args()

    if args.selfcheck:
        return selfcheck()
    if not args.url:
        ap.error("--url required (or --selfcheck)")
    base = args.url.rstrip("/")
    if args.request is not None:
        return show_request(base, args.request)
    if args.command == "journey":
        # --timeline-out here means the journey's chrome trace, not the
        # engine flight recorder
        return show_journey(base, journey_id=args.ident, slow=args.slow,
                            timeline_out=args.timeline_out)
    if args.command == "slow":
        return show_slow(base)
    if args.timeline_out:
        return dump_timeline(base, args.timeline_out)
    if args.command == "cache":
        return show_cache(base)
    if args.command == "slo":
        return show_slo(base)
    if args.command == "predict":
        if args.export:
            return export_predictions(base, args.export)
        return show_predictions(base)
    if args.command == "anatomy":
        return show_anatomy(base, capture=args.capture, out=args.anatomy_out)
    if args.command == "overload":
        return show_overload(base)
    if args.command == "disagg":
        return show_disagg(base)
    if args.command == "constrained":
        return show_constrained(base)
    if args.command == "durable":
        return show_durable(base)
    return summarize(base)


if __name__ == "__main__":
    raise SystemExit(main())
