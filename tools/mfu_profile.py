"""On-chip XLA profile: where does the non-MXU time go?

VERDICT r4 missing #3: the MFU levers were landed but never profiled on
the chip — "is flash attention actually MXU-bound at the chosen blocks?
what does the pipeline shard_map boundary cost?". This tool captures a
jax.profiler device trace of ONE traced training window (the same
program bench.py times), parses the xplane protobuf, and reports the
per-op device-time breakdown grouped into MXU (dot/conv fusions) vs
vector/elementwise vs copy/layout vs infeed/outfeed vs collective time.

Reference analog: the reference reads per-op measured costs out of its
simulator to find hotspots (src/runtime/simulator.cc:588-628); on TPU
the equivalent ground truth is the XLA device trace.

Usage:  python tools/mfu_profile.py [--searched] [--batch 32] [--large]
Output: MFU_PROFILE.json (durable, appended per run) + stdout summary.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from collections import defaultdict
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "MFU_PROFILE.json"


def parse_xspace(logdir: str) -> dict:
    """Per-op device-time breakdown via the xprof ``hlo_stats`` tool.

    The converter ships its own HLO categorization (convolution fusion,
    elementwise fusion, copy, all-reduce, ...), so the fractions below
    use the profiler's official buckets rather than name heuristics.
    """
    files = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    if not files:
        return {"error": f"no xplane.pb under {logdir}"}
    try:
        from xprof.convert import raw_to_tool_data as r2t

        data, _ctype = r2t.xspace_to_tool_data(sorted(files), "hlo_stats", {})
    except Exception as e:  # tool matrix varies across installs
        return {"error": f"hlo_stats conversion failed: {e!r}"}
    s = data.decode() if isinstance(data, (bytes, bytearray)) else data
    table = json.loads(s)
    cols = [c["id"] for c in table.get("cols", [])]
    try:
        i_cat = cols.index("category")
        i_name = cols.index("hlo_op_name")
        i_self = cols.index("total_self_time")
    except ValueError:
        return {"error": f"unexpected hlo_stats columns: {cols}"}

    per_op: dict = {}
    cats: dict = defaultdict(float)
    for row in table.get("rows", []):
        c = [cell.get("v") for cell in row["c"]]
        self_us = float(c[i_self] or 0.0)
        cats[str(c[i_cat])] += self_us
        key = (str(c[i_cat]), str(c[i_name]))
        per_op[key] = per_op.get(key, 0.0) + self_us
    total = sum(cats.values())
    if total <= 0:
        return {"error": "hlo_stats reported zero device time"}
    top = sorted(per_op.items(), key=lambda kv: -kv[1])[:25]
    return {
        "total_device_us": round(total, 1),
        "category_fractions": {k: round(v / total, 4)
                               for k, v in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"category": k[0], "op": k[1][:120],
                     "us": round(us, 1), "frac": round(us / total, 4)}
                    for k, us in top],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--searched", action="store_true")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--allow-cpu", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (smoke test; the hosted "
                         "sitecustomize force-selects the TPU otherwise)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        args.allow_cpu = True
    import numpy as np

    backend = jax.default_backend()
    if backend == "cpu" and not args.allow_cpu:
        print(json.dumps({"error": "no TPU; rerun with --allow-cpu for a smoke test"}))
        sys.exit(2)

    from bench import _bench_one, peak_flops_per_device, train_flops_per_token
    from flexflow_tpu import DataType, FFConfig, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerConfig, build_transformer

    cfg = TransformerConfig(
        num_layers=24 if args.large else 12,
        hidden_size=1024 if args.large else 768,
        num_heads=16 if args.large else 12,
        ff_size=4096 if args.large else 3072,
        seq_length=args.seq, dtype=DataType.BFLOAT16,
    )
    config = FFConfig(
        batch_size=args.batch, workers_per_node=len(jax.devices()), num_nodes=1,
        only_data_parallel=not args.searched,
        search_budget=5 if args.searched else 0,
    )
    model = build_transformer(config, cfg)
    model.compile(optimizer=SGDOptimizer(lr=0.01),
                  loss_type=LossType.MEAN_SQUARED_ERROR)
    ex = model.executor

    # measured step time with the SAME helper bench.py uses, so the
    # profile fractions can be read against the recorded MFU numbers.
    # The timed train_batch_repeated windows inside feed the truth
    # ledger's measure side; the executor registered the simulator's
    # predicted step time at compile — so the prediction-error block
    # below comes from the SHARED ledger, not a private comparison.
    step_s = _bench_one(ex, args.batch, cfg, args.iters)

    from flexflow_tpu.obs.truth import GLOBAL_LEDGER

    truth = next((e for e in GLOBAL_LEDGER.report()["entries"]
                  if e["key"] == f"{ex._prog_ns}.train_step"), None)
    prediction = None
    if truth is not None and truth["pairs"]:
        prediction = {
            "predicted_step_ms": round(truth["predicted_s"] * 1e3, 3),
            "measured_step_ms": round(truth["measured_p50_s"] * 1e3, 3),
            "rel_err": round(truth["rel_err_p50"], 3),
            "pairs": truth["pairs"],
            "provenance": truth["provenance"],
        }

    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(args.batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    y = jnp.asarray(rs.randn(args.batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    rng = jax.random.key(0)

    logdir = str(REPO / ".profile" / time.strftime("%Y%m%d_%H%M%S"))
    with jax.profiler.trace(logdir):
        mets = ex.train_batch_repeated([x], y, rng, num_steps=args.iters)
        float(mets["loss"])

    breakdown = parse_xspace(logdir)

    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", backend)
    peak = peak_flops_per_device(kind, backend) * len(devs)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(ex.params))
    fpt = train_flops_per_token(n_params, cfg.num_layers, cfg.seq_length, cfg.hidden_size)
    mfu = (args.batch * cfg.seq_length / step_s) * fpt / peak

    entry = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": backend, "device_kind": kind,
        "config": {"large": args.large, "batch": args.batch, "seq": args.seq,
                   "searched": args.searched},
        "step_ms": round(step_s * 1e3, 3),
        "mfu": round(mfu, 4),
        "prediction": prediction,
        "breakdown": breakdown,
    }
    data = {"what": "XLA device-trace breakdown of the timed training window",
            "runs": []}
    if OUT.exists():
        try:
            data = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            pass
    data["runs"].append(entry)
    tmp = OUT.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(data, indent=1) + "\n")
    os.replace(tmp, OUT)
    print(json.dumps({k: entry[k] for k in ("backend", "step_ms", "mfu", "prediction")} |
                     {"categories": breakdown.get("category_fractions"),
                      "top3": breakdown.get("top_ops", [])[:3]}))


if __name__ == "__main__":
    main()
