"""On-chip XLA profile: where does the non-MXU time go?

VERDICT r4 missing #3: the MFU levers were landed but never profiled on
the chip — "is flash attention actually MXU-bound at the chosen blocks?
what does the pipeline shard_map boundary cost?". This tool captures a
jax.profiler device trace of ONE traced training window (the same
program bench.py times), parses the xplane protobuf, and reports the
per-op device-time breakdown grouped into MXU (dot/conv fusions) vs
vector/elementwise vs copy/layout vs infeed/outfeed vs collective time.

Reference analog: the reference reads per-op measured costs out of its
simulator to find hotspots (src/runtime/simulator.cc:588-628); on TPU
the equivalent ground truth is the XLA device trace.

Usage:  python tools/mfu_profile.py [--searched] [--batch 32] [--large]
Output: MFU_PROFILE.json (durable, appended per run) + stdout summary.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from collections import defaultdict
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "MFU_PROFILE.json"


def _categorize(name: str) -> str:
    """Bucket an HLO/TPU op name into a hardware-unit category."""
    n = name.lower()
    if any(k in n for k in ("convolution", "dot", "einsum", "matmul")):
        return "mxu"
    if "fusion" in n:
        # XLA names loop fusions "fusion.N"; a fusion containing a dot is
        # usually named after it ("dot_fusion", handled above). Plain
        # fusions are vector-unit elementwise work.
        return "vpu_fusion"
    if any(k in n for k in ("copy", "transpose", "reshape", "bitcast", "layout")):
        return "copy_layout"
    if any(k in n for k in ("all-reduce", "all-gather", "reduce-scatter",
                            "collective", "permute", "send", "recv")):
        return "collective"
    if any(k in n for k in ("infeed", "outfeed", "host")):
        return "host_transfer"
    if any(k in n for k in ("reduce", "scatter", "gather", "sort", "select",
                            "iota", "rng", "compare", "broadcast")):
        return "vpu_other"
    return "other"


def parse_xspace(logdir: str) -> dict:
    """Aggregate device-side event durations from the captured xplane."""
    from tensorflow.core.profiler.protobuf import xplane_pb2  # type: ignore

    files = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    if not files:
        return {"error": f"no xplane.pb under {logdir}"}
    xspace = xplane_pb2.XSpace()
    xspace.ParseFromString(open(sorted(files)[-1], "rb").read())

    per_op: dict = defaultdict(float)
    device_planes = 0
    for plane in xspace.planes:
        # device planes are named like "/device:TPU:0"; skip host threads
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        device_planes += 1
        meta = {m.id: m.name for m in plane.event_metadata.values()}
        for line in plane.lines:
            # XLA op events live on the per-core "XLA Ops"/step lines
            for ev in line.events:
                name = meta.get(ev.metadata_id, "")
                if not name:
                    continue
                per_op[name] += ev.duration_ps / 1e12  # -> seconds
    if not per_op:
        return {"error": f"no device events ({device_planes} device planes)"}

    total = sum(per_op.values())
    cats: dict = defaultdict(float)
    for name, dur in per_op.items():
        cats[_categorize(name)] += dur
    top = sorted(per_op.items(), key=lambda kv: -kv[1])[:25]
    return {
        "device_planes": device_planes,
        "total_device_s": round(total, 6),
        "category_fractions": {k: round(v / total, 4)
                               for k, v in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"op": n[:120], "s": round(d, 6), "frac": round(d / total, 4)}
                    for n, d in top],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--searched", action="store_true")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--allow-cpu", action="store_true")
    args = ap.parse_args()

    import jax
    import numpy as np

    backend = jax.default_backend()
    if backend == "cpu" and not args.allow_cpu:
        print(json.dumps({"error": "no TPU; rerun with --allow-cpu for a smoke test"}))
        sys.exit(2)

    from bench import _bench_one, peak_flops_per_device, train_flops_per_token
    from flexflow_tpu import DataType, FFConfig, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerConfig, build_transformer

    cfg = TransformerConfig(
        num_layers=24 if args.large else 12,
        hidden_size=1024 if args.large else 768,
        num_heads=16 if args.large else 12,
        ff_size=4096 if args.large else 3072,
        seq_length=args.seq, dtype=DataType.BFLOAT16,
    )
    config = FFConfig(
        batch_size=args.batch, workers_per_node=len(jax.devices()), num_nodes=1,
        only_data_parallel=not args.searched,
        search_budget=5 if args.searched else 0,
    )
    model = build_transformer(config, cfg)
    model.compile(optimizer=SGDOptimizer(lr=0.01),
                  loss_type=LossType.MEAN_SQUARED_ERROR)
    ex = model.executor

    # measured step time with the SAME helper bench.py uses, so the
    # profile fractions can be read against the recorded MFU numbers
    step_s = _bench_one(ex, args.batch, cfg, args.iters)

    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(args.batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    y = jnp.asarray(rs.randn(args.batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    rng = jax.random.key(0)

    logdir = str(REPO / ".profile" / time.strftime("%Y%m%d_%H%M%S"))
    with jax.profiler.trace(logdir):
        mets = ex.train_batch_repeated([x], y, rng, num_steps=args.iters)
        float(mets["loss"])

    breakdown = parse_xspace(logdir)

    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", backend)
    peak = peak_flops_per_device(kind, backend) * len(devs)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(ex.params))
    fpt = train_flops_per_token(n_params, cfg.num_layers, cfg.seq_length, cfg.hidden_size)
    mfu = (args.batch * cfg.seq_length / step_s) * fpt / peak

    entry = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": backend, "device_kind": kind,
        "config": {"large": args.large, "batch": args.batch, "seq": args.seq,
                   "searched": args.searched},
        "step_ms": round(step_s * 1e3, 3),
        "mfu": round(mfu, 4),
        "breakdown": breakdown,
    }
    data = {"what": "XLA device-trace breakdown of the timed training window",
            "runs": []}
    if OUT.exists():
        try:
            data = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            pass
    data["runs"].append(entry)
    tmp = OUT.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(data, indent=1) + "\n")
    os.replace(tmp, OUT)
    print(json.dumps({k: entry[k] for k in ("backend", "step_ms", "mfu")} |
                     {"categories": breakdown.get("category_fractions"),
                      "top3": breakdown.get("top_ops", [])[:3]}))


if __name__ == "__main__":
    main()
